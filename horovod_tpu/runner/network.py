"""Socket primitives for the DCN control/data planes.

Reference analogues: horovod/common/gloo/http_store.cc (KV client),
horovod/runner/http/http_server.py:35-241 (rendezvous KV server), and the
point-to-point plumbing under runner/common/service/.  Framing is a 4-byte
big-endian length prefix; payloads are opaque bytes (wire.py messages or raw
numpy buffers).

Bulk transfers ride persistent per-peer duplex channels (`_PeerChannel`):
one long-lived sender thread + bounded queue per neighbor drains
scatter-gather `sendmsg` frames, and receives land in a reusable per-peer
scratch pool via `recv_into` — no per-step thread spawn, no bytes copies
on either direction (the reference keeps Gloo's persistent pair
connections alive the same way).
"""
from __future__ import annotations

import os
import queue
import selectors
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib import error as urlerror
from urllib import request as urlrequest

from ..common.logging import logger

_LEN = struct.Struct(">I")

# Grace for a sender lane to drain after its queue is poisoned at close;
# past it the socket is shut down under the thread (unblocking a sendmsg
# wedged on a dead peer) and a structured warning names the peer.
_CLOSE_JOIN_GRACE = 10.0


def _resilience_state():
    """The process ResilienceState, or None (zero-overhead off mode).
    Late import: resilience/ sits above the transport layer."""
    from ..resilience import active_state
    return active_state()


def _chaos_engine():
    from ..resilience import chaos
    return chaos.active()

# Depth of a channel's outbound queue.  Collective schedules keep at most
# one or two sends in flight per peer; the bound only exists so a runaway
# producer backpressures instead of buffering unbounded payload refs.
_SEND_QUEUE_DEPTH = 8


def send_msg(sock: socket.socket, payload: bytes) -> None:
    if len(payload) < (1 << 16):
        # Small control messages: one syscall, concat is cheap.
        sock.sendall(_LEN.pack(len(payload)) + payload)
    else:
        # Bulk payloads: never materialize header+payload (a full copy of
        # a multi-MB gradient buffer per send).
        sock.sendall(_LEN.pack(len(payload)))
        sock.sendall(payload)


def send_msg_gather(sock: socket.socket, view: memoryview) -> None:
    """Frame + send in one scatter-gather syscall (`sendmsg`): the header
    never gets concatenated onto a multi-MB payload, and the payload is
    consumed straight from the caller's buffer (numpy slice, bytes, ...).
    Handles partial sends — sendmsg may stop at any byte boundary."""
    n = view.nbytes
    hdr = _LEN.pack(n)
    sent = sock.sendmsg([hdr, view])
    while sent < 4 + n:
        if sent < 4:
            sent += sock.send(memoryview(hdr)[sent:])
        else:
            sent += sock.send(view[sent - 4:])


def _as_byte_view(payload) -> memoryview:
    """A flat uint8 memoryview over bytes/bytearray/memoryview/ndarray
    without copying (C-contiguous buffers only — all our payloads are)."""
    view = payload if isinstance(payload, memoryview) else memoryview(payload)
    if view.format != "B" or view.ndim != 1:
        view = view.cast("B")
    return view


def recv_exact(sock: socket.socket, n: int) -> bytearray:
    # Single preallocated buffer + recv_into: no per-chunk allocations,
    # no final join copy (numpy consumes the bytearray zero-copy via
    # frombuffer).
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)  # hvdlint: disable=unbounded-blocking-wait -- mesh-bootstrap rank-id exchange only; bounded upstream by the formation connect timeout
        if r == 0:
            raise ConnectionError("socket closed mid-message")
        got += r
    return buf


def recv_msg(sock: socket.socket) -> bytearray:
    (length,) = _LEN.unpack(recv_exact(sock, 4))
    return recv_exact(sock, length)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# Rendezvous KV store (HTTP, like the reference's RendezvousServer/HTTPStore)
# ---------------------------------------------------------------------------
class _KVHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence default stderr logging
        pass

    def _split(self) -> tuple[str, str]:
        parts = self.path.lstrip("/").split("/", 1)
        scope = parts[0] if parts else ""
        key = parts[1] if len(parts) > 1 else ""
        return scope, key

    def do_PUT(self):
        scope, key = self._split()
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        with self.server.kv_lock:
            self.server.kv.setdefault(scope, {})[key] = value
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        scope, key = self._split()
        with self.server.kv_lock:
            value = self.server.kv.get(scope, {}).get(key)
        if value is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
        else:
            self.send_response(200)
            self.send_header("Content-Length", str(len(value)))
            self.end_headers()
            self.wfile.write(value)

    def do_POST(self):
        """Atomic fetch-and-increment counter per (scope, key) — used for
        per-host slot claims (reference: the spark driver service's
        task-registration counter, spark/runner.py:47-426). A non-empty
        body names the logical claimant: re-presenting the same body
        returns the original index (idempotent under task retries)."""
        scope, key = self._split()
        length = int(self.headers.get("Content-Length", 0))
        claimant = self.rfile.read(length).decode()
        ckey = f"{scope}/{key}"
        with self.server.kv_lock:
            assigned = self.server.claims.setdefault(ckey, {})
            if claimant and claimant in assigned:
                n = assigned[claimant]
            else:
                n = self.server.counters.get(ckey, 0)
                self.server.counters[ckey] = n + 1
                if claimant:
                    assigned[claimant] = n
        body = str(n).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_DELETE(self):
        scope, key = self._split()
        with self.server.kv_lock:
            if key:
                self.server.kv.get(scope, {}).pop(key, None)
            else:
                self.server.kv.pop(scope, None)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class RendezvousServer:
    """Threaded HTTP KV store (reference: runner/http/http_server.py)."""

    def __init__(self, port: int = 0) -> None:
        self._httpd = ThreadingHTTPServer(("", port), _KVHandler)
        self._httpd.kv = {}
        self._httpd.counters = {}
        self._httpd.claims = {}
        self._httpd.kv_lock = threading.Lock()
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="hvd-rendezvous")
        self._thread.start()
        return self.port

    def put(self, scope: str, key: str, value: bytes) -> None:
        with self._httpd.kv_lock:
            self._httpd.kv.setdefault(scope, {})[key] = value

    def get(self, scope: str, key: str) -> bytes | None:
        with self._httpd.kv_lock:
            return self._httpd.kv.get(scope, {}).get(key)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            # Reap the serve thread (hvdlife HVD701): shutdown() above
            # is its wakeup, so the join is prompt.
            self._thread.join(timeout=5.0)
            self._thread = None


class RendezvousClient:
    """HTTP KV client with blocking get (reference: gloo/http_store.cc wait)."""

    def __init__(self, addr: str, port: int, timeout: float = 30.0) -> None:
        self._base = f"http://{addr}:{port}"
        self.timeout = timeout

    def put(self, scope: str, key: str, value: bytes) -> None:
        req = urlrequest.Request(f"{self._base}/{scope}/{key}", data=value,
                                 method="PUT")
        with urlrequest.urlopen(req, timeout=self.timeout):
            pass

    def claim(self, scope: str, key: str, task_key: str = "") -> int:
        """Atomic fetch-and-increment of the (scope, key) counter.
        A non-empty ``task_key`` makes the claim idempotent: retries with
        the same key get the originally assigned index back."""
        req = urlrequest.Request(f"{self._base}/{scope}/{key}",
                                 data=task_key.encode(), method="POST")
        with urlrequest.urlopen(req, timeout=self.timeout) as resp:
            return int(resp.read())

    def get(self, scope: str, key: str) -> bytes | None:
        try:
            req = urlrequest.Request(f"{self._base}/{scope}/{key}",
                                     method="GET")
            with urlrequest.urlopen(req, timeout=self.timeout) as resp:
                return resp.read()
        except urlerror.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def delete(self, scope: str, key: str = "") -> None:
        """Delete one key (or a whole scope when ``key`` is empty) —
        statesync consumes its join/ready/donation marks so a later
        epoch's watcher never replays a resolved event."""
        req = urlrequest.Request(f"{self._base}/{scope}/{key}",
                                 method="DELETE")
        with urlrequest.urlopen(req, timeout=self.timeout):
            pass

    def wait(self, scope: str, key: str,
             timeout: float | None = None) -> bytes:
        deadline = time.monotonic() + (timeout or self.timeout)
        while True:
            value = self.get(scope, key)
            if value is not None:
                return value
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"Rendezvous key {scope}/{key} not available after "
                    f"{timeout or self.timeout}s")
            time.sleep(0.01)


# ---------------------------------------------------------------------------
# Persistent duplex channel to one peer
# ---------------------------------------------------------------------------
class _PeerChannel:
    """One long-lived socket to a peer with a persistent sender lane.

    Sends enqueue onto a bounded queue drained by ONE daemon thread that
    lives as long as the channel (spawned lazily on the first async send,
    so control-plane meshes that never bulk-send cost zero threads).
    Receives go through `recv_begin` (framing) + `recv_exact_into`
    (straight into the caller's buffer) or the reusable scratch pool —
    the zero-copy replacement for the old alloc-per-message recv.
    """

    __slots__ = ("sock", "peer", "_queue", "_sender", "_error",
                 "_scratch", "_hdr", "_on_sent", "_res")

    def __init__(self, sock: socket.socket, peer: int, on_sent,
                 resilience=None) -> None:
        self.sock = sock
        self.peer = peer
        self._queue: queue.Queue | None = None
        self._sender: threading.Thread | None = None
        self._error: BaseException | None = None
        self._scratch = bytearray(0)
        self._hdr = bytearray(4)
        self._on_sent = on_sent    # bytes counter callback (mesh-level)
        # Resilience (HOROVOD_FAULT_TOLERANCE): a non-None state installs
        # a short socket timeout so every blocking wait on this channel
        # becomes a deadline-bounded poll loop — between slices the state
        # raises RanksFailedError on peer death or per-op deadline expiry
        # instead of blocking forever.  None = the exact pre-resilience
        # syscall pattern (zero-overhead off mode).
        self._res = resilience
        if resilience is not None:
            self.sock.settimeout(resilience.poll_interval)

    def _dead(self, exc: BaseException) -> BaseException:
        """Latch a failure on the channel: later sends/recvs raise it
        immediately instead of re-waiting out a deadline on a stream
        that is already known broken (and possibly desynced)."""
        if self._error is None:
            self._error = exc
        return exc

    # -- sending ----------------------------------------------------------
    def send_async(self, payload) -> None:
        """Enqueue one framed message on the persistent sender lane.  The
        caller must not mutate `payload`'s buffer until the channel is
        flushed (collectives flush before returning results)."""
        if self._error is not None:
            raise self._error
        if self._sender is None:
            self._queue = queue.Queue(maxsize=_SEND_QUEUE_DEPTH)
            self._sender = threading.Thread(
                target=self._send_loop, daemon=True,
                name=f"hvd-send-{self.peer}")
            self._sender.start()
        self._queue.put(_as_byte_view(payload))

    def send_sync(self, payload) -> int:
        """Blocking framed send; routed through the sender lane when one
        exists so sync and async frames never interleave on the wire.
        Returns the bytes to account (0 when the lane already counted
        them through its completion callback)."""
        view = _as_byte_view(payload)
        if self._sender is not None:
            self.send_async(view)
            self.flush()
            return 0
        self._send_gather(view)
        return view.nbytes

    def _send_gather(self, view: memoryview) -> None:
        """Framed scatter-gather send, deadline-bounded when resilience
        is on: a sendmsg stalled on a wedged peer's zero-window socket
        polls in slices and raises RanksFailedError at the op deadline
        instead of blocking the lane forever (progress resets the clock —
        the deadline bounds silence, not transfer time)."""
        if self._res is None:
            send_msg_gather(self.sock, view)
            return
        n = view.nbytes
        hdr = _LEN.pack(n)
        sent = 0
        start = time.monotonic()
        while sent < 4 + n:
            try:
                if sent == 0:
                    sent += self.sock.sendmsg([hdr, view])
                elif sent < 4:
                    sent += self.sock.send(memoryview(hdr)[sent:])
                else:
                    sent += self.sock.send(view[sent - 4:])
            except TimeoutError:
                self._res.check(self.peer, time.monotonic() - start,
                                "send")
                continue
            except (ConnectionResetError, BrokenPipeError) as e:
                raise self._dead(self._res.peer_connection_lost(
                    self.peer, "send", str(e))) from e
            start = time.monotonic()

    def _send_loop(self) -> None:
        while True:
            view = self._queue.get()
            try:
                if view is None:
                    return
                self._send_gather(view)
                self._on_sent(view.nbytes)
            except BaseException as e:  # noqa: BLE001 - surfaced to caller
                if self._error is None:
                    self._error = e
                # Wake a peer blocked in recv on the dead channel.
                try:
                    self.sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            finally:
                self._queue.task_done()

    def flush(self) -> None:
        """Block until every queued frame has been handed to the kernel
        (the pre-channel code's per-step join gave the same guarantee).
        Bounded indirectly: under fault tolerance every send the lane
        drains is itself deadline-bounded, so the join below terminates
        within one op deadline of a peer failure."""
        if self._queue is not None:
            self._queue.join()  # hvdlint: disable=unbounded-blocking-wait -- each queued send is deadline-bounded (see _send_gather); the lane always reaches task_done
        if self._error is not None:
            raise self._error

    # -- receiving --------------------------------------------------------
    def recv_exact_into(self, view: memoryview) -> None:
        got, n = 0, view.nbytes
        if self._res is None:   # zero-overhead off mode: original loop
            while got < n:
                r = self.sock.recv_into(view[got:], n - got)  # hvdlint: disable=unbounded-blocking-wait -- intentional pre-resilience behavior when HOROVOD_FAULT_TOLERANCE is off
                if r == 0:
                    raise ConnectionError("socket closed mid-message")
                got += r
            return
        start = time.monotonic()
        while got < n:
            try:
                r = self.sock.recv_into(view[got:], n - got)  # hvdlint: disable=unbounded-blocking-wait -- bounded by the socket poll timeout installed at channel construction; the except arm enforces the op deadline
            except TimeoutError:
                # check() raises RanksFailedError on peer death or op-
                # deadline expiry; otherwise keep polling.
                self._res.check(self.peer, time.monotonic() - start,
                                "recv")
                continue
            except (ConnectionResetError, BrokenPipeError) as e:
                raise self._dead(self._res.peer_connection_lost(
                    self.peer, "recv", str(e))) from e
            if r == 0:
                raise self._dead(self._res.peer_connection_lost(
                    self.peer, "recv", "socket closed mid-message"))
            got += r
            start = time.monotonic()   # progress: deadline bounds silence

    def recv_begin(self) -> int:
        """Read one frame header; the next `nbytes` on the wire are the
        payload, consumed by the caller via recv_exact_into/scratch."""
        if self._error is not None:
            raise self._error
        hv = memoryview(self._hdr)
        self.recv_exact_into(hv)
        return _LEN.unpack(self._hdr)[0]

    def scratch(self, nbytes: int) -> memoryview:
        """A reusable receive buffer of at least `nbytes` (grown
        geometrically, never shrunk): steady-state receives allocate
        nothing.  Contents are valid until the next scratch recv on this
        channel — consume before receiving again."""
        if len(self._scratch) < nbytes:
            self._scratch = bytearray(max(nbytes, 2 * len(self._scratch)))
        return memoryview(self._scratch)[:nbytes]

    def close(self) -> None:
        """Shutdown-leak fix (mirrors the Timeline writer fix): poison
        the queue FIRST, then join.  The old order (bounded join with no
        poison-first guarantee) could time out silently and leak the
        sender thread plus its bounded queue — every payload it
        referenced stayed pinned for the process lifetime.  A sender
        wedged in sendmsg on a dead peer is woken by shutting the socket
        down under it; if it STILL survives, a structured warning names
        the peer instead of hiding the leak."""
        if self._sender is not None:
            try:
                self.flush()
            except BaseException:  # noqa: BLE001 - already torn down
                pass
            self._queue.put(None)                      # poison first
            self._sender.join(timeout=_CLOSE_JOIN_GRACE)
            if self._sender.is_alive():
                # Unblock a send wedged on a dead/zero-window peer, then
                # give the lane one more chance to observe the poison.
                try:
                    self.sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                self._sender.join(timeout=1.0)
            if self._sender.is_alive():
                logger.warning(
                    "peer-channel close: sender thread for peer %d "
                    "survived poison + socket shutdown (queue depth %d); "
                    "leaking it as daemon", self.peer,
                    self._queue.qsize() if self._queue is not None else -1)
            self._sender = None
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Full-mesh point-to-point connections between ranks
# ---------------------------------------------------------------------------
class PeerMesh:
    """Connect every pair of ranks once; expose send/recv by peer rank.

    Bootstraps peer addresses through the rendezvous KV store, then lower
    rank listens / higher rank connects (the reference's gloo
    connectFullMesh does the same through its HTTPStore).
    """

    def __init__(self, rank: int, size: int, kv: RendezvousClient,
                 scope: str = "mesh", timeout: float = 30.0,
                 resilience=None) -> None:
        self.rank = rank
        self.size = size
        self.scope = scope
        self._socks: dict[int, socket.socket] = {}
        self._channels: dict[int, _PeerChannel] = {}
        self._lock = threading.Lock()
        # Resilience (HOROVOD_FAULT_TOLERANCE) + chaos (HOROVOD_CHAOS):
        # captured at formation.  Both None in the default off mode, so
        # the per-call cost is one attribute test; tests may inject a
        # private ResilienceState (the process default is rank-global).
        self._resilience = resilience if resilience is not None \
            else _resilience_state()
        self._chaos = _chaos_engine()
        # Payload byte counters (framing excluded): the observability the
        # compression subsystem's bandwidth claims are asserted against
        # (tests/test_compress.py) and PERFORMANCE.md numbers come from.
        self.bytes_sent = 0
        self.bytes_received = 0
        # Telemetry (HOROVOD_METRICS): per-peer wire counters + send-queue
        # depth, labelled by mesh scope so control/data/stream meshes stay
        # distinguishable.  Null registry when off — per-call cost is one
        # attribute test on _tm_on.
        from ..telemetry import metrics as _tm_metrics
        self._tm = _tm_metrics()
        self._tm_on = self._tm.enabled
        self._tm_sent: dict[int, object] = {}
        self._tm_recv: dict[int, object] = {}
        self._tm_qdepth = self._tm.histogram(
            "horovod_tcp_send_queue_depth",
            "Outbound frames queued on a peer's persistent sender lane "
            "at enqueue time", labels={"mesh": scope}) if self._tm_on \
            else None
        if size == 1:
            return

        listener = socket.socket()
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("", 0))
        listener.listen(size)
        port = listener.getsockname()[1]
        host = self._advertised_host()
        kv.put(scope, f"addr:{rank}", f"{host}:{port}".encode())

        expected_inbound = size - 1 - rank   # peers with higher rank dial in
        accepted: dict[int, socket.socket] = {}

        def _tune(sock: socket.socket) -> None:
            # Bulk data plane: large kernel buffers keep the ring's
            # concurrent 1-8 MB chunk exchanges streaming instead of
            # ping-ponging on default (~200 KB) windows.
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
                try:
                    sock.setsockopt(socket.SOL_SOCKET, opt, 4 << 20)
                except OSError:
                    pass

        def _accept():
            for _ in range(expected_inbound):
                conn, _ = listener.accept()
                peer = int.from_bytes(recv_exact(conn, 4), "big")
                _tune(conn)
                accepted[peer] = conn

        acceptor = threading.Thread(target=_accept, daemon=True,
                                    name="hvd-mesh-accept")
        acceptor.start()

        for peer in range(rank):   # dial every lower-ranked peer
            raw = kv.wait(scope, f"addr:{peer}", timeout).decode()
            peer_host, peer_port = raw.rsplit(":", 1)
            deadline = time.monotonic() + timeout
            while True:
                try:
                    sock = socket.create_connection(
                        (peer_host, int(peer_port)), timeout=timeout)
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)
            _tune(sock)
            sock.sendall(self.rank.to_bytes(4, "big"))
            self._socks[peer] = sock

        acceptor.join(timeout)
        if len(accepted) != expected_inbound:
            raise TimeoutError(
                f"rank {rank}: only {len(accepted)}/{expected_inbound} "
                f"inbound peers connected")
        self._socks.update(accepted)
        listener.close()
        for peer, sock in self._socks.items():
            self._channels[peer] = _PeerChannel(sock, peer,
                                                self._count_sent,
                                                resilience=self._resilience)

    @staticmethod
    def _advertised_host() -> str:
        """Address peers dial: HOROVOD_GLOO_IFACE pins the NIC when set
        (reference: gloo_context.cc reads the same variable to select the
        Gloo transport device); otherwise the hostname's address."""
        iface = os.environ.get("HOROVOD_GLOO_IFACE")
        if iface:
            from .driver_service import candidate_addresses
            return candidate_addresses(iface)[0]
        return socket.gethostbyname(socket.gethostname())

    def _count_sent(self, nbytes: int) -> None:
        with self._lock:   # sender lanes run concurrently with the ring
            self.bytes_sent += nbytes

    def _count_received(self, nbytes: int) -> None:
        with self._lock:
            self.bytes_received += nbytes

    # -- per-peer telemetry counters (lazily created per peer) ----------
    def _tm_peer(self, table: dict, name: str, peer: int):
        c = table.get(peer)
        if c is None:
            c = self._tm.counter(
                name, "Payload bytes on the wire by peer rank "
                "(framing excluded)",
                labels={"mesh": self.scope, "peer": str(peer)})
            table[peer] = c
        return c

    def _tm_count_sent(self, peer: int, nbytes: int) -> None:
        self._tm_peer(self._tm_sent,
                      "horovod_tcp_bytes_sent_total", peer).inc(nbytes)

    def _tm_count_recv(self, peer: int, nbytes: int) -> None:
        self._tm_peer(self._tm_recv,
                      "horovod_tcp_bytes_received_total", peer).inc(nbytes)

    def send(self, peer: int, payload: bytes) -> None:
        if self._chaos is not None:
            act = self._chaos.on_send(self.scope, peer)
            if act == "drop":
                return
            if act == "dup":
                self._count_sent(self._channels[peer].send_sync(payload))
        self._count_sent(self._channels[peer].send_sync(payload))
        if self._tm_on:
            self._tm_count_sent(peer, len(payload))

    def send_async(self, peer: int, payload) -> None:
        """Enqueue a framed message on the peer's persistent sender lane
        (counted by the lane on completion).  Zero-copy: the payload
        buffer must stay unmutated until `flush()`."""
        ch = self._channels[peer]
        if self._chaos is not None:
            act = self._chaos.on_send(self.scope, peer)
            if act == "drop":
                return
            if act == "dup":
                ch.send_async(payload)
        ch.send_async(payload)
        if self._tm_on:
            # Depth AFTER the put: what's now waiting on the lane.
            if ch._queue is not None:
                self._tm_qdepth.observe(ch._queue.qsize())
            self._tm_count_sent(peer, _as_byte_view(payload).nbytes)

    def recv(self, peer: int) -> bytearray:
        """Receive one framed message, allocated fresh.  Routed through
        the peer channel so the wait is deadline-bounded under fault
        tolerance (the channel falls back to the original blocking loop
        when resilience is off)."""
        ch = self._channels.get(peer)
        if ch is None:   # size-1 mesh / pre-channel peer: legacy path
            data = recv_msg(self._socks[peer])
        else:
            n = ch.recv_begin()
            data = bytearray(n)
            if n:
                ch.recv_exact_into(memoryview(data))
        self._count_received(len(data))
        if self._tm_on:
            self._tm_count_recv(peer, len(data))
        return data

    # -- zero-copy receive surface (bulk data plane) --------------------
    def recv_begin(self, peer: int) -> int:
        """Read one frame header from `peer`; returns the payload length
        the caller must now consume via recv_raw_into/scratch."""
        n = self._channels[peer].recv_begin()
        self._count_received(n)
        if self._tm_on:
            self._tm_count_recv(peer, n)
        return n

    def recv_raw_into(self, peer: int, view: memoryview) -> None:
        """Receive exactly len(view) payload bytes straight into the
        caller's buffer (no staging copy)."""
        self._channels[peer].recv_exact_into(view)

    def scratch(self, peer: int, nbytes: int) -> memoryview:
        """The peer channel's reusable receive scratch (see
        _PeerChannel.scratch for the validity contract)."""
        return self._channels[peer].scratch(nbytes)

    def recv_in_arrival_order(self, peers):
        """Yield (peer, message) for one framed message from each of
        `peers`, draining whichever peer's bytes arrive first (selectors)
        instead of fixed rank order — one slow rank no longer serializes
        the drain behind the sockets after it."""
        remaining = set(peers)
        if not remaining:
            return
        res = self._resilience
        with selectors.DefaultSelector() as sel:
            for p in remaining:
                sel.register(self._socks[p], selectors.EVENT_READ, p)
            start = time.monotonic()
            while remaining:
                events = sel.select(None if res is None
                                    else res.poll_interval)
                if not events:
                    if res is not None:
                        # Deadline-bounded drain: a silent slice checks
                        # the liveness table and the op deadline,
                        # attributed to the still-missing peers.
                        res.check(min(remaining),
                                  time.monotonic() - start, "gather")
                    continue
                for key, _ in events:
                    peer = key.data
                    sel.unregister(key.fileobj)
                    remaining.discard(peer)
                    yield peer, self.recv(peer)  # hvdlint: disable=unbounded-blocking-wait -- bounded inside the peer channel (socket poll timeout + op deadline)
                start = time.monotonic()

    def flush(self, peer: int | None = None) -> None:
        """Wait until queued sends (to `peer`, or everyone) reached the
        kernel.  Collectives flush before returning so callers may mutate
        result buffers; direct-fd paths (native ring) flush first so raw
        writes never interleave with queued frames."""
        channels = [self._channels[peer]] if peer is not None \
            else self._channels.values()
        for ch in channels:
            ch.flush()

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()
        for sock in self._socks.values():   # size-1 meshes have no channels
            try:
                sock.close()
            except OSError:
                pass
        self._channels.clear()
        self._socks.clear()
