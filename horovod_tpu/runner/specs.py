"""Protocol spec for the rendezvous-failover election (hvdmc).

Co-located with ``controlplane.py``: the durable replicated rendezvous
elects its leader through the write-ahead log itself — the primary
renews ``lease`` records, standbys tail the log and, on lease lapse,
append a ``leader`` record at ``epoch + 1``; the FIRST leader record at
a given epoch wins and everyone else demotes.  A primary whose lease
lapsed (SIGSTOP / partition — the ``coordpause:`` chaos shape) must
re-read the log before accepting another write: a higher-epoch leader
record fences it out.  Clients hold a multi-endpoint seed list and
converge on whichever replica currently leads via connection-refused
rotation and 409 leader hints.

Checked properties (``analysis/hvdmc/machines.py`` FailoverModel):

- **two-leaders** — no two replicas ever serve as primary at once
  (per-epoch leadership is unique by first-leader-record arbitration);
- **committed-write-lost** — no write acked to a client is dropped by
  a later promotion's replay (epoch fencing at the log);
- **clients-converge** — from every reachable state the client can
  still reach a state where all its writes are acked (AG EF).

The seeded ``accept-stale-lease`` mutation (``--mutate``) lets a
resumed primary skip the log re-verification — the checker answers
with a two-leaders (and lost-write) counterexample trace.
"""
from __future__ import annotations

from ..analysis.hvdmc.spec import ProtocolSpec, Transition, Verb

__all__ = ["failover_spec"]

_CP = "runner.controlplane.ControlPlane"
_NET = "runner.network"


def failover_spec() -> ProtocolSpec:
    transitions = (
        Transition("pri.renew", "primary", "leading", "leading",
                   "kv:LEASE",
                   binds=(f"{_CP}._renew_lease",),
                   requires_calls=("append",),
                   doc="lease record every third of "
                       "HOROVOD_RENDEZVOUS_LEASE_MS"),
        Transition("pri.commit", "primary", "leading", "leading",
                   "kv:PUT", guard="lease-valid",
                   binds=(f"{_CP}.check_write", f"{_NET}._kv_apply"),
                   requires_calls=("record", "apply_record"),
                   doc="WAL-commit + apply one mutating KV verb; acked "
                       "only after the group-commit fsync"),
        Transition("pri.pause", "primary", "leading", "paused",
                   "fault:pause",
                   doc="SIGSTOP / GC pause / partition: the lease "
                       "keeps ticking while the process does not"),
        Transition("pri.die", "primary", "leading", "dead",
                   "fault:kill"),
        Transition("pri.resume-fenced", "primary", "paused", "fenced",
                   "internal:reverify", guard="epoch-fence",
                   binds=(f"{_CP}._reverify_lease",),
                   requires_calls=("replay_state", "_demote"),
                   doc="a higher-epoch leader record in the log fences "
                       "the resumed primary out: demote, 409 + hint"),
        Transition("pri.resume-reclaim", "primary", "paused", "leading",
                   "internal:reverify", guard="epoch-fence",
                   binds=(f"{_CP}._reverify_lease",),
                   requires_calls=("replay_state",),
                   doc="lease lapsed but uncontested: self-succeed "
                       "under a fresh epoch so racing candidates are "
                       "fenced"),
        Transition("sb.tail", "standby", "tailing", "tailing",
                   "kv:LEASE",
                   binds=(f"{_CP}._tail_once",
                          "runner.controlplane.Replicator._run"),
                   requires_calls=("urlopen",),
                   doc="log-tail replication doubles as lease "
                       "observation"),
        Transition("sb.lapse", "standby", "tailing", "candidate",
                   "internal:lease-lapse", guard="lapse-after-silence",
                   binds=(f"{_CP}._lease_loop",),
                   doc="no leader sign for ~2x the lease (staggered by "
                       "replica id)"),
        Transition("sb.promote", "standby", "candidate", "promoted",
                   "kv:LEADER", guard="first-leader-wins",
                   binds=(f"{_CP}._try_promote",),
                   requires_calls=("replay_state", "_election_winner"),
                   doc="append leader@epoch+1, re-read the log, first "
                       "record at the new epoch wins; replay the WAL "
                       "into the serving state"),
        Transition("sb.lose", "standby", "candidate", "tailing",
                   "internal:lost-election",
                   binds=(f"{_CP}._election_winner",),
                   doc="a peer's leader record landed first: adopt its "
                       "epoch, keep tailing"),
        Transition("cli.write", "client", "connected", "connected",
                   "kv:PUT",
                   binds=(f"{_NET}.RendezvousClient._call",),
                   doc="idempotent verbs retry across endpoints inside "
                       "one deadline; bare claims fail fast"),
        Transition("cli.failover", "client", "connected", "retrying",
                   "internal:endpoint-failover",
                   binds=(f"{_NET}.RendezvousClient._failover",),
                   doc="connection refused / 409: rotate to the next "
                       "seed or follow the X-Hvd-Leader hint"),
        Transition("cli.converge", "client", "retrying", "connected",
                   "internal:leader-found",
                   binds=(f"{_NET}.RendezvousClient.find_primary",),
                   requires_calls=("urlopen",)),
    )
    return ProtocolSpec(
        name="rendezvous-failover",
        doc="durable replicated rendezvous leader election "
            "(docs/controlplane.md)",
        roles=("primary", "standby", "client"),
        states={"primary": ("leading", "paused", "fenced", "dead"),
                "standby": ("tailing", "candidate", "promoted"),
                "client": ("connected", "retrying")},
        verbs=(
            Verb("LEASE", "kv", "lease",
                 doc="leader liveness record, wall-clock expiry in the "
                     "value"),
            Verb("LEADER", "kv", "leader",
                 doc="election record: epoch-fenced, first-at-epoch "
                     "wins"),
            Verb("PUT", "kv", "put", doc="client KV set, WAL-committed"),
            Verb("CLAIM", "kv", "claim",
                 doc="fetch-and-increment; the record carries the "
                     "assigned index so replay is order-free"),
            Verb("DELETE", "kv", "delete"),
        ),
        transitions=transitions,
        anchor_modules=("runner.controlplane",),
        properties={
            "two-leaders":
                "no two replicas serve as primary at once — per-epoch "
                "leadership is unique (first leader record arbitrates)",
            "committed-write-lost":
                "every write acked to a client survives any later "
                "promotion's WAL replay (epoch fencing)",
            "clients-converge":
                "from every reachable state the client can still "
                "reach all-writes-acked (AG EF resolution)",
        })
