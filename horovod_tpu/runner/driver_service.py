"""NIC discovery / reachability probing between the launcher ("driver")
and worker hosts.

Reference: horovod/runner/driver/driver_service.py +
runner/task/task_service.py — before launching, the reference spawns a
probe on every worker host that attempts to connect back to each of the
driver's interface addresses; the launcher then advertises only addresses
every host can actually reach (multi-NIC clusters routinely have
interfaces that exist but don't route, e.g. docker0 or an IB fabric the
head node isn't on).

Pieces:
- :func:`candidate_addresses` — the driver's IPv4 addresses (psutil),
  routable NICs first, loopback last;
- :class:`ProbeServer` — one listening socket; workers dial each
  candidate ``(addr, port)`` and get a banner back;
- :func:`probe` / ``python -m horovod_tpu.runner.driver_service`` — the
  worker-side client, printing the reachable subset as JSON;
- :func:`discover_common_interfaces` — runs the probe on every remote
  host through a caller-supplied exec function (ssh in production, a
  local shell in tests) and intersects the results.
"""
from __future__ import annotations

import json
import socket
import sys
import threading
from typing import Callable, Sequence

_BANNER = b"hvd-tpu-probe\n"


def candidate_addresses(interface: str | None = None) -> list[str]:
    """This host's IPv4 addresses; ``interface`` restricts to one NIC.
    Routable addresses come first, loopback last (it is only reachable
    from local workers)."""
    import psutil

    addrs: list[str] = []
    loopback: list[str] = []
    for nic, entries in psutil.net_if_addrs().items():
        if interface is not None and nic != interface:
            continue
        for entry in entries:
            if entry.family != socket.AF_INET:
                continue
            (loopback if entry.address.startswith("127.")
             else addrs).append(entry.address)
    if interface is not None and not (addrs or loopback):
        raise ValueError(f"no IPv4 address on interface {interface!r}")
    return addrs + loopback


class ProbeServer:
    """Accepts probe connections on every interface and replies with a
    banner so clients can distinguish "something listens here" from an
    unrelated service."""

    def __init__(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("", 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvd-probe")
        self._thread.start()

    def _loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                conn.sendall(_BANNER)
            except OSError:
                pass
            finally:
                conn.close()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        # Reap the accept loop (hvdlife HVD701): the socket close is
        # its wakeup.
        self._thread.join(timeout=5.0)


def probe(addresses: Sequence[str], port: int,
          timeout: float = 2.0) -> list[str]:
    """Worker side: which of ``addresses`` accept a connection on
    ``port`` and answer with the probe banner."""
    from .network import recv_exact

    reachable = []
    for addr in addresses:
        try:
            with socket.create_connection((addr, port),
                                          timeout=timeout) as s:
                s.settimeout(timeout)
                # recv_exact: a single recv may legally return a partial
                # banner (TCP segmentation on tunneled links).
                if recv_exact(s, len(_BANNER)) == _BANNER:
                    reachable.append(addr)
        except OSError:
            continue
    return reachable


def discover_common_interfaces(
        hostnames: Sequence[str],
        remote_exec: Callable[[str, list[str]], str],
        interface: str | None = None,
        timeout: float = 10.0) -> list[str]:
    """Driver side: start a probe server, run the probe client on every
    host through ``remote_exec(hostname, argv) -> stdout``, and return
    the addresses every host reached (driver NIC order preserved).

    ``remote_exec`` is ssh in production (see runner.hosts.ssh_argv);
    tests substitute a local shell."""
    addresses = candidate_addresses(interface)
    server = ProbeServer()
    try:
        common = list(addresses)
        argv = [sys.executable, "-m",
                "horovod_tpu.runner.driver_service",
                str(server.port), ",".join(addresses), str(timeout)]
        for hostname in hostnames:
            out = remote_exec(hostname, argv)
            line = out.strip().splitlines()[-1] if out.strip() else "[]"
            reachable = set(json.loads(line))
            common = [a for a in common if a in reachable]
        if not common:
            raise RuntimeError(
                f"no common reachable interface: driver addresses "
                f"{addresses} are not all reachable from {hostnames}")
        return common
    finally:
        server.close()


def main() -> int:
    port = int(sys.argv[1])
    addresses = [a for a in sys.argv[2].split(",") if a]
    timeout = float(sys.argv[3]) if len(sys.argv) > 3 else 2.0
    print(json.dumps(probe(addresses, port, timeout=timeout)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
