"""What ``horovodrun --fleet`` (HOROVOD_FLEET=1) actually starts.

The CLI flag only exports the env var (runner/launch.py args_to_env);
these hooks are the runtime wiring the flag promises (docs/fleet.md):

- **training side** (:meth:`Trainer.fit <horovod_tpu.training.Trainer
  .fit>` calls :func:`attach_trainer`): rank 0 hosts the
  FleetController and the WeightPublisher (single writer of the
  ``fleet.pub`` scope); every rank's fit loop drives a throttled
  train-gauge publish (world size + straggler lag) so the controller
  sees the trainer's load without a direct channel;
- **serving side** (:meth:`ReplicaExecutor.serve_loop
  <horovod_tpu.serving.replica.ReplicaExecutor.serve_loop>` calls
  :func:`attach_replica`): every replica attaches a WeightPuller
  against the coordinator KV, and the front end publishes the serve
  gauges (queue depth + per-interval shed rate) the rebalancing
  policy thresholds.

Everything rides the rendezvous KV the job already has
(HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT) — no new endpoints, no new
threads beyond the three hvdsan-rooted fleet loops.
"""
from __future__ import annotations

import time

from ..common import config
from ..common.logging import logger
from .controller import FleetController, publish_gauge
from .deploy import WeightPublisher

__all__ = ["FleetRuntime", "attach_replica", "attach_trainer"]


def _fleet_kv():
    from ..statesync.service import _kv_client

    return _kv_client()


class FleetRuntime:
    """The per-process bundle ``--fleet`` starts, owning exactly what
    it created: the optional controller + publisher (training rank 0)
    and this world's throttled gauge publish.  ``close()`` stops them
    in reverse dependency order."""

    def __init__(self, kv, world: str, *, controller=None,
                 publisher=None) -> None:
        self.kv = kv
        self.world = world
        self.controller = controller
        self.publisher = publisher
        # Gauges refresh at half the controller interval: fresh enough
        # that a policy tick never reasons from a whole-interval-old
        # world, without a KV put per step.
        self._gauge_interval_s = max(
            config.FLEET_INTERVAL_S.get() / 2.0, 0.05)
        self._last_gauge = 0.0

    def publish_gauge(self, size_fn, fields_fn=None) -> None:
        """Throttled gauge publish.  ``size_fn`` / ``fields_fn`` are
        callables invoked only when the interval elapsed, keeping the
        per-step cost of the hook to one clock read."""
        now = time.monotonic()
        if now - self._last_gauge < self._gauge_interval_s:
            return
        self._last_gauge = now
        fields = fields_fn() if fields_fn is not None else {}
        try:
            publish_gauge(self.kv, self.world, int(size_fn()), **fields)
        except (TimeoutError, OSError) as exc:
            logger.debug("fleet: %s gauge publish failed: %s",
                         self.world, exc)

    def close(self) -> None:
        if self.publisher is not None:
            self.publisher.close()
        if self.controller is not None:
            self.controller.stop()


def attach_trainer(trainer):
    """Wire the training side of ``--fleet``: rank 0 hosts the
    FleetController + WeightPublisher and the publisher is attached to
    the trainer's publish hook; every rank gets a FleetRuntime whose
    gauge hook the fit loop drives.  Returns None when fleet mode is
    off or the coordinator KV is not configured."""
    if not config.FLEET.get():
        return None
    from .. import core

    try:
        kv = _fleet_kv()
    except RuntimeError as exc:
        logger.warning("fleet: HOROVOD_FLEET set but no coordinator "
                       "KV: %s", exc)
        return None
    controller = publisher = None
    if core.global_state().rank == 0:
        controller = FleetController(kv)
        controller.start()
        publisher = WeightPublisher(kv)
        publisher.start()
        trainer.attach_fleet_publisher(publisher)
        logger.info("fleet: controller + weight publisher started on "
                    "training rank 0")
    return FleetRuntime(kv, "train", controller=controller,
                        publisher=publisher)


def trainer_gauges() -> dict:
    """The trainer-side gauge fields the policy consumes: the
    coordinator straggler-lag gauge when telemetry is live, 0.0
    otherwise (the policy then simply never proposes serve->train on
    straggler evidence)."""
    from ..telemetry import metrics

    reg = metrics()
    lag = 0.0
    if reg.enabled:
        try:
            lag = float(reg.gauge("horovod_controller_straggler_lag_ms",
                                  labels={"stat": "mean"}).value)
        except Exception:  # noqa: BLE001 - absent gauge reads as 0
            lag = 0.0
    return {"straggler_lag_ms": lag}


def attach_replica(executor):
    """Wire the serving side of ``--fleet``: the replica pulls
    published weights (boundary swap stays front-scheduled), and the
    front end's step path publishes the serve gauges.  Returns the
    FleetRuntime (None when fleet mode is off or the KV is not
    configured); the puller itself is owned by the executor
    (``ReplicaExecutor.close`` joins it)."""
    if not config.FLEET.get():
        return None
    try:
        kv = _fleet_kv()
    except RuntimeError as exc:
        logger.warning("fleet: HOROVOD_FLEET set but no coordinator "
                       "KV: %s", exc)
        return None
    executor.attach_fleet(kv)
    runtime = FleetRuntime(kv, "serve")
    totals = {"shed": 0.0, "offered": 0.0}

    def _fields(ex=executor) -> dict:
        # Per-interval shed rate over the admission outcome counters
        # (the statesync/autoscale.py registry_source computation,
        # scoped to this executor); queue depth is outstanding work —
        # queued + in-flight — like the acceptance battery publishes.
        out = ex.admission.outcome_totals()
        shed = float(out.get("shed", 0.0)) + float(out.get("expired",
                                                           0.0))
        offered = shed + float(out.get("served", 0.0))
        d_shed = shed - totals["shed"]
        d_offered = offered - totals["offered"]
        totals["shed"], totals["offered"] = shed, offered
        return {
            "shed_rate": (d_shed / d_offered) if d_offered > 0 else 0.0,
            "queue_depth": float(ex.queue.depth()
                                 + ex.batcher.inflight_count()),
        }

    executor._fleet_gauge = lambda ex: runtime.publish_gauge(
        lambda: ex.size, _fields)
    logger.info("fleet: serving replica attached (puller + front "
                "gauges)")
    return runtime
