"""Pure-logic fleet rebalancing policy: which world is starved, and by
how many ranks (docs/fleet.md).

The shape is the autoscale policy's (statesync/autoscale.py) — streak
counters with hysteresis, a cooldown after every decision — but the
actuator differs: autoscale changes ONE world's target size against an
external pool, while the fleet policy moves ranks BETWEEN two live
worlds sharing a fixed host pool.  That makes oscillation the dominant
failure mode (a move that fixes serving starves training, which a naive
policy immediately reverses), so the cooldown here is its own knob
(``HOROVOD_FLEET_COOLDOWN_ROUNDS``) layered on top of hysteresis and
both floors (``HOROVOD_FLEET_MIN_TRAIN`` / ``_MIN_SERVE``) are hard:
the policy never proposes a move it would have to take back on the
next tick just to restore a floor.

No I/O, no threads, no clocks: the controller (controller.py) feeds
gauges in and executes decisions out, so every branch here is unit-
testable in microseconds (tests/test_fleet.py).
"""
from __future__ import annotations

import dataclasses

from ..common import config

__all__ = ["FleetDecision", "FleetPolicy"]

TRAIN_TO_SERVE = "train->serve"
SERVE_TO_TRAIN = "serve->train"


@dataclasses.dataclass(frozen=True)
class FleetDecision:
    """One rebalance decision: move ``n`` ranks in ``direction``."""
    direction: str                 # TRAIN_TO_SERVE | SERVE_TO_TRAIN
    n: int
    reason: str


class FleetPolicy:
    """Hysteresis + cooldown rebalancer over the two worlds' gauges.

    ``observe`` is called once per controller interval with the current
    world sizes and the freshest gauges; it returns a
    :class:`FleetDecision` or None.  A condition must hold for
    ``hysteresis_rounds`` consecutive intervals before a decision
    fires, and after every decision the policy stays silent for
    ``cooldown_rounds`` intervals — so the number of migrations in any
    window of R rounds is bounded by ``R / (hysteresis + cooldown)``
    regardless of how adversarial the gauge sequence is (the
    oscillation bound asserted in tests/test_fleet.py)."""

    def __init__(self, *, min_train: int | None = None,
                 min_serve: int | None = None,
                 up_shed_rate: float | None = None,
                 up_queue_fraction: float | None = None,
                 idle_queue_fraction: float | None = None,
                 train_lag_ms: float | None = None,
                 hysteresis_rounds: int | None = None,
                 cooldown_rounds: int | None = None,
                 queue_depth_limit: int | None = None) -> None:
        self.min_train = config.FLEET_MIN_TRAIN.get() \
            if min_train is None else int(min_train)
        self.min_serve = config.FLEET_MIN_SERVE.get() \
            if min_serve is None else int(min_serve)
        self.up_shed_rate = config.FLEET_UP_SHED_RATE.get() \
            if up_shed_rate is None else float(up_shed_rate)
        self.up_queue_fraction = config.FLEET_UP_QUEUE_FRACTION.get() \
            if up_queue_fraction is None else float(up_queue_fraction)
        self.idle_queue_fraction = config.FLEET_IDLE_QUEUE_FRACTION.get() \
            if idle_queue_fraction is None else float(idle_queue_fraction)
        self.train_lag_ms = config.FLEET_TRAIN_LAG_MS.get() \
            if train_lag_ms is None else float(train_lag_ms)
        self.hysteresis_rounds = config.FLEET_HYSTERESIS_ROUNDS.get() \
            if hysteresis_rounds is None else int(hysteresis_rounds)
        self.cooldown_rounds = config.FLEET_COOLDOWN_ROUNDS.get() \
            if cooldown_rounds is None else int(cooldown_rounds)
        self.queue_depth_limit = config.SERVE_QUEUE_DEPTH.get() \
            if queue_depth_limit is None else int(queue_depth_limit)
        self._serve_hot = 0            # consecutive overloaded intervals
        self._train_hot = 0            # consecutive trainer-starved ones
        self._cooldown = 0
        self.decisions = 0

    def _reset_streaks(self) -> None:
        self._serve_hot = 0
        self._train_hot = 0
        self._cooldown = self.cooldown_rounds

    def observe(self, train_size: int, serve_size: int, *,
                shed_rate: float = 0.0, queue_depth: float = 0.0,
                straggler_lag_ms: float = 0.0) -> FleetDecision | None:
        """One policy tick.  Gauges: serving shed rate over the last
        interval, serving queue depth, trainer straggler lag."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        queue_frac = float(queue_depth) / max(self.queue_depth_limit, 1)
        serve_hot = (shed_rate > self.up_shed_rate
                     or queue_frac > self.up_queue_fraction)
        serve_idle = (shed_rate <= 0.0
                      and queue_frac < self.idle_queue_fraction)
        train_hot = serve_idle and straggler_lag_ms > self.train_lag_ms
        self._serve_hot = self._serve_hot + 1 if serve_hot else 0
        self._train_hot = self._train_hot + 1 if train_hot else 0
        if self._serve_hot >= self.hysteresis_rounds \
                and train_size - 1 >= self.min_train:
            self._reset_streaks()
            self.decisions += 1
            return FleetDecision(
                TRAIN_TO_SERVE, 1,
                f"serving overloaded (shed={shed_rate:.3f} "
                f"queue={queue_frac:.2f})")
        if self._train_hot >= self.hysteresis_rounds \
                and serve_size - 1 >= self.min_serve:
            self._reset_streaks()
            self.decisions += 1
            return FleetDecision(
                SERVE_TO_TRAIN, 1,
                f"trainer starved (lag={straggler_lag_ms:.1f}ms, "
                f"serving idle)")
        return None
