"""horovod_tpu.fleet — unified train+serve fleet controller: one shared
host pool arbitrated between a training world and a serving world
(traffic-driven rank rebalancing + continuous weight deployment).

See docs/fleet.md for the architecture and the migration state
machine; fleet/specs.py is the hvdmc protocol spec the implementation
is conformance-bound to.
"""
from __future__ import annotations

from .controller import (CTL_SCOPE, GAUGE_SCOPE, JOURNAL_SCOPE,
                         FleetController, mark_joined, poll_depart,
                         publish_gauge, read_gauge)
from .deploy import PUB_SCOPE, WeightPublisher, WeightPuller
from .policy import (SERVE_TO_TRAIN, TRAIN_TO_SERVE, FleetDecision,
                     FleetPolicy)
from .specs import fleet_spec
from .wiring import FleetRuntime, attach_replica, attach_trainer

__all__ = [
    "CTL_SCOPE", "GAUGE_SCOPE", "JOURNAL_SCOPE", "PUB_SCOPE",
    "SERVE_TO_TRAIN", "TRAIN_TO_SERVE", "FleetController",
    "FleetDecision", "FleetPolicy", "FleetRuntime", "WeightPublisher",
    "WeightPuller", "attach_replica", "attach_trainer", "fleet_spec",
    "mark_joined", "poll_depart", "publish_gauge", "read_gauge",
]
