"""Continuous weight deployment: trainer-side publisher and serving-
side puller over the coordinator KV (docs/fleet.md).

The path is the statesync fast-donation mold (service.py
``_fast_donate`` / ``fetch_donation``): the trainer's rank 0 flattens
its param tree (snapshot.py leaf order), chunks it into independently
addressed KV shards under the ``fleet.pub`` scope, and commits the
version by writing the ``meta:{v}`` record (digest + nbytes + shard
count) and only then bumping ``head``.  Pullers poll ``head`` on a
timeout-bounded wait, fetch the shards, digest-verify the reassembly
against the meta record, and hand the verified image to the replica's
staging callback — the replica swaps it in at a BatchPlan boundary
(serving/replica.py), never here.  Verify-before-stage is the safety
property the fleet hvdmc spec model-checks (fleet/specs.py); the
seeded ``swap-before-verify`` mutation is exactly this ordering
dropped.

Both threads are owned: ``close()`` sets the wakeup event and joins
with a timeout (hvdlife HVD701/HVD705 posture, registered in hvdsan
ownership.THREAD_ROOTS).
"""
from __future__ import annotations

import json
import threading

from ..common import config
from ..common.logging import logger
from ..statesync.snapshot import flatten_state, state_digest
from ..telemetry.flight import recorder

__all__ = ["PUB_SCOPE", "WeightPublisher", "WeightPuller"]

PUB_SCOPE = "fleet.pub"


def _meta_key(version: int) -> str:
    return f"meta:{version}"


def _shard_key(version: int, i: int) -> str:
    return f"shard:{version}.{i}"


class WeightPublisher(threading.Thread):
    """Trainer-side snapshot publisher (rank 0 only).

    ``maybe_publish(step, tree)`` runs on the training thread: it
    flattens the tree (the only device sync, paid once per
    ``HOROVOD_FLEET_PUBLISH_STEPS``) and enqueues the image; the
    publisher thread does the digest, the shard puts, the meta commit,
    the head bump and old-version GC off the step critical path."""

    def __init__(self, kv, *, publish_steps: int | None = None,
                 chunk_bytes: int | None = None,
                 keep: int | None = None) -> None:
        super().__init__(daemon=True, name="hvd-fleet-publisher")
        self.kv = kv
        self.publish_steps = config.FLEET_PUBLISH_STEPS.get() \
            if publish_steps is None else int(publish_steps)
        self.chunk_bytes = max(config.FLEET_CHUNK_BYTES.get()
                               if chunk_bytes is None else int(chunk_bytes),
                               1)
        self.keep = max(config.FLEET_PUBLISH_KEEP.get()
                        if keep is None else int(keep), 2)
        # Pending hand-off to the publisher thread: AT MOST ONE
        # (version, step, image-bytes) entry.  Only the newest image
        # matters to pullers, and an unbounded queue would accumulate
        # full flattened param images on the trainer host whenever KV
        # commits run slower than the publish cadence.
        self._work: list = []
        self._inflight = False         # publisher thread mid-commit
        self._wake = threading.Event()
        self._halt = threading.Event()
        self._lock = threading.Lock()
        self.version = 0               # last version handed to the thread
        self.published = 0             # versions fully committed to KV
        self.coalesced = 0             # superseded pending images dropped
        self._shards: dict[int, int] = {}   # version -> shard count

    # -- training-thread side -------------------------------------------
    def maybe_publish(self, step: int, tree) -> int | None:
        """Publish ``tree`` if ``step`` is on the publish cadence;
        returns the assigned version (or None when off-cadence)."""
        if self.publish_steps <= 0 or step % self.publish_steps != 0:
            return None
        image = bytes(flatten_state(tree))
        with self._lock:
            self.version += 1
            version = self.version
            if self._work:
                # Coalesce: replace the not-yet-committed pending image
                # instead of queueing behind it — the superseded version
                # is simply never published (pullers only want newest),
                # and host memory stays bounded at one pending image.
                self._work[-1] = (version, step, image)
                self.coalesced += 1
            else:
                self._work.append((version, step, image))
        self._wake.set()
        return version

    # -- publisher thread -----------------------------------------------
    def run(self) -> None:
        while not self._halt.is_set():
            self._wake.wait(timeout=1.0)
            self._wake.clear()
            while True:
                with self._lock:
                    if not self._work:
                        break
                    version, step, image = self._work.pop(0)
                    self._inflight = True
                try:
                    self._publish(version, step, image)
                finally:
                    with self._lock:
                        self._inflight = False

    def _publish(self, version: int, step: int, image: bytes) -> None:
        digest = state_digest(image)
        shards = -(-len(image) // self.chunk_bytes) or 1
        records = [(PUB_SCOPE, _shard_key(version, i),
                    image[i * self.chunk_bytes:(i + 1) * self.chunk_bytes])
                   for i in range(shards)]
        self.kv.put_many(records)
        # Shards first, meta second, head last: a puller that sees the
        # head bump is guaranteed a complete, addressable snapshot.
        meta = {"version": version, "step": step, "digest": digest,
                "nbytes": len(image), "shards": shards}
        self.kv.put(PUB_SCOPE, _meta_key(version),
                    json.dumps(meta).encode())
        self.kv.put(PUB_SCOPE, "head", str(version).encode())
        self.published += 1
        self._shards[version] = shards
        rec = recorder()
        if rec.enabled:
            rec.record("fleet-publish", name=f"v{version}",
                       detail=f"step={step} nbytes={len(image)} "
                              f"shards={shards}")
        stale = sorted(self._shards)[:-self.keep]
        for old in stale:
            n = self._shards.pop(old)
            self.kv.delete(PUB_SCOPE, _meta_key(old))
            for i in range(n):
                self.kv.delete(PUB_SCOPE, _shard_key(old, i))

    def drain(self, timeout: float = 10.0) -> None:
        """Block (bounded) until the pending image (if any) is
        committed — the battery's determinism hook, not a production
        path."""
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._work and not self._inflight:
                    return
            self._wake.set()
            time.sleep(0.02)

    def close(self) -> None:
        self._halt.set()
        self._wake.set()
        if self.is_alive() and self is not threading.current_thread():
            self.join(timeout=10.0)


class WeightPuller(threading.Thread):
    """Serving-side snapshot puller: polls ``head``, fetches + digest-
    verifies new versions, and stages them through ``stage(version,
    image, meta)`` — the replica swaps at a front-scheduled plan
    boundary.  A stage callback returning ``False`` refuses the
    version (staging window full); the puller keeps its watermark and
    offers the then-current head again on the next poll."""

    def __init__(self, kv, stage, *, interval_s: float = 0.5) -> None:
        super().__init__(daemon=True, name="hvd-fleet-puller")
        self.kv = kv
        self._stage = stage
        self.interval_s = float(interval_s)
        self._halt = threading.Event()
        self.seen = 0                  # newest version staged
        self.pulled = 0
        self.verify_failures = 0

    def run(self) -> None:
        while not self._halt.wait(timeout=self.interval_s):
            try:
                self.poll_once()
            except (TimeoutError, OSError) as exc:
                logger.debug("fleet: puller poll failed: %s", exc)

    def poll_once(self) -> int | None:
        """One head poll; returns the version staged (None if no news).
        Split out of run() so the battery and units can drive the pull
        synchronously."""
        raw = self.kv.get(PUB_SCOPE, "head")
        if raw is None:
            return None
        head = int(raw)
        if head <= self.seen:
            return None
        meta_raw = self.kv.get(PUB_SCOPE, _meta_key(head))
        if meta_raw is None:
            return None                # head raced the GC window: retry
        meta = json.loads(meta_raw)
        parts = []
        for i in range(int(meta["shards"])):
            shard = self.kv.get(PUB_SCOPE, _shard_key(head, i))
            if shard is None:
                return None            # torn fetch: retry next poll
            parts.append(shard)
        image = b"".join(parts)
        # THE ordering the fleet spec model-checks: digest-verify BEFORE
        # the image is staged anywhere a swap can reach it.
        if len(image) != int(meta["nbytes"]) \
                or state_digest(image) != int(meta["digest"]):
            self.verify_failures += 1
            logger.warning(
                "fleet: snapshot v%d failed digest verify "
                "(%d bytes); discarding", head, len(image))
            return None
        # The stage callback may refuse (the replica's staging window
        # is full): leave the watermark untouched so the next poll
        # retries — a refused version is delayed, never dropped.
        if self._stage(head, image, meta) is False:
            return None
        self.seen = head
        self.pulled += 1
        rec = recorder()
        if rec.enabled:
            rec.record("fleet-pull", name=f"v{head}",
                       detail=f"nbytes={len(image)} verified")
        return head

    def close(self) -> None:
        self._halt.set()
        if self.is_alive() and self is not threading.current_thread():
            self.join(timeout=self.interval_s + 10.0)
