"""Protocol spec for the train<->serve fleet handoff (hvdmc DSL).

One spec, two intertwined machines over the coordinator KV
(docs/fleet.md):

- **migration** — the controller journals a move (``mig:``), publishes
  the ``depart:`` directive, the donor rank departs at its statesync
  boundary and joins the other world, and its ``joined:`` mark closes
  the journal record (done) — or a controller failover / deadline
  closes it (aborted);
- **deployment** — the publisher commits ``shard:`` records, then the
  ``meta:`` stamp, then the ``head`` bump (strictly in that order), and
  every replica pulls, digest-verifies, stages, and swaps at a
  BatchPlan boundary.

The safety property the checker earns its keep on is
``swap-verified``: a replica must never swap in an image that did not
reproduce the published meta digest.  The seeded ``swap-before-verify``
mutation (machines.FleetModel) drops exactly that guard, and the
shard-corrupt fault then drives a corrupt image into the serving path
— the counterexample trace tier-1 asserts byte-for-byte.

HVD506 binds every verb and transition to the fleet implementation
(controller.py / deploy.py / the statesync depart hook / the replica
boundary swap), so protocol drift in either direction fails the tree.
"""
from __future__ import annotations

from ..analysis.hvdmc.spec import ProtocolSpec, Transition, Verb

__all__ = ["fleet_spec"]

_CTL = "fleet.controller"
_DEP = "fleet.deploy"
_FC = f"{_CTL}.FleetController"
_PUB = f"{_DEP}.WeightPublisher"
_PUL = f"{_DEP}.WeightPuller"


def fleet_spec() -> ProtocolSpec:
    verbs = (
        Verb("GAUGE", "kv", "fleet.gauges",
             doc="each world front's load gauge (size, shed rate, "
                 "queue depth, straggler lag)"),
        Verb("JOURNAL", "kv", "mig:",
             doc="epoch-stamped migration journal record "
                 "(planned -> departing -> done | aborting -> "
                 "aborted)"),
        Verb("DEPART", "kv", "depart:",
             doc="the directive a donor rank consumes at its statesync "
                 "step boundary"),
        Verb("JOINED", "kv", "joined:",
             doc="the mover's arrival mark, written only after the "
                 "destination world's join completed"),
        Verb("SHARD", "kv", "shard:",
             doc="one chunk of a published param snapshot"),
        Verb("META", "kv", "meta:",
             doc="a version's commit stamp: digest + nbytes + shard "
                 "count"),
        Verb("HEAD", "kv", "head",
             doc="the newest fully committed snapshot version"),
    )
    transitions = (
        # -- controller --------------------------------------------------
        Transition("ctl.observe", "controller", "idle", "idle",
                   "kv:GAUGE",
                   binds=(f"{_FC}.tick", f"{_CTL}.publish_gauge",
                          f"{_CTL}.read_gauge"),
                   doc="poll both worlds' gauges, feed the policy"),
        Transition("ctl.plan", "controller", "idle", "planning",
                   "kv:JOURNAL", guard="hysteresis-held",
                   requires_calls=("claim",), observe="fleet-migrate",
                   binds=(f"{_FC}.begin_migration",),
                   doc="journal first: every KV state a failover can "
                       "observe is unambiguous about the directive"),
        Transition("ctl.direct", "controller", "planning", "migrating",
                   "kv:DEPART",
                   binds=(f"{_FC}.begin_migration",)),
        Transition("ctl.complete", "controller", "migrating", "idle",
                   "kv:JOINED", requires_calls=("delete",),
                   binds=(f"{_FC}._advance",),
                   doc="joined mark observed: journal done, directive "
                       "withdrawn"),
        Transition("ctl.abort-planned", "controller", "planning",
                   "idle", "internal:failover-abort",
                   guard="directive-never-published",
                   binds=(f"{_FC}.recover",),
                   doc="failover adopted a planned record whose "
                       "directive was never written: abort is safe, no "
                       "rank can be acting on it"),
        Transition("ctl.abort-deadline", "controller", "migrating",
                   "aborting", "internal:deadline-exceeded",
                   binds=(f"{_FC}._advance",),
                   doc="deadline passed: withdraw the directive, but "
                       "the donor may have ALREADY consumed it — hold "
                       "an abort-grace window rather than declaring "
                       "aborted while the rank is mid-flight"),
        Transition("ctl.reconcile-late-join", "controller", "aborting",
                   "idle", "kv:JOINED", requires_calls=("delete",),
                   binds=(f"{_FC}._advance",),
                   doc="the mover's joined mark lands inside the abort "
                       "grace: the rank really migrated, so the journal "
                       "reconciles to done (an 'aborted' record here "
                       "would let the policy double-shrink the donor)"),
        Transition("ctl.abort-final", "controller", "aborting", "idle",
                   "internal:abort-grace-exceeded",
                   binds=(f"{_FC}._advance",),
                   doc="no joined mark through the grace window either: "
                       "a wedged mover never blocks the controller "
                       "forever"),
        Transition("ctl.resume", "controller", "migrating", "migrating",
                   "internal:epoch-claimed", guard="journal-resumable",
                   requires_calls=("claim",),
                   binds=(f"{_FC}.recover",),
                   doc="failover adopted a departing record: the mover "
                       "may be mid-join, keep waiting for its mark"),
        # -- mover (the donor rank changing worlds) ----------------------
        Transition("mov.directive", "mover", "training", "boundary",
                   "kv:DEPART",
                   binds=(f"{_CTL}.poll_depart",),
                   doc="the donor rank's boundary poll consumed its "
                       "directive"),
        Transition("mov.depart", "mover", "boundary", "joining",
                   "boundary", guard="depart-at-boundary",
                   observe="fleet-depart",
                   binds=("statesync.service.StateSyncService"
                          ".request_depart",),
                   doc="orderly departure through the preemption-grace "
                       "boundary: survivors shrink proactively, no "
                       "RanksFailedError"),
        Transition("mov.join", "mover", "joining", "serving",
                   "internal:join-complete",
                   requires_calls=("join_world",),
                   binds=("serving.replica.join_serving_world",),
                   doc="peer-streamed state into the destination world "
                       "(the statesync-grow machine runs here)"),
        Transition("mov.arrive", "mover", "serving", "serving",
                   "kv:JOINED", requires_calls=("put",),
                   observe="fleet-join",
                   binds=(f"{_CTL}.mark_joined",)),
        # -- publisher (trainer rank 0) ----------------------------------
        Transition("pub.shards", "publisher", "run", "run", "kv:SHARD",
                   requires_calls=("put_many",),
                   binds=(f"{_PUB}._publish",)),
        Transition("pub.meta", "publisher", "run", "run", "kv:META",
                   guard="meta-after-shards",
                   binds=(f"{_PUB}._publish",)),
        Transition("pub.head", "publisher", "run", "run", "kv:HEAD",
                   observe="fleet-publish",
                   binds=(f"{_PUB}._publish",),
                   doc="head bumps LAST: a puller that sees it is "
                       "guaranteed a complete, addressable snapshot"),
        # -- replica (serving-side puller + boundary swap) ---------------
        Transition("rep.poll", "replica", "serving", "serving",
                   "kv:HEAD",
                   binds=(f"{_PUL}.poll_once",)),
        Transition("rep.fetch", "replica", "serving", "fetched",
                   "kv:SHARD",
                   binds=(f"{_PUL}.poll_once",)),
        Transition("rep.verify-stage", "replica", "fetched", "staged",
                   "internal:digest-verifies",
                   guard="verify-before-stage", observe="fleet-pull",
                   binds=(f"{_PUL}.poll_once",),
                   doc="digest-verify BEFORE the image is staged "
                       "anywhere a swap can reach it — the guard the "
                       "swap-before-verify mutation drops"),
        Transition("rep.verify-reject", "replica", "fetched", "serving",
                   "internal:digest-mismatch",
                   guard="verify-before-stage",
                   binds=(f"{_PUL}.poll_once",)),
        Transition("rep.swap", "replica", "staged", "serving",
                   "boundary", guard="swap-at-plan-boundary",
                   observe="fleet-swap",
                   binds=("serving.replica.ReplicaExecutor"
                          "._apply_plan",),
                   doc="the broadcast BatchPlan IS the schedule: every "
                       "rank swaps at the same step, zero dropped "
                       "admitted requests"),
        # -- injected faults ---------------------------------------------
        Transition("net.failover", "net", "env", "env",
                   "fault:controller-failover",
                   doc="the controller host dies mid-migration; a "
                       "successor claims the next epoch and adopts the "
                       "journal"),
        Transition("net.shard-corrupt", "net", "env", "env",
                   "fault:shard-corrupt"),
    )
    return ProtocolSpec(
        name="fleet-handoff",
        doc="train<->serve rank migration + continuous weight "
            "deployment (docs/fleet.md)",
        roles=("controller", "mover", "publisher", "replica", "net"),
        states={"controller": ("idle", "planning", "migrating",
                               "aborting"),
                "mover": ("training", "boundary", "joining", "serving"),
                "publisher": ("run",),
                "replica": ("serving", "fetched", "staged"),
                "net": ("env",)},
        verbs=verbs,
        transitions=transitions,
        anchor_modules=(_CTL, _DEP),
        properties={
            "swap-verified":
                "a replica never swaps in an image that did not "
                "reproduce the published meta record's digest",
            "journal-resolves":
                "every journaled migration reaches done or aborted, "
                "even across a controller failover",
            "resolution-reachable":
                "from every reachable state the handoff can still "
                "complete: the migration closes and the published "
                "version lands verified (AG EF)",
        })
