"""FleetController: the rank-0-hosted arbiter of one shared host pool
between a training world and a serving world (docs/fleet.md).

Everything the controller knows and decides lives in the coordinator
KV, under three scopes:

- ``fleet.gauges`` — each world's front publishes its load gauges
  (``train`` / ``serve`` keys: world size plus shed rate / queue depth
  / straggler lag), so the controller never needs a direct channel to
  either world;
- ``fleet.journal`` — an epoch-stamped record per migration
  (``mig:{id}``) advancing planned -> departing -> done, or through
  the abort path departing -> aborting (deadline exceeded, directive
  withdrawn, a late join still reconciles to done) -> aborted.
  The journal is the failover story: a re-elected controller claims a
  fresh epoch, adopts every non-terminal record, and either resumes it
  (directive already written — the mover may be mid-join) or safely
  aborts it (never started);
- ``fleet.ctl`` — the actuation records: ``depart:{id}`` directives a
  donor rank consumes at its statesync step boundary, and
  ``joined:{id}`` marks the mover writes after ``join_serving_world``
  / statesync grow completes on the other side.

Execution rides existing machinery end to end: the donor world shrinks
through the statesync preemption-grace boundary
(``StateSyncService.request_depart`` — orderly departure, no
RanksFailedError) and the mover joins the other world via peer-streamed
state.  The controller itself only writes KV records — which is what
makes its failover trivial and its protocol model-checkable
(fleet/specs.py).
"""
from __future__ import annotations

import json
import threading
import time

from ..common import config
from ..common.logging import logger
from ..telemetry.flight import recorder
from .policy import SERVE_TO_TRAIN, TRAIN_TO_SERVE, FleetPolicy

__all__ = ["CTL_SCOPE", "GAUGE_SCOPE", "JOURNAL_SCOPE", "FleetController",
           "mark_joined", "poll_depart", "publish_gauge", "read_gauge"]

GAUGE_SCOPE = "fleet.gauges"
JOURNAL_SCOPE = "fleet.journal"
CTL_SCOPE = "fleet.ctl"
# The statesync membership scope (statesync/service.py): rank 0 of
# each world publishes {"epoch", "size", "seq"} under the world's
# HOROVOD_STATESYNC_WORLD name at every transition — the controller
# reads it at actuation time, when a gauge may already be stale.
STATESYNC_SCOPE = "statesync"


# -- gauge + actuation records (both worlds' side) ------------------------
def publish_gauge(kv, world: str, size: int, **fields) -> None:
    """Publish one world's load gauge (world is "train" or "serve")."""
    rec = {"world": world, "size": int(size), "ts": time.time()}
    rec.update(fields)
    kv.put(GAUGE_SCOPE, world, json.dumps(rec).encode())


def read_gauge(kv, world: str) -> dict | None:
    raw = kv.get(GAUGE_SCOPE, world)
    return None if raw is None else json.loads(raw)


def poll_depart(kv, world: str, rank: int) -> dict | None:
    """A donor rank's boundary poll: the ``depart:{id}`` directive
    addressed to (world, rank), or None.  One scope dump per poll."""
    for key, raw in kv.get_scope(CTL_SCOPE).items():
        if not key.startswith("depart:"):
            continue
        rec = json.loads(raw)
        if rec.get("world") == world and int(rec.get("rank", -1)) == rank:
            return rec
    return None


def mark_joined(kv, mid: int, **fields) -> None:
    """The mover's arrival mark: written only after the destination
    world's join (peer-streamed state, digest-verified) completed."""
    rec = {"mid": int(mid), "ts": time.time()}
    rec.update(fields)
    kv.put(CTL_SCOPE, f"joined:{mid}", json.dumps(rec).encode())
    rec2 = recorder()
    if rec2.enabled:
        rec2.record("fleet-join", name=f"mig:{mid}",
                    detail=json.dumps(fields, sort_keys=True))


class FleetController(threading.Thread):
    """The rank-0 controller loop: poll gauges, tick the policy,
    journal and drive migrations, survive its own failover."""

    def __init__(self, kv, policy: FleetPolicy | None = None, *,
                 interval_s: float | None = None,
                 migrate_timeout_s: float | None = None) -> None:
        super().__init__(daemon=True, name="hvd-fleet-controller")
        self.kv = kv
        self.policy = FleetPolicy() if policy is None else policy
        self.interval_s = config.FLEET_INTERVAL_S.get() \
            if interval_s is None else float(interval_s)
        self.migrate_timeout_s = config.FLEET_MIGRATE_TIMEOUT_S.get() \
            if migrate_timeout_s is None else float(migrate_timeout_s)
        self._halt = threading.Event()
        self.epoch = -1                  # claimed in recover()
        self.open: dict[int, dict] = {}  # mid -> journal record
        self.stats = {"migrations": 0, "completed": 0, "aborted": 0,
                      "resumed": 0, "ticks": 0}

    # -- journal primitives ----------------------------------------------
    def _journal(self, rec: dict) -> None:
        self.kv.put(JOURNAL_SCOPE, f"mig:{rec['mid']}",
                    json.dumps(rec).encode())

    def _flight(self, rec: dict, what: str) -> None:
        fr = recorder()
        if fr.enabled:
            fr.record("fleet-migrate", name=f"mig:{rec['mid']}",
                      detail=f"{what} {rec['direction']} "
                             f"rank={rec['rank']} epoch={rec['epoch']}")

    # -- failover --------------------------------------------------------
    def recover(self) -> None:
        """Claim a controller epoch and adopt every non-terminal
        journal record left by a predecessor: a record whose directive
        was already written is resumed (the mover may be mid-flight); a
        merely planned one is safely aborted (its directive was never
        published, so no rank can be acting on it)."""
        self.epoch = self.kv.claim(JOURNAL_SCOPE, "epoch")
        ctl = self.kv.get_scope(CTL_SCOPE)
        for key, raw in self.kv.get_scope(JOURNAL_SCOPE).items():
            if not key.startswith("mig:"):
                continue
            rec = json.loads(raw)
            if rec.get("state") in ("done", "aborted"):
                continue
            rec["epoch"] = self.epoch
            if rec.get("state") == "planned" \
                    and f"depart:{rec['mid']}" not in ctl:
                rec["state"] = "aborted"
                rec["why"] = "controller failover before directive"
                self._journal(rec)
                self.stats["aborted"] += 1
                self._flight(rec, "aborted")
                continue
            rec["deadline"] = time.time() + self.migrate_timeout_s
            if rec.get("state") == "aborting":
                # Adopted mid-abort-grace: keep watching for the late
                # joined mark under a fresh grace window.
                rec["abort_deadline"] = time.time() \
                    + self.migrate_timeout_s
            self._journal(rec)
            self.open[int(rec["mid"])] = rec
            self.stats["resumed"] += 1
            self._flight(rec, "resumed")

    # -- migration lifecycle ---------------------------------------------
    def _donor_size(self, world: str, gauge_size: int) -> int:
        """The donor world's size at actuation time.  Gauges can be
        stale — a real preemption may have shrunk the world since the
        last publish, and a directive addressed to a rank that no
        longer exists would sit unconsumed until the deadline abort.
        The statesync membership record is refreshed at every world
        transition, so it wins when present."""
        try:
            raw = self.kv.get(STATESYNC_SCOPE, world)
        except (TimeoutError, OSError):
            raw = None
        if raw:
            try:
                return int(json.loads(raw)["size"])
            except (KeyError, TypeError, ValueError):
                pass
        return int(gauge_size)

    def begin_migration(self, direction: str, donor_size: int) -> dict:
        """Journal + actuate one move: the donor world's highest rank
        departs.  Journal first (planned), directive second, journal
        again (departing) — so every KV state a failover can observe is
        unambiguous about whether the directive may exist."""
        mid = self.kv.claim(JOURNAL_SCOPE, "seq")
        donor = "train" if direction == TRAIN_TO_SERVE else "serve"
        rec = {"mid": mid, "direction": direction, "world": donor,
               "rank": self._donor_size(donor, donor_size) - 1,
               "state": "planned",
               "epoch": self.epoch, "ts": time.time(),
               "deadline": time.time() + self.migrate_timeout_s}
        self._journal(rec)
        self.kv.put(CTL_SCOPE, f"depart:{mid}", json.dumps(
            {"mid": mid, "world": donor, "rank": rec["rank"],
             "direction": direction, "epoch": self.epoch}).encode())
        rec["state"] = "departing"
        self._journal(rec)
        self.open[mid] = rec
        self.stats["migrations"] += 1
        self._flight(rec, "departing")
        logger.info("fleet: migration %d %s rank %d departing",
                    mid, direction, rec["rank"])
        return rec

    def _advance(self) -> None:
        """Advance every open migration.  Joined mark -> done, with the
        depart AND joined actuation records cleaned up (a closed
        migration leaves nothing in CTL_SCOPE).  An expired deadline
        only REQUESTS the abort: the directive is withdrawn (a donor
        that has not consumed it yet will never depart), but a donor
        whose boundary poll already consumed it is past recall — it
        will depart and write its joined mark later.  Journaling
        'aborted' immediately would lie about a rank that actually
        migrated, leak its joined record, and let the policy fire a
        second migration against the already-shrunk donor.  So the
        record moves to 'aborting' and keeps watching for a late mark
        through one more timeout window: a late join reconciles to
        done, silence finally aborts."""
        if not self.open:
            return
        ctl = self.kv.get_scope(CTL_SCOPE)
        now = time.time()
        for mid, rec in list(self.open.items()):
            if f"joined:{mid}" in ctl:
                aborting = rec["state"] == "aborting"
                rec["state"] = "done"
                rec["done_ts"] = now
                if aborting:
                    rec["why"] = ("mover joined after the abort "
                                  "request: reconciled to done")
                self._journal(rec)
                self.kv.delete(CTL_SCOPE, f"depart:{mid}")
                self.kv.delete(CTL_SCOPE, f"joined:{mid}")
                del self.open[mid]
                self.stats["completed"] += 1
                self._flight(rec, "done")
                logger.info("fleet: migration %d complete%s", mid,
                            " (late join reconciled)" if aborting
                            else "")
            elif rec["state"] == "departing" \
                    and now > rec.get("deadline", 0):
                rec["state"] = "aborting"
                rec["why"] = "migration deadline exceeded"
                rec["abort_deadline"] = now + self.migrate_timeout_s
                self._journal(rec)
                self.kv.delete(CTL_SCOPE, f"depart:{mid}")
                self._flight(rec, "aborting")
                logger.warning(
                    "fleet: migration %d deadline exceeded; directive "
                    "withdrawn, watching for a late join", mid)
            elif rec["state"] == "aborting" \
                    and now > rec.get("abort_deadline", 0):
                rec["state"] = "aborted"
                self._journal(rec)
                self.kv.delete(CTL_SCOPE, f"joined:{mid}")
                del self.open[mid]
                self.stats["aborted"] += 1
                self._flight(rec, "aborted")
                logger.warning("fleet: migration %d aborted (no join "
                               "within the abort grace)", mid)

    # -- the loop --------------------------------------------------------
    def tick(self) -> dict | None:
        """One controller interval: advance open migrations, then (only
        when none is in flight — one move settles before the next is
        considered) feed the policy.  Returns the migration record if a
        new one began."""
        self.stats["ticks"] += 1
        self._advance()
        if self.open:
            return None
        train = read_gauge(self.kv, "train")
        serve = read_gauge(self.kv, "serve")
        if train is None or serve is None:
            return None
        decision = self.policy.observe(
            int(train["size"]), int(serve["size"]),
            shed_rate=float(serve.get("shed_rate", 0.0)),
            queue_depth=float(serve.get("queue_depth", 0.0)),
            straggler_lag_ms=float(train.get("straggler_lag_ms", 0.0)))
        if decision is None:
            return None
        donor_size = int(train["size"]) \
            if decision.direction == TRAIN_TO_SERVE else int(serve["size"])
        return self.begin_migration(decision.direction, donor_size)

    def run(self) -> None:
        try:
            self.recover()
        except (TimeoutError, OSError) as exc:
            logger.warning("fleet: controller recover failed: %s", exc)
            return
        while not self._halt.wait(timeout=self.interval_s):
            try:
                self.tick()
            except (TimeoutError, OSError) as exc:
                logger.debug("fleet: controller tick failed: %s", exc)

    def stop(self) -> None:
        self._halt.set()
        if self.is_alive() and self is not threading.current_thread():
            self.join(timeout=self.interval_s + 10.0)

    close = stop


_DIRECTIONS = (TRAIN_TO_SERVE, SERVE_TO_TRAIN)
