"""Elastic training state: commit / restore / sync.

Reference: horovod/common/elastic.py — ``State`` checkpoints to host memory
on ``commit()``, restores after a collective failure, and broadcast-syncs
from the new rank 0 after every re-rendezvous.  ``ObjectState`` handles plain
Python attributes; :class:`ArrayState` handles pytrees of jax/numpy arrays
(the idiomatic JAX analogue of the reference's per-framework tensor states).
"""
from __future__ import annotations

import copy
from typing import Any, Callable

import numpy as np

from ..common.exceptions import HostsUpdatedInterrupt
from .discovery import HostUpdateResult
from .worker import notification_manager


class State:
    """Base elastic state with commit/restore/sync hooks."""

    def __init__(self) -> None:
        self._reset_callbacks: list[Callable[[], None]] = []
        notification_manager.register_listener(self)

    def register_reset_callbacks(self, callbacks) -> None:
        """Callbacks run after every re-rendezvous (world size changed) —
        e.g. rescale the learning rate or repartition the dataset."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        self.reset()
        for callback in self._reset_callbacks:
            callback()

    def on_hosts_updated(self, timestamp: int, update_res: int) -> None:
        # Notification thread context: nothing to do eagerly; the training
        # thread observes the pending update in check_host_updates().
        pass

    def commit(self) -> None:
        """Checkpoint to host memory and surface any pending host updates."""
        self.save()
        self.check_host_updates()

    def check_host_updates(self) -> None:
        """Raise :class:`HostsUpdatedInterrupt` when membership changed.

        All ranks must agree to interrupt at the same point, so the locally
        pending notification timestamp is max-allreduced: if any rank heard
        from the driver, every rank interrupts together (reference:
        common/elastic.py:73-96).
        """
        from .. import allreduce  # late import: avoid cycle at package init

        if not notification_manager.has_driver:
            return
        pending_ts, pending_res = notification_manager.pending_update()
        # Sum-allreduce [heard?, added?, removed?]: if ANY rank heard from
        # the driver, every rank interrupts at this same point.
        local = np.array(
            [1 if pending_ts > 0 else 0,
             1 if pending_res & HostUpdateResult.ADDED else 0,
             1 if pending_res & HostUpdateResult.REMOVED else 0], np.int64)
        agreed = allreduce(local, average=False,
                           name="__elastic_host_updates__")
        if int(agreed[0]) <= 0:
            return
        # Only acknowledge what THIS rank actually heard; ranks that had not
        # yet received the notification clear it at the next rendezvous
        # (the driver stamps assignments with its notification clock).
        notification_manager.acknowledge(pending_ts)
        # Pure additions can keep the current state (no data was lost);
        # removals force a sync from the survivors' committed state.
        skip_sync = int(agreed[1]) > 0 and int(agreed[2]) == 0
        raise HostsUpdatedInterrupt(skip_sync)

    # -- to be provided by subclasses --------------------------------------
    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class ObjectState(State):
    """State holding plain picklable attributes
    (reference: common/elastic.py ObjectState)."""

    def __init__(self, **kwargs: Any) -> None:
        self._saved_state = kwargs
        for attr, value in kwargs.items():
            setattr(self, attr, value)
        super().__init__()

    def save(self) -> None:
        new_state = {}
        for attr in self._saved_state:
            new_state[attr] = copy.deepcopy(getattr(self, attr))
        self._saved_state = new_state

    def restore(self) -> None:
        for attr, value in self._saved_state.items():
            setattr(self, attr, copy.deepcopy(value))

    def sync(self) -> None:
        if self._saved_state:
            from .. import broadcast_object
            synced = broadcast_object(self._saved_state, root_rank=0,
                                      name="__elastic_object_state__")
            self._saved_state = synced
            self.restore()


class ArrayState(State):
    """State over pytrees of jax / numpy arrays (params, optimizer state,
    batch stats) plus plain-object extras.

    ``save()`` copies every leaf to host numpy; ``sync()`` broadcasts the
    committed leaves from rank 0 leaf-by-leaf (fused by the runtime's tensor
    fusion) so a joining worker adopts the survivors' state.
    """

    def __init__(self, trees: dict[str, Any] | None = None,
                 **objects: Any) -> None:
        self._trees: dict[str, Any] = dict(trees or {})
        self._objects = ObjectProxy(objects)
        self._saved_trees: dict[str, list[np.ndarray]] = {}
        self._treedefs: dict[str, Any] = {}
        for attr, value in objects.items():
            setattr(self, attr, value)
        self._object_names = list(objects)
        super().__init__()

    def tree(self, name: str) -> Any:
        return self._trees[name]

    def set_tree(self, name: str, value: Any) -> None:
        self._trees[name] = value

    def _flatten(self, value):
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(value)
        return leaves, treedef

    def save(self) -> None:
        import jax
        for name, value in self._trees.items():
            leaves, treedef = self._flatten(value)
            self._saved_trees[name] = [np.array(leaf) for leaf in leaves]
            self._treedefs[name] = treedef
        self._objects.data = {attr: copy.deepcopy(getattr(self, attr))
                              for attr in self._object_names}
        del jax

    def restore(self) -> None:
        import jax
        for name, host_leaves in self._saved_trees.items():
            treedef = self._treedefs[name]
            self._trees[name] = jax.tree_util.tree_unflatten(
                treedef, [jax.numpy.asarray(leaf) for leaf in host_leaves])
        for attr, value in self._objects.data.items():
            setattr(self, attr, copy.deepcopy(value))

    def sync(self) -> None:
        import jax
        from .. import broadcast, broadcast_object
        if not self._saved_trees:
            self.save()
        # Structure (treedefs, shapes, plain objects) first, then bulk leaves.
        meta = broadcast_object(
            {"objects": self._objects.data,
             "shapes": {n: [(leaf.shape, str(leaf.dtype))
                            for leaf in leaves]
                        for n, leaves in self._saved_trees.items()}},
            root_rank=0, name="__elastic_array_meta__")
        self._objects.data = meta["objects"]
        for name, shape_dtypes in meta["shapes"].items():
            local = self._saved_trees.get(name, [])
            synced = []
            for i, (shape, dtype) in enumerate(shape_dtypes):
                if i < len(local) and tuple(local[i].shape) == tuple(shape) \
                        and str(local[i].dtype) == dtype:
                    leaf = local[i]
                else:
                    leaf = np.zeros(shape, dtype)
                synced.append(np.asarray(
                    broadcast(leaf, root_rank=0,
                              name=f"__elastic_leaf__.{name}.{i}")))
            self._saved_trees[name] = synced
        self.restore()
        del jax


class ObjectProxy:
    """Mutable holder so saved plain objects survive deepcopy cycles."""

    def __init__(self, data: dict) -> None:
        self.data = data
