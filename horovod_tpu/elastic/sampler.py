"""Elastic-aware data sampler.

Reference: horovod/torch/elastic/sampler.py — partitions a dataset across
ranks, records which indices were already processed this epoch, and after a
world-size change re-shards only the *remaining* indices so no sample is
dropped or repeated.  Framework-agnostic here (works with torch DataLoaders
via ``__iter__``/``__len__``, or any Python loop).
"""
from __future__ import annotations

import random
from typing import Iterator, Sized


class ElasticSampler:
    def __init__(self, dataset: Sized, shuffle: bool = True,
                 seed: int = 0) -> None:
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_indices: set[int] = set()
        self.num_replicas = 1
        self.rank = 0
        self.remaining_indices: list[int] = []
        self.reset()

    def set_epoch(self, epoch: int) -> None:
        """New epoch: forget processed indices and reshuffle."""
        self.epoch = epoch
        self.processed_indices = set()
        self.reset()

    def record_batch(self, batch_idx: int, batch_size: int) -> None:
        """Mark one batch of this rank's shard as processed."""
        start = self.rank + batch_idx * batch_size * self.num_replicas
        indices = self.indices[batch_idx * batch_size:
                               (batch_idx + 1) * batch_size]
        del start
        self.record_indices(indices)

    def record_indices(self, indices) -> None:
        self.processed_indices.update(int(i) for i in indices)

    def reset(self) -> None:
        """Re-shard the remaining (unprocessed) indices over the current
        world.  Called on construction and from State.on_reset."""
        try:
            from .. import core
            if core.is_initialized():
                self.num_replicas = core.size()
                self.rank = core.rank()
        except Exception:  # noqa: BLE001 - usable before init in tests
            pass

        remaining = [i for i in range(len(self.dataset))
                     if i not in self.processed_indices]
        if self.shuffle:
            random.Random(self.seed + self.epoch).shuffle(remaining)
        self.remaining_indices = remaining

        # Pad so every rank yields the same number of samples (collectives
        # stay aligned), then take this rank's strided shard.
        total = len(remaining)
        if total % self.num_replicas != 0 and total > 0:
            pad = self.num_replicas - total % self.num_replicas
            remaining = remaining + remaining[:pad]
        self.indices = remaining[self.rank::self.num_replicas]

    def __iter__(self) -> Iterator[int]:
        return iter(self.indices)

    def __len__(self) -> int:
        return len(self.indices)

    # -- State integration -------------------------------------------------
    def state_dict(self) -> dict:
        return {"epoch": self.epoch,
                "processed_indices": sorted(self.processed_indices)}

    def load_state_dict(self, state: dict) -> None:
        self.epoch = state["epoch"]
        self.processed_indices = set(state["processed_indices"])
        self.reset()
