"""Tiny authenticated pickle-RPC over TCP.

Reference analogue: horovod/runner/common/service/{driver,task}_service.py +
common/util/{network,secret}.py — socket RPC between the launcher driver and
workers, HMAC-signed with a shared per-job secret so arbitrary processes on
the network cannot inject commands.
"""
from __future__ import annotations

import hmac
import hashlib
import os
import pickle
import socket
import threading

from ..common import wire
from ..common.logging import logger
from ..runner.network import advertised_hello, recv_exact, recv_msg, \
    send_msg

_DIGEST = hashlib.sha256
SECRET_ENV = "HOROVOD_SECRET_KEY"


def make_secret() -> str:
    return os.urandom(16).hex()


def _sign(secret: str, payload: bytes) -> bytes:
    return hmac.new(secret.encode(), payload, _DIGEST).digest()


def _pack(secret: str, obj) -> bytes:
    payload = pickle.dumps(obj)
    return _sign(secret, payload) + payload


def _unpack(secret: str, blob: bytes):
    mac, payload = blob[:_DIGEST().digest_size], blob[_DIGEST().digest_size:]
    if not hmac.compare_digest(mac, _sign(secret, payload)):
        raise PermissionError("RPC message failed HMAC verification")
    return pickle.loads(payload)


class RpcServer:
    """Serves method calls on a handler object: any public method becomes an
    RPC endpoint.  One thread per connection; connections may issue many
    calls (workers keep one open)."""

    def __init__(self, handler, secret: str, port: int = 0) -> None:
        self._handler = handler
        self._secret = secret
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("", port))
        self._listener.listen(128)
        self._closed = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="hvd-rpc-accept")
        self._thread.start()

    @property
    def port(self) -> int:
        return self._listener.getsockname()[1]

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="hvd-rpc-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            # Versioned handshake: the first bytes on every RPC
            # connection are a HELLO exchange, so a driver at framework
            # version N and a worker at N+1 agree on the min common
            # schema before any pickled call crosses (the rolling-
            # upgrade boundary lives exactly on this socket).
            try:
                peer_proto, peer_feats = wire.unpack_hello(
                    recv_exact(conn, wire.HELLO_LEN))
            except (ConnectionError, ValueError) as exc:
                logger.warning("rpc: connection rejected at HELLO: %s",
                               exc)
                return
            proto, feats = advertised_hello()
            conn.sendall(wire.pack_hello(proto, feats))
            while True:
                try:
                    method, args, kwargs = _unpack(self._secret,
                                                   recv_msg(conn))
                except (ConnectionError, EOFError):
                    return
                except PermissionError as exc:
                    logger.warning("rpc: %s", exc)
                    return
                try:
                    if method.startswith("_"):
                        raise AttributeError(method)
                    result = getattr(self._handler, method)(*args, **kwargs)
                    reply = (True, result)
                except Exception as exc:  # noqa: BLE001 - ship to caller
                    reply = (False, exc)
                send_msg(conn, _pack(self._secret, reply))
        finally:
            conn.close()

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        # Reap the accept loop (hvdlife HVD701): the listener close
        # above is its wakeup (accept raises OSError and the loop
        # returns).  Per-connection threads stay daemon by design —
        # see LIFECYCLE_ALLOWED in analysis/hvdlife/life.py.
        self._thread.join(timeout=5.0)


class RpcClient:
    """Blocking RPC client; one persistent connection, thread-safe."""

    def __init__(self, addr: str, port: int, secret: str,
                 timeout: float = 30.0) -> None:
        self._sock = socket.create_connection((addr, port), timeout=timeout)
        proto, feats = advertised_hello()
        self._sock.sendall(wire.pack_hello(proto, feats))
        self.peer_proto, peer_feats = wire.unpack_hello(
            recv_exact(self._sock, wire.HELLO_LEN))
        self.negotiated_proto, self.negotiated_features = wire.negotiate(
            proto, feats, self.peer_proto, peer_feats)
        # Calls may legitimately block far longer than the connect timeout:
        # get_assignment waits server-side for a rendezvous round (up to the
        # driver's elastic_timeout).  Block until the server answers or the
        # connection breaks — a short recv timeout here would crash healthy
        # workers and cascade into host blacklisting.
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._secret = secret
        self._lock = threading.Lock()

    def call(self, method: str, *args, **kwargs):
        with self._lock:
            send_msg(self._sock, _pack(self._secret, (method, args, kwargs)))
            ok, result = _unpack(self._secret, recv_msg(self._sock))
        if not ok:
            raise result
        return result

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
