"""The elastic worker retry loop: ``hvd.elastic.run(train_fn)``.

Reference: horovod/common/elastic.py:151-175 ``run_fn`` — wraps the training
function so that collective failures restore the last committed state and
host-membership changes re-rendezvous, both followed by re-initialising the
runtime with a freshly assigned rank.
"""
from __future__ import annotations

import functools
import os

from ..common import config
from ..common.exceptions import (HorovodInternalError,
                                 HostsUpdatedInterrupt)
from ..common.logging import logger
from .state import State
from .worker import notification_manager


class _WorkerDropped(Exception):
    """This worker's slot is not part of the new assignment; exit quietly."""


def _apply_assignment(assignment: dict) -> None:
    env = {
        "HOROVOD_RANK": assignment["rank"],
        "HOROVOD_SIZE": assignment["size"],
        "HOROVOD_LOCAL_RANK": assignment["local_rank"],
        "HOROVOD_LOCAL_SIZE": assignment["local_size"],
        "HOROVOD_CROSS_RANK": assignment["cross_rank"],
        "HOROVOD_CROSS_SIZE": assignment["cross_size"],
        "HOROVOD_HOST_IDS": assignment.get("host_ids", ""),
        "HOROVOD_RENDEZVOUS_EPOCH": assignment["epoch"],
    }
    for key, value in env.items():
        os.environ[key] = str(value)
    # The driver stamps each assignment with its notification clock: any
    # host-update notification at or before this epoch's formation is
    # already reflected in the assignment, so drop it.
    notification_manager.acknowledge(int(assignment.get("notify_ts", 0)))


def _rendezvous(min_epoch: int) -> int:
    """(Re-)initialise the runtime, pulling a fresh rank assignment from the
    driver when one is attached (reference: gloo_context.cc:154-200 re-reads
    rank from the rendezvous server on reset)."""
    from .. import core

    notification_manager.init()
    if notification_manager.has_driver:
        # Asking for an epoch newer than the driver's current one IS the
        # READY signal: the driver forms a new round once every expected
        # worker has asked (or failed).
        assignment = notification_manager.get_assignment(min_epoch)
        if assignment is None:
            raise _WorkerDropped()
        _apply_assignment(assignment)
        epoch = int(assignment["epoch"])
    else:
        epoch = min_epoch
    core.init()
    return epoch


def run(func):
    """Decorator for elastic training functions.

    The wrapped function must take a :class:`State` as its first argument::

        @hvd.elastic.run
        def train(state, ...):
            ...

    On ``HorovodInternalError`` the last committed state is restored; on
    ``HostsUpdatedInterrupt`` the current state is kept; either way the
    runtime re-initialises against the new world before retrying.
    """
    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        from .. import core

        reset_required = not core.is_initialized()
        skip_sync = False
        epoch = int(os.environ.get("HOROVOD_RENDEZVOUS_EPOCH", "0"))
        if reset_required:
            try:
                epoch = _rendezvous(epoch)
            except _WorkerDropped:
                return None

        while True:
            try:
                if not skip_sync:
                    state.sync()
                result = func(state, *args, **kwargs)
                notification_manager.record_success()
                return result
            except HorovodInternalError:
                logger.warning("collective failure; restoring last "
                               "committed state and re-rendezvousing")
                state.restore()
                skip_sync = False
            except HostsUpdatedInterrupt as exc:
                logger.info("host membership changed; re-rendezvousing")
                skip_sync = exc.skip_sync
            except _WorkerDropped:
                return None

            core.shutdown()
            try:
                epoch = _rendezvous(epoch + 1)
            except _WorkerDropped:
                return None
            state.on_reset()

    return wrapper


def run_fn(func, reset):  # pragma: no cover - thin compatibility alias
    """Reference-compatible functional form (common/elastic.py run_fn)."""
    return run(func)
