"""Worker-state barrier for elastic rendezvous rounds.

Reference: horovod/runner/elastic/registration.py — ``WorkerStateRegistry``
collects READY / SUCCESS / FAILURE records from workers per rendezvous round;
when every live worker has reported, it triggers the driver's ``resume`` (on
failure or host change) or marks the job finished.
"""
from __future__ import annotations

import threading
from collections import defaultdict

from ..common.logging import logger

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"


class WorkerStateRegistry:
    def __init__(self, driver, host_manager, reset_limit: int | None = None,
                 verbose: bool = False) -> None:
        self._driver = driver
        self._host_manager = host_manager
        self._reset_limit = reset_limit
        self._verbose = verbose
        self._lock = threading.Lock()
        self._states: dict[str, str] = {}
        self._workers: dict[str, set[str]] = defaultdict(set)
        self._rendezvous_id = 0
        self._size = 0
        self._expected: set[str] | None = None
        self._round_complete = False

    @property
    def rendezvous_id(self) -> int:
        return self._rendezvous_id

    def get_recorded_slots(self) -> list[str]:
        with self._lock:
            return list(self._states)

    def get(self, state: str) -> list[str]:
        with self._lock:
            return sorted(self._workers.get(state, set()))

    def count(self, state: str) -> int:
        with self._lock:
            return len(self._workers.get(state, set()))

    def reset(self, size: int, expected_slots=None) -> None:
        """Start a new rendezvous round expecting ``size`` workers.

        ``expected_slots``: optional iterable of "host[slot]" keys; records
        for any other slot (e.g. a long-dead worker on a host removed in an
        earlier round) are ignored so they cannot complete the round
        barrier prematurely."""
        with self._lock:
            logger.debug("registry reset: size=%d round=%d", size,
                         self._rendezvous_id)
            self._states.clear()
            self._workers.clear()
            self._size = size
            self._expected = set(expected_slots) \
                if expected_slots is not None else None
            self._rendezvous_id += 1
            self._round_complete = False

    def size(self) -> int:
        with self._lock:
            return self._size

    def last_rendezvous(self) -> int:
        return self._rendezvous_id

    def record_ready(self, host: str, slot: int,
                     round_id: int | None = None) -> int:
        return self._record_state(host, slot, READY, round_id)

    def record_success(self, host: str, slot: int) -> int:
        return self._record_state(host, slot, SUCCESS)

    def record_failure(self, host: str, slot: int) -> int:
        return self._record_state(host, slot, FAILURE)

    def _record_state(self, host: str, slot: int, state: str,
                      round_id: int | None = None) -> int:
        if self._driver.finished():
            return self._rendezvous_id
        if state == FAILURE:
            # A failed worker taints its host for future assignment rounds.
            self._host_manager.blacklist(host)

        key = f"{host}[{slot}]"
        fire = False
        with self._lock:
            if round_id is not None and round_id != self._rendezvous_id:
                # The record targeted a round that already resolved (the
                # caller re-checks the epoch); dropping it prevents a READY
                # from leaking into the NEXT round's barrier.
                return self._rendezvous_id
            if self._expected is not None and key not in self._expected:
                logger.debug("ignoring %s record for %s: not part of "
                             "round %d", state, key, self._rendezvous_id)
                return self._rendezvous_id
            cur = self._states.get(key)
            if cur is None:
                self._states[key] = state
                self._workers[state].add(key)
            elif cur != state and state != READY:
                # A failure/success overrides a prior READY (worker died or
                # finished after declaring readiness); READY never downgrades.
                logger.debug("%s: state %s -> %s", key, cur, state)
                self._workers[cur].discard(key)
                self._states[key] = state
                self._workers[state].add(key)
            rendezvous_id = self._rendezvous_id
            if not self._round_complete and len(self._states) >= self._size:
                self._round_complete = True
                fire = True
        if fire:
            self._on_workers_recorded()
        return rendezvous_id

    def _on_workers_recorded(self) -> None:
        logger.debug("all %d workers recorded", self._size)
        if self.count(SUCCESS) == self._size:
            logger.info("all workers succeeded; job complete")
            self._driver.stop()
            return
        if self._driver.finished():
            return
        if self.count(FAILURE) > 0 and self._reset_limit is not None and \
                self._rendezvous_id >= self._reset_limit:
            logger.error(
                "reset limit %d reached; terminating job", self._reset_limit)
            self._driver.set_reset_limit_exceeded()
            self._driver.stop()
            return
        # Otherwise a new rendezvous round is wanted: either a host change
        # (all READY) or a failure with budget remaining.
        self._driver.resume()
