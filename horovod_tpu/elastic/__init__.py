"""Elastic (fault-tolerant, autoscaling) training.

TPU-native rebuild of the reference elastic layer
(reference: horovod/runner/elastic/{driver,discovery,registration,worker}.py
and horovod/common/elastic.py).  Three cooperating pieces:

- the **driver** (launcher side): polls a host-discovery source, keeps a
  blacklist of failed hosts, computes stable rank assignments, spawns/respawns
  worker processes, and publishes assignments through the rendezvous KV;
- the **worker state machine**: ``hvd.elastic.run(fn)`` wraps the training
  function in a retry loop that commits/restores :class:`State` and
  re-rendezvouses on membership changes or collective failures;
- **notification plumbing**: the driver pushes host-change events into
  running workers so they can interrupt proactively instead of failing.
"""
from __future__ import annotations

from .discovery import (FixedHostDiscovery, HostDiscovery,
                        HostDiscoveryScript, HostManager)
from .registration import READY, FAILURE, SUCCESS, WorkerStateRegistry
from .state import ArrayState, ObjectState, State
from .run import run
from .sampler import ElasticSampler

__all__ = [
    "ArrayState", "ElasticSampler", "FixedHostDiscovery", "HostDiscovery",
    "HostDiscoveryScript", "HostManager", "ObjectState", "State",
    "WorkerStateRegistry", "READY", "SUCCESS", "FAILURE", "run",
]
