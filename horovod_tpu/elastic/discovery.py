"""Host discovery for elastic runs.

Reference: horovod/runner/elastic/discovery.py — ``HostDiscoveryScript``
executes a user script whose stdout lists ``hostname:slots`` lines;
``HostManager`` tracks the active host set and a blacklist of hosts that
failed, so they are never assigned ranks again.
"""
from __future__ import annotations

import subprocess
import threading
import time
from collections import OrderedDict

from ..common.logging import logger


class HostUpdateResult:
    NO_UPDATE = 0
    ADDED = 1
    REMOVED = 2
    MIXED = ADDED | REMOVED


class HostDiscovery:
    """Source of the current available hosts."""

    def find_available_hosts_and_slots(self) -> "OrderedDict[str, int]":
        """Return {hostname: slot_count} for every currently usable host."""
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs a user-provided executable; each stdout line is ``host`` or
    ``host:slots`` (reference: discovery.py HostDiscoveryScript)."""

    def __init__(self, discovery_script: str, default_slots: int) -> None:
        self._script = discovery_script
        self._default_slots = default_slots

    def find_available_hosts_and_slots(self) -> "OrderedDict[str, int]":
        out = subprocess.check_output(self._script, shell=True).decode()
        hosts: "OrderedDict[str, int]" = OrderedDict()
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                hostname, slots = line.rsplit(":", 1)
                hosts[hostname] = int(slots)
            else:
                hosts[line] = self._default_slots
        return hosts


class FixedHostDiscovery(HostDiscovery):
    """Static host set (used when -H/--hosts is given for an elastic run)."""

    def __init__(self, hosts: "OrderedDict[str, int]") -> None:
        self._hosts = OrderedDict(hosts)

    def find_available_hosts_and_slots(self) -> "OrderedDict[str, int]":
        return OrderedDict(self._hosts)


class HostManager:
    """Tracks available hosts and the blacklist
    (reference: discovery.py HostManager).

    Unlike the reference (and this tree before ISSUE 10), the blacklist
    is not one-way for the life of the driver: an entry can carry a
    cooldown (preempted cloud hosts routinely come back) and can be
    cleared manually (``clear_blacklist``).  A host whose entry expires
    or is cleared re-enters discovery on the next update with its
    CURRENT slot count — the discovery script's answer is authoritative,
    so a host that returned smaller or larger is assigned accordingly,
    never from a stale remembered count."""

    def __init__(self, discovery: HostDiscovery,
                 blacklist_cooldown: float | None = None) -> None:
        self._discovery = discovery
        self._lock = threading.Lock()
        self._current_hosts: "OrderedDict[str, int]" = OrderedDict()
        # host -> expiry (monotonic seconds; inf = until cleared).
        self._blacklist: dict[str, float] = {}
        self._default_cooldown = blacklist_cooldown

    def _expire_blacklist_locked(self) -> bool:
        now = time.monotonic()
        expired = [h for h, t in self._blacklist.items() if t <= now]
        for h in expired:
            logger.warning("blacklist for host %s expired; it may "
                           "re-enter discovery", h)
            del self._blacklist[h]
        return bool(expired)

    def update_available_hosts(self) -> int:
        """Re-run discovery; return a HostUpdateResult bitmask."""
        discovered = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            self._expire_blacklist_locked()
            usable = OrderedDict((h, s) for h, s in discovered.items()
                                 if h not in self._blacklist)
            prev = set(self._current_hosts)
            cur = set(usable)
            res = HostUpdateResult.NO_UPDATE
            if cur - prev:
                res |= HostUpdateResult.ADDED
            if prev - cur:
                res |= HostUpdateResult.REMOVED
            # Slot-count change on an existing host counts as an update too.
            if res == HostUpdateResult.NO_UPDATE and usable != \
                    self._current_hosts:
                res = HostUpdateResult.MIXED
            self._current_hosts = usable
            return res

    @property
    def current_hosts(self) -> "OrderedDict[str, int]":
        with self._lock:
            return OrderedDict(self._current_hosts)

    def blacklist(self, host: str, cooldown: float | None = None) -> None:
        """Exclude ``host`` from assignment.  ``cooldown`` seconds (or
        the manager default) bound the exclusion; None on both means
        until :meth:`clear_blacklist`."""
        if cooldown is None:
            cooldown = self._default_cooldown
        expiry = float("inf") if cooldown is None \
            else time.monotonic() + float(cooldown)
        with self._lock:
            if self._blacklist.get(host, 0.0) >= expiry:
                return
            logger.warning(
                "blacklisting host %s%s", host,
                "" if cooldown is None else f" for {cooldown:g}s")
            self._blacklist[host] = expiry
            self._current_hosts.pop(host, None)

    def clear_blacklist(self, host: str) -> bool:
        """Manually re-admit a host (a returning preempted node, an
        operator override).  It re-enters on the next discovery update
        with whatever slot count the discovery source then reports."""
        with self._lock:
            if host not in self._blacklist:
                return False
            logger.warning("blacklist cleared for host %s", host)
            del self._blacklist[host]
            return True

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            self._expire_blacklist_locked()
            return host in self._blacklist

    @property
    def blacklisted_hosts(self) -> set[str]:
        with self._lock:
            self._expire_blacklist_locked()
            return set(self._blacklist)
