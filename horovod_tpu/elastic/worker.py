"""Worker-side notification plumbing for elastic runs.

Reference: horovod/runner/elastic/worker.py — the driver pushes host-change
events into running workers; ``State.check_host_updates`` consumes them
between batches and raises :class:`HostsUpdatedInterrupt` so every rank
re-rendezvouses proactively instead of waiting for a collective to fail.
"""
from __future__ import annotations

import os
import threading

from ..common import config
from ..common.logging import logger
from .discovery import HostUpdateResult
from .rpc import SECRET_ENV, RpcClient, RpcServer

DRIVER_ADDR_ENV = "HOROVOD_DRIVER_ADDR"
DRIVER_PORT_ENV = "HOROVOD_DRIVER_PORT"


class _NotificationHandler:
    """RPC surface the driver calls into the worker."""

    def __init__(self, manager: "WorkerNotificationManager") -> None:
        self._manager = manager

    def notify_hosts_updated(self, timestamp: int, update_res: int) -> None:
        self._manager.handle_hosts_updated(timestamp, update_res)

    def ping(self) -> str:
        return "ok"


class WorkerNotificationManager:
    """Process-wide singleton workers use to receive driver events and to
    report lifecycle state (READY/SUCCESS/FAILURE) back to the driver."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._service: RpcServer | None = None
        self._driver: RpcClient | None = None
        self._listeners: list = []
        self._last_timestamp = 0
        self._pending_timestamp = 0
        self._pending_res = HostUpdateResult.NO_UPDATE

    # -- setup -------------------------------------------------------------
    def init(self) -> None:
        """Start the notification service and register with the driver.
        No-op when not launched by an elastic driver."""
        with self._lock:
            if self._service is not None or \
                    DRIVER_ADDR_ENV not in os.environ:
                return
            secret = os.environ.get(SECRET_ENV, "")
            self._service = RpcServer(_NotificationHandler(self), secret)
            self._driver = RpcClient(os.environ[DRIVER_ADDR_ENV],
                                     int(os.environ[DRIVER_PORT_ENV]),
                                     secret)
            hostname = config.HOSTNAME.get() or "localhost"
            local_rank = max(config.LOCAL_RANK.get(), 0)
            from ..runner.network import advertised_hello
            self._driver.call("register_worker", hostname, local_rank,
                              self._service.port,
                              proto=advertised_hello()[0])
            logger.debug("worker notification service on port %d",
                         self._service.port)

    @property
    def has_driver(self) -> bool:
        return self._driver is not None

    # -- driver-pushed events ---------------------------------------------
    def handle_hosts_updated(self, timestamp: int, update_res: int) -> None:
        with self._lock:
            if timestamp <= self._last_timestamp:
                return
            self._pending_timestamp = max(self._pending_timestamp, timestamp)
            self._pending_res |= update_res
            listeners = list(self._listeners)
        for listener in listeners:
            listener.on_hosts_updated(timestamp, update_res)

    def pending_update(self) -> tuple[int, int]:
        with self._lock:
            return self._pending_timestamp, self._pending_res

    def acknowledge(self, timestamp: int) -> None:
        with self._lock:
            self._last_timestamp = max(self._last_timestamp, timestamp)
            if self._pending_timestamp <= self._last_timestamp:
                self._pending_timestamp = 0
                self._pending_res = HostUpdateResult.NO_UPDATE

    def register_listener(self, listener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def remove_listener(self, listener) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    # -- worker → driver lifecycle reports ---------------------------------
    def _slot(self) -> tuple[str, int]:
        return (config.HOSTNAME.get() or "localhost",
                max(config.LOCAL_RANK.get(), 0))

    def record_ready(self) -> None:
        if self._driver is not None:
            host, slot = self._slot()
            self._driver.call("record_ready", host, slot)

    def record_success(self) -> None:
        if self._driver is not None:
            host, slot = self._slot()
            self._driver.call("record_success", host, slot)

    def record_failure(self) -> None:
        if self._driver is not None:
            host, slot = self._slot()
            self._driver.call("record_failure", host, slot)

    def get_assignment(self, min_epoch: int) -> dict:
        """Fetch this slot's rank assignment for the next rendezvous epoch
        (blocking on the driver until one with epoch >= min_epoch exists)."""
        assert self._driver is not None
        host, slot = self._slot()
        return self._driver.call("get_assignment", host, slot, min_epoch)

    def shutdown(self) -> None:
        with self._lock:
            if self._service is not None:
                self._service.close()
                self._service = None
            if self._driver is not None:
                self._driver.close()
                self._driver = None


notification_manager = WorkerNotificationManager()
