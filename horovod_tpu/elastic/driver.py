"""The elastic driver: discovery polling, stable rank assignment, worker
lifecycle, and rendezvous-round formation.

Reference: horovod/runner/elastic/driver.py — a discovery thread re-runs the
user's host script (default every 1s), diffs the host set, notifies running
workers; rank assignments preserve existing placements where possible; failed
workers blacklist their host and trigger a resume on the surviving set.

Round protocol (TPU rebuild, replaces the reference's HTTP rendezvous
handler): the driver owns a monotonically increasing **epoch**.  Workers call
``get_assignment(host, slot, min_epoch)``:

- ``min_epoch <= current``: returns the current round's assignment (initial
  join);
- ``min_epoch > current``: counts as a READY record for that slot; the call
  blocks until a new round forms, which happens when every slot of the
  current round has recorded READY / SUCCESS / FAILURE.
"""
from __future__ import annotations

import threading
import time
from typing import Callable

from ..common.logging import logger
from ..runner.hosts import (HostInfo, SlotInfo, get_host_assignments,
                            host_ids_env)
from .discovery import HostManager, HostUpdateResult
from .registration import WorkerStateRegistry
from .rpc import RpcClient
from .worker import SECRET_ENV  # noqa: F401  (re-export convenience)

DISCOVERY_INTERVAL_SECS = 1.0


class ElasticDriver:
    def __init__(self, discovery, min_np: int, max_np: int | None = None,
                 timeout: float = 600.0, reset_limit: int | None = None,
                 secret: str = "", verbose: bool = False) -> None:
        self._host_manager = HostManager(discovery)
        self._min_np = min_np
        self._max_np = max_np
        self._timeout = timeout
        self._secret = secret
        self._verbose = verbose
        self.registry = WorkerStateRegistry(self, self._host_manager,
                                            reset_limit=reset_limit)

        self._lock = threading.Lock()
        self._round_cond = threading.Condition(self._lock)
        self._epoch = 0
        self._notify_clock = 0
        self._assignments: dict[tuple[str, int], SlotInfo] = {}
        self._host_order: list[str] = []
        self._running: set[tuple[str, int]] = set()
        self._results: dict[str, tuple[int, float]] = {}
        self._workers: dict[tuple[str, int], RpcClient] = {}
        # Wire proto version each registered worker advertised (rolling-
        # upgrade observability; see register_worker).
        self._worker_protos: dict[tuple[str, int], int] = {}

        # Autoscale target (statesync/autoscale.py): caps the slots the
        # next round assigns.  None = no cap beyond max_np.
        self._target_np: int | None = None

        self._finished = threading.Event()
        self._shutdown = threading.Event()
        self._reset_limit_exceeded = False
        self._resume_failed = False
        self._create_worker_fn: Callable[[SlotInfo], int] | None = None
        self._discovery_thread: threading.Thread | None = None

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------
    def start(self, np: int,
              create_worker_fn: Callable[[SlotInfo], int]) -> None:
        """Form the first round with ``np`` target slots and spawn workers.
        ``create_worker_fn(slot_info)`` must block until the worker process
        exits and return its exit code (run per-slot in a thread)."""
        self._create_worker_fn = create_worker_fn
        self.wait_for_available_slots(self._min_np)
        self._form_round()
        self._discovery_thread = threading.Thread(
            target=self._discover_hosts, daemon=True, name="hvd-discovery")
        self._discovery_thread.start()

    def wait_for_available_slots(self, min_np: int) -> None:
        deadline = time.monotonic() + self._timeout
        while True:
            self._host_manager.update_available_hosts()
            avail = sum(self._host_manager.current_hosts.values())
            if avail >= min_np:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {avail}/{min_np} slots became available within "
                    f"{self._timeout}s")
            time.sleep(DISCOVERY_INTERVAL_SECS)

    def stop(self) -> None:
        self._finished.set()
        with self._round_cond:
            self._round_cond.notify_all()
        # Reap the discovery loop (hvdlife HVD701): _finished is its
        # wakeup (the loop polls it every DISCOVERY_INTERVAL_SECS).
        # stop() can be invoked from the discovery thread itself on the
        # failed-resume path — never self-join.
        t = self._discovery_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=DISCOVERY_INTERVAL_SECS + 5.0)
            self._discovery_thread = None

    def finished(self) -> bool:
        return self._finished.is_set()

    def set_reset_limit_exceeded(self) -> None:
        self._reset_limit_exceeded = True

    @property
    def reset_limit_exceeded(self) -> bool:
        return self._reset_limit_exceeded

    @property
    def resume_failed(self) -> bool:
        """True when a mid-job resume could not re-form a round (e.g. too
        few surviving slots) — the job ended abnormally even if some
        workers exited 0."""
        return self._resume_failed

    def join(self, timeout: float | None = None) -> bool:
        return self._finished.wait(timeout)

    def wait_for_workers_exit(self, timeout: float = 30.0) -> None:
        """Drain live worker processes after the job finishes.  The
        registry marks the job complete on the workers' SUCCESS RPC, which
        arrives BEFORE their processes exit — collecting results without
        draining would miss the successful exit codes."""
        deadline = time.time() + timeout
        while self._running and time.time() < deadline:
            time.sleep(0.05)

    def shutdown(self) -> None:
        self.stop()
        self._shutdown.set()
        for client in self._workers.values():
            client.close()

    def get_results(self) -> dict[str, tuple[int, float]]:
        return dict(self._results)

    def world_size(self) -> int:
        """Size of the most recently formed world (0 before any round)."""
        return len(self._assignments)

    @property
    def current_epoch(self) -> int:
        """Epoch of the most recently formed round."""
        with self._round_cond:
            return self._epoch

    def final_slots(self) -> dict[int, str]:
        """rank -> "host[local_rank]" of the most recently formed round."""
        with self._round_cond:
            return {s.rank: f"{s.hostname}[{s.local_rank}]"
                    for s in self._assignments.values()}

    def set_target_np(self, n: int) -> None:
        """Autoscale hook (statesync/autoscale.py): cap the slots the
        NEXT round assigns to ``n`` (clamped to [min_np, max_np]).  The
        running round is untouched — the target applies when discovery
        changes or a resume re-forms the world."""
        n = max(int(n), self._min_np)
        if self._max_np is not None:
            n = min(n, self._max_np)
        with self._round_cond:
            self._target_np = n

    def target_np(self) -> int | None:
        with self._round_cond:
            return self._target_np

    def rank_to_slot(self) -> dict[int, "SlotInfo"]:
        """rank -> SlotInfo of the most recently formed round — the
        lookup the resilience shrink policy uses to map a
        RanksFailedError's failed-rank set onto hosts to blacklist
        (resilience/policy.py apply_shrink)."""
        with self._round_cond:
            return {s.rank: s for s in self._assignments.values()}

    # ------------------------------------------------------------------
    # Round formation / rank assignment
    # ------------------------------------------------------------------
    def _ordered_hosts(self) -> list[HostInfo]:
        """Current hosts in seniority order: hosts that already hold ranks
        keep their position; new hosts append (reference: driver.py
        _update_host_assignments rank-preservation)."""
        current = self._host_manager.current_hosts
        order = [h for h in self._host_order if h in current]
        order.extend(h for h in current if h not in order)
        self._host_order = order
        return [HostInfo(hostname=h, slots=current[h]) for h in order]

    def _form_round(self) -> None:
        """Compute assignments for the current host set and open a new
        epoch.  Called at start and whenever a round completes."""
        with self._round_cond:
            hosts = self._ordered_hosts()
            max_np = self._max_np if self._target_np is None \
                else self._target_np
            slots = get_host_assignments(hosts, self._min_np, max_np)
            self._assignments = {(s.hostname, s.local_rank): s
                                 for s in slots}
            self._epoch += 1
            self.registry.reset(len(slots),
                                expected_slots=[
                                    f"{s.hostname}[{s.local_rank}]"
                                    for s in slots])
            logger.info("elastic round %d: %d slots on %s", self._epoch,
                        len(slots), ",".join(h.hostname for h in hosts))
            self._round_cond.notify_all()
        # Spawn processes for slots that have no live worker.
        for key, slot in list(self._assignments.items()):
            if key not in self._running:
                self._launch_worker(slot)

    def resume(self) -> None:
        """Form a new round on the surviving host set (called by the
        registry when the current round fully resolves)."""
        if self.finished():
            return
        try:
            self.wait_for_available_slots(self._min_np)
            self._form_round()
        except (TimeoutError, ValueError) as exc:
            logger.error("cannot resume elastic job: %s", exc)
            self._resume_failed = True
            self.stop()

    def _launch_worker(self, slot: SlotInfo) -> None:
        key = (slot.hostname, slot.local_rank)
        self._running.add(key)

        def _run() -> None:
            try:
                exit_code = self._create_worker_fn(slot)
            except Exception as exc:  # noqa: BLE001 - spawn failure
                logger.error("worker %s[%d] spawn failed: %s",
                             slot.hostname, slot.local_rank, exc)
                exit_code = 1
            self._running.discard(key)
            self._handle_worker_exit(slot, exit_code)

        threading.Thread(target=_run, daemon=True,
                         name=f"hvd-worker-{slot.hostname}-"
                              f"{slot.local_rank}").start()

    def _handle_worker_exit(self, slot: SlotInfo, exit_code: int) -> None:
        name = f"{slot.hostname}[{slot.local_rank}]"
        self._results[name] = (exit_code, time.time())
        if self.finished():
            return
        if exit_code == 0:
            self.registry.record_success(slot.hostname, slot.local_rank)
        else:
            logger.warning("worker %s exited with code %d", name, exit_code)
            self.registry.record_failure(slot.hostname, slot.local_rank)

    # ------------------------------------------------------------------
    # RPC surface (called by workers through RpcServer)
    # ------------------------------------------------------------------
    def register_worker(self, host: str, slot: int, port: int,
                        proto: int | None = None) -> None:
        """Worker announces its notification service endpoint.  `proto`
        is the wire protocol version the worker speaks (None = a
        pre-handshake worker): the driver keeps the per-slot table so a
        rolling upgrade is observable — a mixed-version world logs the
        lagging slots, and :meth:`worker_protos` feeds the operator
        view."""
        try:
            client = RpcClient(host, port, self._secret)
        except OSError as exc:
            logger.warning("cannot connect to worker %s[%d]: %s",
                           host, slot, exc)
            return
        self._workers[(host, slot)] = client
        self._worker_protos[(host, slot)] = \
            client.peer_proto if proto is None else int(proto)
        versions = set(self._worker_protos.values())
        if len(versions) > 1:
            lagging = sorted(k for k, v in self._worker_protos.items()
                             if v == min(versions))
            logger.warning(
                "elastic: mixed wire proto versions in the world "
                "(%s); lagging slots: %s — rolling upgrade in "
                "progress, collectives run at the min common schema",
                sorted(versions), lagging)

    def worker_protos(self) -> dict:
        """{(host, slot): advertised wire proto} of registered workers."""
        return dict(self._worker_protos)

    def record_ready(self, host: str, slot: int) -> None:
        self.registry.record_ready(host, slot)

    def record_success(self, host: str, slot: int) -> None:
        self.registry.record_success(host, slot)

    def record_failure(self, host: str, slot: int) -> None:
        self.registry.record_failure(host, slot)

    def get_assignment(self, host: str, slot: int,
                       min_epoch: int) -> dict | None:
        """Return this slot's assignment once ``epoch >= min_epoch`` (and
        >= 1).  Asking beyond the current epoch records READY.  Returns
        None when the slot is not part of the new round (worker exits)."""
        with self._round_cond:
            current = self._epoch
        if min_epoch > current:
            # Record READY outside the round lock (the registry may resume()
            # synchronously, and _form_round re-acquires the lock), but
            # bound to the round it targets: if the round resolves between
            # the epoch read and the record, the registry drops it so the
            # stale READY cannot pre-complete the NEXT round's barrier.
            self.registry.record_ready(host, slot, round_id=current)
        deadline = time.monotonic() + self._timeout
        with self._round_cond:
            while self._epoch < max(min_epoch, 1) and not self.finished():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"no rendezvous round >= {min_epoch} formed within "
                        f"{self._timeout}s")
                self._round_cond.wait(timeout=min(remaining, 1.0))
            if self.finished():
                return None
            info = self._assignments.get((host, slot))
            if info is None:
                return None
            return {
                "rank": info.rank, "size": info.size,
                "local_rank": info.local_rank,
                "local_size": info.local_size,
                "cross_rank": info.cross_rank,
                "cross_size": info.cross_size,
                "epoch": self._epoch,
                "notify_ts": self._notify_clock,
                "hostname": info.hostname,
                # Whole-round rank→host map: rounds formed on uneven
                # slots-per-host break the homogeneous layout that
                # local/cross-size topology auto-detection assumes, so the
                # worker feeds this into topology.resolve(hosts=...).
                "host_ids": host_ids_env(list(self._assignments.values())),
            }

    # ------------------------------------------------------------------
    # Discovery thread
    # ------------------------------------------------------------------
    def _discover_hosts(self) -> None:
        while not self._finished.is_set():
            try:
                res = self._host_manager.update_available_hosts()
            except Exception as exc:  # noqa: BLE001 - discovery script error
                logger.warning("host discovery failed: %s", exc)
                res = HostUpdateResult.NO_UPDATE
            if res != HostUpdateResult.NO_UPDATE:
                self._notify_workers_host_changes(res)
            self._finished.wait(DISCOVERY_INTERVAL_SECS)

    def _notify_workers_host_changes(self, update_res: int) -> None:
        with self._lock:
            self._notify_clock += 1
            timestamp = self._notify_clock
        logger.info("host changes detected (res=%d, ts=%d); notifying "
                    "workers", update_res, timestamp)
        for key, client in list(self._workers.items()):
            try:
                client.call("notify_hosts_updated", timestamp, update_res)
            except Exception:  # noqa: BLE001 - worker may be gone
                self._workers.pop(key, None)
