"""Elastic launch: wires the rendezvous server, the RPC service and the
elastic driver to per-slot worker processes.

Reference: horovod/runner/gloo_run.py:287-323 launch_gloo_elastic.
"""
from __future__ import annotations

import os
import sys
from collections import OrderedDict

from ..common.logging import logger
from ..runner.hosts import SlotInfo, parse_hosts
from ..runner import safe_shell_exec
from .discovery import FixedHostDiscovery, HostDiscoveryScript
from .driver import ElasticDriver
from .rpc import SECRET_ENV, RpcServer, make_secret
from .worker import DRIVER_ADDR_ENV, DRIVER_PORT_ENV

from ..runner.hosts import is_local_host as _is_local  # noqa: E402


def _make_discovery(args):
    if getattr(args, "host_discovery_script", None):
        return HostDiscoveryScript(args.host_discovery_script,
                                   default_slots=getattr(args, "slots", None)
                                   or 1)
    hosts = getattr(args, "hosts", None)
    if not hosts:
        raise ValueError(
            "elastic run requires --host-discovery-script or -H/--hosts")
    fixed = OrderedDict((h.hostname, h.slots) for h in parse_hosts(hosts))
    return FixedHostDiscovery(fixed)


def _driver_address(discovery, network_interface: str | None = None) -> str:
    hosts = discovery.find_available_hosts_and_slots()
    if all(_is_local(h) for h in hosts):
        return "127.0.0.1"
    if network_interface:
        # --network-interface pins the advertised NIC on multi-NIC head
        # nodes (same contract as the static launch path).
        from ..runner.driver_service import candidate_addresses
        return candidate_addresses(network_interface)[0]
    import socket
    return socket.getfqdn()


def launch_elastic(args, command: list[str], *,
                   payload: bytes | None = None,
                   collect_results: bool = False,
                   extra_env: dict | None = None):
    """Drive an elastic world of `command` workers.

    With ``payload``/``collect_results`` (the programmatic
    ``run(func, min_np=...)`` path), the pickled function is seeded into
    the rendezvous KV for elastic_run_worker bootstraps to fetch, and the
    per-final-rank outcomes are read back before teardown; returns
    ``(rc, results, final_world_size)`` then, plain ``rc`` otherwise.
    ``extra_env`` adds user variables to every worker (the static path's
    ``env=`` contract)."""
    discovery = _make_discovery(args)
    secret = make_secret()

    min_np = args.min_np or args.num_proc or 1
    max_np = args.max_np
    driver = ElasticDriver(
        discovery, min_np=min_np, max_np=max_np,
        timeout=args.elastic_timeout if getattr(args, "elastic_timeout",
                                                None) is not None else 600.0,
        reset_limit=getattr(args, "reset_limit", None), secret=secret,
        verbose=bool(getattr(args, "verbose", False)))

    addr = _driver_address(discovery,
                           getattr(args, "network_interface", None))
    from ..runner.launch import start_rendezvous
    rendezvous_servers, addr_spec, rendezvous_port = \
        start_rendezvous(addr)
    rendezvous = rendezvous_servers[0]
    if payload is not None:
        from ..runner.elastic_run_worker import PAYLOAD_SCOPE
        rendezvous.put(PAYLOAD_SCOPE, "blob", payload)
    rpc = RpcServer(driver, secret)

    from ..runner.launch import args_to_env
    base_env = dict(os.environ)
    # Inherited world/round state (e.g. launching from inside a prior
    # worker) would make fresh workers wait for an epoch that never
    # forms or adopt a stale rank.
    for stale in ("HOROVOD_RENDEZVOUS_EPOCH", "HOROVOD_RANK",
                  "HOROVOD_SIZE", "HOROVOD_HOST_IDS"):
        base_env.pop(stale, None)
    base_env.update(extra_env or {})
    base_env.update(args_to_env(args))
    base_env.update({
        "HOROVOD_CONTROLLER": "tcp",
        "HOROVOD_GLOO_TIMEOUT_SECONDS":
            str(getattr(args, "start_timeout", None) or 30),
    })

    def create_worker(slot: SlotInfo) -> int:
        env = dict(base_env)
        env.update({
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_HOSTNAME": slot.hostname,
            "HOROVOD_LOCAL_RANK": str(slot.local_rank),
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": addr_spec,
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(rendezvous_port),
            DRIVER_ADDR_ENV: addr,
            DRIVER_PORT_ENV: str(rpc.port),
            SECRET_ENV: secret,
        })
        if _is_local(slot.hostname):
            return safe_shell_exec.execute(list(command), env=env,
                                           index=slot.rank)
        import shlex

        from ..runner.hosts import ssh_argv
        # The HMAC secret travels over ssh stdin (`read -r`), never argv —
        # argv is world-readable in the remote host's process list.
        exports = " ".join(
            f"{k}={shlex.quote(str(v))}" for k, v in env.items()
            if k.startswith("HOROVOD_") and k != SECRET_ENV)
        remote = " ".join(shlex.quote(c) for c in command)
        script = (f"read -r {SECRET_ENV} && export {SECRET_ENV} && "
                  f"env {exports} {remote}")
        return safe_shell_exec.execute(
            ssh_argv(slot.hostname, script), env=env, index=None,
            stdin_data=(secret + "\n").encode())

    def _done(rc: int):
        if not collect_results:
            return rc
        # Read per-final-rank outcomes BEFORE the rendezvous stops; keys
        # are epoch-qualified so a stale result from an earlier round's
        # incarnation of a rank is never misattributed to the final round
        # (it would otherwise defeat the caller's "ranks returned no
        # result" guard).  A result may legitimately sit one or more
        # epochs BEHIND the final round — a worker's success can race the
        # final round forming — so earlier epochs are accepted when the
        # publishing slot provably IS the final round's slot for that
        # rank and that slot's process exited cleanly.
        import pickle

        from ..runner.elastic_run_worker import RESULT_SCOPE
        world = driver.world_size()
        final_epoch = driver.current_epoch
        slots = driver.final_slots()
        exit_codes = {name: code
                      for name, (code, _) in driver.get_results().items()}
        fn_results = {}
        for rank in range(world):
            # Bounded lookback: the success-vs-round-formation race spans
            # adjacent rounds, and acceptance needs the final round's
            # exact slot anyway — scanning all history would make
            # teardown O(epochs x world) HTTP gets for ranks that died
            # without publishing.
            for epoch in range(final_epoch, max(final_epoch - 3, 0), -1):
                blob = rendezvous.get(RESULT_SCOPE, f"{epoch}:{rank}")
                if blob is None:
                    continue
                outcome, slot = pickle.loads(blob)
                if epoch == final_epoch or (
                        slot == slots.get(rank)
                        and exit_codes.get(slot, 1) == 0):
                    fn_results[rank] = outcome
                break   # nearer epochs take precedence; stop at first hit
        return rc, fn_results, world

    autoscaler = None
    try:
        try:
            driver.start(args.num_proc or min_np, create_worker)
            from ..common import config as _config
            if _config.AUTOSCALE.get():
                # Autoscale policy loop (statesync/autoscale.py): the
                # driver-side controller scrapes rank 0's metrics
                # endpoint and moves the target world size with
                # hysteresis; decisions are counters + flight events.
                from ..statesync.autoscale import (AutoscaleController,
                                                   AutoscalePolicy,
                                                   http_source)
                port = _config.METRICS_PORT.get()
                bind = _config.METRICS_BIND.get() or "127.0.0.1"
                if port > 0:
                    autoscaler = AutoscaleController(
                        driver, http_source(f"http://{bind}:{port}/"),
                        AutoscalePolicy(min_np, max_np or min_np * 4))
                    autoscaler.start()
                else:
                    logger.warning(
                        "HOROVOD_AUTOSCALE=1 needs HOROVOD_METRICS_PORT "
                        "(the controller scrapes rank 0's exposition "
                        "endpoint); autoscale disabled")
            driver.join()
            driver.wait_for_workers_exit()
        except (TimeoutError, ValueError) as exc:
            sys.stderr.write(f"horovodrun-tpu elastic: {exc}\n")
            return _done(1)
        finally:
            if autoscaler is not None:
                autoscaler.stop()
            driver.shutdown()
            rpc.close()

        if driver.reset_limit_exceeded:
            sys.stderr.write(
                "horovodrun-tpu elastic: reset limit exceeded\n")
            return _done(1)
        if driver.resume_failed:
            sys.stderr.write(
                "horovodrun-tpu elastic: job could not resume after "
                "failure (insufficient surviving slots)\n")
            return _done(1)
        results = driver.get_results()
        failures = [name for name, (code, _) in results.items()
                    if code != 0]
        if failures and len(failures) == len(results):
            logger.error("all workers failed: %s", ", ".join(failures))
            return _done(1)
        return _done(0)
    finally:
        for srv in rendezvous_servers:
            srv.stop()
