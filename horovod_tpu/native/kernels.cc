// Native data-plane kernels for horovod_tpu.
//
// TPU-native equivalent of the reference's C++ core hot paths:
//  - fused-buffer pack/unpack      (reference: horovod/common/ops/
//    collective_operations.cc MemcpyInFusionBuffer/MemcpyOutFusionBuffer
//    and ops/cuda/cuda_kernels.cu batched memcpy)
//  - buffer scaling                (reference: collective_operations.h:89-125
//    ScaleBuffer, incl. the fp16 AVX path — here fp16/bf16 via fp32 widening,
//    autovectorized by -O3 -march=native)
//  - ring allreduce over TCP fds   (reference: ops/gloo_operations.cc ring
//    allreduce; same reduce-scatter + allgather schedule as the Python
//    fallback in backend/tcp.py, byte-compatible wire layout)
//  - Adasum combine primitives     (reference: ops/adasum/adasum.h:38-552
//    per-layer dot products / norms and scale-insensitive combine)
//
// Exposed as a plain C ABI for ctypes (the reference loads its core the same
// way: horovod/common/basics.py ctypes.CDLL).
#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <memory>
#include <thread>
#include <vector>

#include <poll.h>
#include <sys/socket.h>
#include <sys/types.h>

extern "C" {

// ---------------------------------------------------------------------------
// Fusion buffer pack / unpack
// ---------------------------------------------------------------------------
void hvd_pack(const void** srcs, const int64_t* nbytes, int32_t n,
              char* dst) {
  int64_t offset = 0;
  for (int32_t i = 0; i < n; ++i) {
    if (srcs[i] != nullptr) {
      std::memcpy(dst + offset, srcs[i], (size_t)nbytes[i]);
    } else {
      std::memset(dst + offset, 0, (size_t)nbytes[i]);  // joined-rank zeros
    }
    offset += nbytes[i];
  }
}

void hvd_unpack(const char* src, const int64_t* nbytes, int32_t n,
                void** dsts) {
  int64_t offset = 0;
  for (int32_t i = 0; i < n; ++i) {
    std::memcpy(dsts[i], src + offset, (size_t)nbytes[i]);
    offset += nbytes[i];
  }
}

// ---------------------------------------------------------------------------
// Buffer scaling
// ---------------------------------------------------------------------------
void hvd_scale_f32(float* buf, int64_t n, float factor) {
  for (int64_t i = 0; i < n; ++i) buf[i] *= factor;
}

void hvd_scale_f64(double* buf, int64_t n, double factor) {
  for (int64_t i = 0; i < n; ++i) buf[i] *= factor;
}

// ---------------------------------------------------------------------------
// Socket helpers: exact-size send/recv that tolerate O_NONBLOCK fds
// (Python sockets with timeouts are non-blocking underneath).
// ---------------------------------------------------------------------------
static int poll_wait(int fd, short events) {
  struct pollfd p;
  p.fd = fd;
  p.events = events;
  for (;;) {
    int r = poll(&p, 1, 60000 /* ms */);
    if (r > 0) return 0;
    if (r == 0) return -1;              // timeout
    if (errno != EINTR) return -1;
  }
}

// Wire format: every message is a 4-byte big-endian length prefix followed
// by the payload — byte-identical to runner/network.py send_msg/recv_msg,
// so a rank on the native path interoperates with a rank on the Python
// fallback (mixed toolchains must not corrupt the ring).
static int send_exact(int fd, const char* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t w = send(fd, buf + off, n - off, MSG_NOSIGNAL);
    if (w > 0) {
      off += (size_t)w;
    } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      if (poll_wait(fd, POLLOUT) != 0) return -1;
    } else if (w < 0 && errno == EINTR) {
      continue;
    } else {
      return -1;
    }
  }
  return 0;
}

static int recv_exact(int fd, char* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t r = recv(fd, buf + off, n - off, 0);
    if (r > 0) {
      off += (size_t)r;
    } else if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      if (poll_wait(fd, POLLIN) != 0) return -1;
    } else if (r < 0 && errno == EINTR) {
      continue;
    } else {
      return -1;  // peer closed or hard error
    }
  }
  return 0;
}

}  // extern "C" (reopened below for the remaining entry points)

// ---------------------------------------------------------------------------
// Ring allreduce (sum) over raw fds
// ---------------------------------------------------------------------------
// dtype codes: 0=f32 1=f64 2=i32 3=i64
template <typename T>
static void add_into(T* dst, const T* src, int64_t n) {
  for (int64_t i = 0; i < n; ++i) dst[i] += src[i];
}

template <typename T>
static int ring_allreduce_t(int send_fd, int recv_fd, T* buf, int64_t n,
                            int rank, int size) {
  // Chunk layout identical to backend/tcp.py: first `rem` chunks get one
  // extra element.
  int64_t base = n / size, rem = n % size;
  std::vector<int64_t> bounds(size + 1, 0);
  for (int i = 0; i < size; ++i)
    bounds[i + 1] = bounds[i] + base + (i < rem ? 1 : 0);

  int64_t max_chunk = base + (rem ? 1 : 0);
  // Uninitialized staging (std::vector would memset a chunk-sized block
  // per op — 32 MB of pure overhead on a 64 MB payload).
  std::unique_ptr<T[]> incoming(new T[(size_t)max_chunk]);

  // Inline-send ceiling: the lesser of 64 KB and half the smaller actual
  // kernel buffer (the 4 MB SO_SNDBUF request in PeerMesh may have been
  // clamped by tcp_wmem); a blocking sendall below this bound cannot
  // deadlock the ring even when no peer is mid-recv.
  size_t inline_max = 64 * 1024;
  {
    int sb = 0, rb = 0;
    socklen_t sl = sizeof(sb);
    if (getsockopt(send_fd, SOL_SOCKET, SO_SNDBUF, &sb, &sl) == 0 &&
        getsockopt(recv_fd, SOL_SOCKET, SO_RCVBUF, &rb,
                   (sl = sizeof(rb), &sl)) == 0) {
      size_t floor_bytes = (size_t)(sb < rb ? sb : rb) / 2;
      if (floor_bytes < inline_max) inline_max = floor_bytes;
    }
  }

  // Reduce-scatter, then allgather.  Concurrent send/recv per step so the
  // ring cannot deadlock on filled socket buffers.
  for (int phase = 0; phase < 2; ++phase) {
    for (int step = 0; step < size - 1; ++step) {
      int send_idx = phase == 0 ? (rank - step) % size
                                : (rank + 1 - step) % size;
      int recv_idx = phase == 0 ? (rank - step - 1) % size
                                : (rank - step) % size;
      if (send_idx < 0) send_idx += size;
      if (recv_idx < 0) recv_idx += size;

      const char* send_ptr = (const char*)(buf + bounds[send_idx]);
      size_t send_bytes =
          (size_t)(bounds[send_idx + 1] - bounds[send_idx]) * sizeof(T);
      int64_t recv_elems = bounds[recv_idx + 1] - bounds[recv_idx];
      size_t recv_bytes = (size_t)recv_elems * sizeof(T);

      unsigned char send_hdr[4] = {
          (unsigned char)(send_bytes >> 24), (unsigned char)(send_bytes >> 16),
          (unsigned char)(send_bytes >> 8), (unsigned char)send_bytes};

      // Small chunks: sequential send-then-recv below the inline ceiling
      // (skipping the per-step std::thread saves ~0.5 ms/op, which
      // dominates small-tensor cached-cycle latency).  Large chunks keep
      // the concurrent sender thread so the ring cannot deadlock on
      // filled buffers.
      auto do_send = [&]() -> int {
        int rc = send_exact(send_fd, (const char*)send_hdr, 4);
        if (rc == 0) rc = send_exact(send_fd, send_ptr, send_bytes);
        return rc;
      };
      int send_rc_val = 0, recv_rc = -1;
      bool threaded = send_bytes > inline_max;
      std::thread sender;
      if (threaded) {
        // join() below synchronizes the plain write.
        sender = std::thread([&] { send_rc_val = do_send(); });
      } else {
        send_rc_val = do_send();
      }
      // Inline path: a dead link already failed the send — skip the recv
      // (its own 60 s poll timeout would double time-to-error).
      if (threaded || send_rc_val == 0) {
        unsigned char recv_hdr[4];
        recv_rc = recv_exact(recv_fd, (char*)recv_hdr, 4);
        if (recv_rc == 0) {
          size_t framed = ((size_t)recv_hdr[0] << 24) |
                          ((size_t)recv_hdr[1] << 16) |
                          ((size_t)recv_hdr[2] << 8) | (size_t)recv_hdr[3];
          if (framed != recv_bytes) {
            recv_rc = -1;  // peer desync: fail loudly, never misparse
          } else if (phase == 0) {
            // PIPELINED reduce: consume the incoming chunk in ~256 KB
            // segments, adding each into the accumulator while the NIC
            // (and the peer's sender) stream the next segment into the
            // kernel buffer — on a real network the adds ride entirely
            // inside the transfer time instead of serializing after it.
            constexpr size_t kSeg = 256 * 1024;
            T* dst = buf + bounds[recv_idx];
            size_t done = 0;
            recv_rc = 0;
            while (done < recv_bytes && recv_rc == 0) {
              size_t seg = recv_bytes - done;
              if (seg > kSeg) seg = kSeg;
              recv_rc = recv_exact(
                  recv_fd, (char*)incoming.get() + done, seg);
              if (recv_rc == 0) {
                add_into(dst + done / sizeof(T),
                         (const T*)((const char*)incoming.get() + done),
                         (int64_t)(seg / sizeof(T)));
                done += seg;
              }
            }
          } else {
            // Allgather phase: no compute to overlap; one bulk recv
            // straight into place (no staging copy).
            recv_rc = recv_exact(recv_fd, (char*)(buf + bounds[recv_idx]),
                                 recv_bytes);
          }
        }
      }
      if (threaded) sender.join();
      if (send_rc_val != 0 || recv_rc != 0) return -1;
    }
  }
  return 0;
}

extern "C" {

int32_t hvd_ring_allreduce(int32_t send_fd, int32_t recv_fd, void* buf,
                           int64_t n, int32_t dtype, int32_t rank,
                           int32_t size) {
  if (size <= 1) return 0;
  switch (dtype) {
    case 0: return ring_allreduce_t(send_fd, recv_fd, (float*)buf, n, rank, size);
    case 1: return ring_allreduce_t(send_fd, recv_fd, (double*)buf, n, rank, size);
    case 2: return ring_allreduce_t(send_fd, recv_fd, (int32_t*)buf, n, rank, size);
    case 3: return ring_allreduce_t(send_fd, recv_fd, (int64_t*)buf, n, rank, size);
    default: return -2;
  }
}

// ---------------------------------------------------------------------------
// Fused codec kernels (compress/fused.py native half; EQuARX-style
// blockwise affine quantization, arXiv:2506.17615 + arXiv:2305.06942).
//
// THE single-pass computation-collective kernels: hvd_qdecode with
// accumulate=1 consumes an arriving wire segment and updates the fp32
// accumulator in place — dequantize and reduce in ONE loop over the
// payload — and hvd_qencode requantizes an accumulator straight into a
// contiguous wire image (scales || zero_points || payload, the exact
// compress/quantize.py layout).
//
// Bit-exactness contract with the numpy reference (compress/quantize.py):
// identical IEEE fp32 operations in identical order — subtract, divide,
// rintf (round-half-even, = np.rint), clip, truncating uint8 cast on the
// way in; multiply, add, accumulate-add on the way out.  The build passes
// -ffp-contract=off so the compiler cannot fuse the q*scale+zp
// multiply-add into an FMA (numpy rounds between the two ops; an FMA
// would not).  Tail blocks follow the same pad rule (padding repeats the
// block's own last element, so min/max are unchanged and only `count`
// real elements are coded); odd-length uint4 payloads zero the pad
// nibble, byte-identical to the numpy packer.
// ---------------------------------------------------------------------------
extern "C" {

int32_t hvd_qencode(const float* x, int64_t n, int32_t block_size,
                    int32_t levels, int32_t pack4, uint8_t* wire) {
  if (n <= 0 || block_size <= 0) return 0;
  int64_t nb = (n + block_size - 1) / block_size;
  uint8_t* sp = wire;                 // per-block scales   (fp32)
  uint8_t* zpp = wire + nb * 4;       // per-block zero pts (fp32)
  uint8_t* pl = wire + nb * 8;        // packed levels
  const float maxq = (float)(levels - 1);
  for (int64_t b = 0; b < nb; ++b) {
    int64_t start = b * block_size;
    int64_t count = n - start;
    if (count > block_size) count = block_size;
    float lo = x[start], hi = x[start];
    for (int64_t i = 1; i < count; ++i) {
      float v = x[start + i];
      if (v < lo) lo = v;
      if (v > hi) hi = v;
    }
    float scale = (hi - lo) / maxq;
    if (!(scale > 0.0f)) scale = 1.0f;   // flat (or NaN) block
    std::memcpy(sp + b * 4, &scale, 4);
    std::memcpy(zpp + b * 4, &lo, 4);
    if (!pack4) {
      for (int64_t i = 0; i < count; ++i) {
        float q = rintf((x[start + i] - lo) / scale);
        if (q < 0.0f) q = 0.0f;
        else if (q > maxq) q = maxq;
        pl[start + i] = (uint8_t)q;
      }
    } else {
      // block_size is even by config validation, so nibble pairs never
      // straddle blocks; an odd GLOBAL tail zeroes its pad nibble.
      int64_t i = 0;
      for (; i + 1 < count; i += 2) {
        float qa = rintf((x[start + i] - lo) / scale);
        float qb = rintf((x[start + i + 1] - lo) / scale);
        if (qa < 0.0f) qa = 0.0f; else if (qa > maxq) qa = maxq;
        if (qb < 0.0f) qb = 0.0f; else if (qb > maxq) qb = maxq;
        pl[(start + i) >> 1] =
            (uint8_t)(((uint8_t)qa << 4) | (uint8_t)qb);
      }
      if (i < count) {
        float qa = rintf((x[start + i] - lo) / scale);
        if (qa < 0.0f) qa = 0.0f; else if (qa > maxq) qa = maxq;
        pl[(start + i) >> 1] = (uint8_t)((uint8_t)qa << 4);
      }
    }
  }
  return 0;
}

int32_t hvd_qdecode(const uint8_t* wire, int64_t n, int32_t block_size,
                    int32_t pack4, float* dst, int32_t accumulate) {
  if (n <= 0 || block_size <= 0) return 0;
  int64_t nb = (n + block_size - 1) / block_size;
  const uint8_t* sp = wire;
  const uint8_t* zpp = wire + nb * 4;
  const uint8_t* pl = wire + nb * 8;
  for (int64_t b = 0; b < nb; ++b) {
    int64_t start = b * block_size;
    int64_t count = n - start;
    if (count > block_size) count = block_size;
    float scale, zp;
    std::memcpy(&scale, sp + b * 4, 4);   // wire may be unaligned (shm
    std::memcpy(&zp, zpp + b * 4, 4);     // regions slice at odd offsets)
    if (accumulate) {
      for (int64_t i = 0; i < count; ++i) {
        int64_t g = start + i;
        uint8_t q = pack4 ? (uint8_t)((g & 1) ? pl[g >> 1] & 0x0F
                                              : pl[g >> 1] >> 4)
                          : pl[g];
        float v = (float)q * scale;       // separate mul + add: numpy
        v = v + zp;                       // rounds between them (no FMA)
        dst[g] += v;
      }
    } else {
      for (int64_t i = 0; i < count; ++i) {
        int64_t g = start + i;
        uint8_t q = pack4 ? (uint8_t)((g & 1) ? pl[g >> 1] & 0x0F
                                              : pl[g >> 1] >> 4)
                          : pl[g];
        float v = (float)q * scale;
        v = v + zp;
        dst[g] = v;
      }
    }
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Adasum primitives (reference: ops/adasum/adasum.h ComputeDotAndNormSqrds
// and ScaledAdd — the per-layer statistics and the scale-insensitive combine)
// ---------------------------------------------------------------------------
void hvd_dot_norms_f64(const double* a, const double* b, int64_t n,
                       double* out3 /* dot, normsq_a, normsq_b */) {
  double dot = 0, na = 0, nb = 0;
  for (int64_t i = 0; i < n; ++i) {
    dot += a[i] * b[i];
    na += a[i] * a[i];
    nb += b[i] * b[i];
  }
  out3[0] = dot;
  out3[1] = na;
  out3[2] = nb;
}

void hvd_scaled_add_f64(double* a, const double* b, int64_t n,
                        double ca, double cb) {
  for (int64_t i = 0; i < n; ++i) a[i] = ca * a[i] + cb * b[i];
}

int32_t hvd_abi_version(void) { return 1; }

}  // extern "C"
