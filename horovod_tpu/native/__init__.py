"""Native (C++) data-plane kernels, loaded via ctypes.

The reference ships a ~17k-LoC C++ core loaded through ctypes
(reference: horovod/common/basics.py:25-31); this package is its TPU-native
counterpart for the paths that stay on the host CPU: fusion-buffer
pack/unpack, buffer scaling, the TCP ring allreduce, and Adasum combine
primitives.  The XLA/Pallas compute path needs no host kernels — these only
serve the eager multi-process API.

Build model: kernels.cc is compiled once per machine with g++ -O3
-march=native into a cache directory at first import; every entry point has
a pure-Python fallback, so a missing/failed toolchain degrades performance,
never correctness.  Set HOROVOD_TPU_DISABLE_NATIVE=1 to force the fallback.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "kernels.cc")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_DTYPE_CODES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
}


def _cache_dir() -> str:
    root = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    path = os.path.join(root, "horovod_tpu")
    os.makedirs(path, exist_ok=True)
    return path


def _cpu_tag() -> str:
    """CPU-generation fingerprint: -march=native code must never be loaded
    on a different microarchitecture (shared NFS caches across
    heterogeneous hosts would SIGILL otherwise)."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("model name", "flags")):
                    return hashlib.sha256(
                        line.encode()).hexdigest()[:8]
    except OSError:
        pass
    import platform
    return hashlib.sha256(platform.processor().encode()).hexdigest()[:8]


def _build() -> str | None:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so_path = os.path.join(_cache_dir(),
                           f"hvd_native_{digest}_{_cpu_tag()}.so")
    if os.path.exists(so_path):
        return so_path
    tmp = tempfile.mktemp(suffix=".so", dir=_cache_dir())
    # -ffp-contract=off: the fused codec kernels must round between the
    # q*scale multiply and the +zero_point add exactly like numpy does —
    # an FMA contraction would break their bitwise-parity contract with
    # compress/quantize.py (tests/test_fused.py pins it).
    cmd = ["g++", "-O3", "-march=native", "-ffp-contract=off", "-shared",
           "-fPIC", "-std=c++17", "-pthread", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)   # atomic: concurrent builders race safely
        return so_path
    except (subprocess.SubprocessError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("HOROVOD_TPU_DISABLE_NATIVE", "") in ("1", "true"):
            return None
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.hvd_abi_version.restype = ctypes.c_int32
            if lib.hvd_abi_version() != 1:
                return None
            lib.hvd_pack.argtypes = [ctypes.POINTER(ctypes.c_void_p),
                                     ctypes.POINTER(ctypes.c_int64),
                                     ctypes.c_int32, ctypes.c_char_p]
            lib.hvd_unpack.argtypes = [ctypes.c_char_p,
                                       ctypes.POINTER(ctypes.c_int64),
                                       ctypes.c_int32,
                                       ctypes.POINTER(ctypes.c_void_p)]
            lib.hvd_ring_allreduce.argtypes = [
                ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32]
            lib.hvd_ring_allreduce.restype = ctypes.c_int32
            lib.hvd_scale_f32.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                          ctypes.c_float]
            lib.hvd_scale_f64.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                          ctypes.c_double]
            lib.hvd_qencode.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p]
            lib.hvd_qencode.restype = ctypes.c_int32
            lib.hvd_qdecode.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_int32]
            lib.hvd_qdecode.restype = ctypes.c_int32
            _lib = lib
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def ring_allreduce(send_fd: int, recv_fd: int, buf: np.ndarray,
                   rank: int, size: int) -> bool:
    """In-place sum ring allreduce over raw socket fds.  Returns False when
    the native path cannot handle this dtype (caller falls back)."""
    lib = _load()
    code = _DTYPE_CODES.get(buf.dtype)
    if lib is None or code is None or not buf.flags.c_contiguous:
        return False
    rc = lib.hvd_ring_allreduce(
        send_fd, recv_fd, buf.ctypes.data_as(ctypes.c_void_p),
        buf.size, code, rank, size)
    if rc == -1:
        raise ConnectionError("native ring allreduce: peer socket failed")
    return rc == 0


def qencode(x: np.ndarray, block_size: int, levels: int, pack4: bool,
            wire: np.ndarray) -> bool:
    """Single-pass blockwise quantize of contiguous fp32 ``x`` straight
    into the wire image ``scales || zero_points || payload`` (the
    compress/quantize.py layout, byte-identical).  Returns False when the
    native library is unavailable (caller falls back to numpy)."""
    lib = _load()
    if lib is None:
        return False
    lib.hvd_qencode(x.ctypes.data_as(ctypes.c_void_p), x.size,
                    block_size, levels, 1 if pack4 else 0,
                    wire.ctypes.data_as(ctypes.c_void_p))
    return True


def qdecode(wire: np.ndarray, n: int, block_size: int, pack4: bool,
            dst: np.ndarray, accumulate: bool) -> bool:
    """Single-pass fused dequantize of a wire image into contiguous fp32
    ``dst`` — with ``accumulate`` the kernel performs
    ``dst += q·scale + zp`` in ONE loop over the payload (the fused
    computation-collective inner loop).  Returns False when the native
    library is unavailable."""
    lib = _load()
    if lib is None:
        return False
    lib.hvd_qdecode(wire.ctypes.data_as(ctypes.c_void_p), n, block_size,
                    1 if pack4 else 0,
                    dst.ctypes.data_as(ctypes.c_void_p),
                    1 if accumulate else 0)
    return True


def pack(parts: list[np.ndarray | None], sizes: list[int],
         dtype: np.dtype, out: np.ndarray | None = None
         ) -> np.ndarray | None:
    """Concatenate flattened arrays (None → zeros) into one fused buffer.
    ``out``, when given, is the persistent staging buffer to fill
    (reference: fusion_buffer_manager.cc reuse)."""
    lib = _load()
    if lib is None:
        return None
    dtype = np.dtype(dtype)
    # A desync between the response's tensor_sizes and the staged arrays
    # would read out-of-bounds memory through the raw pointers below (the
    # numpy fallback raises instead) — validate, fall back on mismatch.
    for p, sz in zip(parts, sizes):
        if p is not None and (p.size != sz or p.dtype != dtype
                              or not p.flags.c_contiguous):
            return None
    total = sum(sizes)
    if out is None:
        out = np.empty(total, dtype=dtype)
    elif (out.size != total or out.dtype != dtype
          or not out.flags.c_contiguous):
        return None
    n = len(parts)
    src_ptrs = (ctypes.c_void_p * n)()
    nbytes = (ctypes.c_int64 * n)()
    for i, (p, sz) in enumerate(zip(parts, sizes)):
        nbytes[i] = sz * dtype.itemsize
        src_ptrs[i] = None if p is None else p.ctypes.data_as(
            ctypes.c_void_p).value
    lib.hvd_pack(src_ptrs, nbytes, n,
                 out.ctypes.data_as(ctypes.c_char_p))
    return out
