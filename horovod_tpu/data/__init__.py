"""Data-loading utilities: sharded, prefetching input pipelines.

Reference: horovod/data/data_loader_base.py (AsyncDataLoaderMixin prefetch
thread) and horovod/spark/data_loaders/pytorch_data_loaders.py.  TPU-native
additions: device prefetch that overlaps host→HBM transfer with the current
step, and mesh-aware batch sharding.
"""
from .loader import (AsyncDataLoaderMixin, BaseDataLoader,
                     ShardedBatchLoader, StoreShardReader,
                     prefetch_to_device, write_dataset_shards)

__all__ = ["BaseDataLoader", "AsyncDataLoaderMixin", "ShardedBatchLoader",
           "StoreShardReader", "write_dataset_shards",
           "prefetch_to_device"]
