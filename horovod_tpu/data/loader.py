"""Sharded + prefetching data loaders.

Reference: horovod/data/data_loader_base.py — ``BaseDataLoader`` is the
iterator contract, ``AsyncDataLoaderMixin`` moves batch production onto a
background thread with a bounded queue.  ``prefetch_to_device`` is the
TPU-specific piece: it pushes upcoming batches to device HBM (with the
mesh sharding applied) while the current step runs, hiding host→device
latency — the role the reference's pinned-memory loaders play for GPUs.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Sequence

import numpy as np


class BaseDataLoader:
    """Iterator contract (reference: data_loader_base.py BaseDataLoader)."""

    def __len__(self) -> int:
        raise NotImplementedError

    def _iterate(self) -> Iterator[Any]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[Any]:
        return self._iterate()


class AsyncDataLoaderMixin:
    """Prefetch batches on a background thread
    (reference: data_loader_base.py:48-130 AsyncDataLoaderMixin).

    Mix in BEFORE the loader class::

        class AsyncLoader(AsyncDataLoaderMixin, MyLoader): ...
    """

    def __init__(self, *args, async_loader_queue_size: int = 4,
                 **kwargs) -> None:
        self.async_loader_queue_size = async_loader_queue_size
        super().__init__(*args, **kwargs)

    def _iterate(self) -> Iterator[Any]:
        if self.async_loader_queue_size <= 0:
            yield from super()._iterate()
            return
        q: "queue.Queue" = queue.Queue(maxsize=self.async_loader_queue_size)
        done = object()
        stop = threading.Event()
        err: list[BaseException] = []

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer() -> None:
            try:
                for item in super(AsyncDataLoaderMixin, self)._iterate():
                    if not _put(item):
                        return     # consumer abandoned the iterator
            except BaseException as e:  # noqa: BLE001 - re-raised in consumer
                err.append(e)
            finally:
                _put(done)

        thread = threading.Thread(target=producer, daemon=True,
                                  name="hvd-data-prefetch")
        thread.start()
        try:
            while True:
                item = q.get()
                if item is done:
                    break
                yield item
        finally:
            # Early exit (break in the consumer loop): unblock and retire
            # the producer instead of leaking one thread per epoch.
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            thread.join(timeout=5)
        if err:
            raise err[0]


def _replica_indices(n: int, rank: int, num_replicas: int,
                     shuffle: bool, rng) -> np.ndarray:
    """This rank's row indices, PADDED so every rank gets exactly
    ceil(n/num_replicas) rows (indices wrap — the torch
    DistributedSampler contract).  Equal per-rank row counts are what
    keep per-rank step counts aligned: a rank with one extra batch would
    block forever in its collective."""
    idx = np.arange(n)
    if shuffle:
        rng.shuffle(idx)
    per = -(-n // num_replicas)
    total = per * num_replicas
    if total > n:
        idx = np.concatenate([idx, idx[:total - n]])
    return idx[rank::num_replicas]


def _batch_count(rows: int, batch_size: int, drop_last: bool) -> int:
    if drop_last:
        return rows // batch_size
    return -(-rows // batch_size)


def _iter_batches(idx: np.ndarray, batch_size: int,
                  drop_last: bool) -> Iterator[np.ndarray]:
    stop = len(idx) - (len(idx) % batch_size) if drop_last else len(idx)
    for start in range(0, stop, batch_size):
        yield idx[start:start + batch_size]


class ShardedBatchLoader(BaseDataLoader):
    """Batches a numpy dataset dict, sharded by rank (eager API) or whole
    (SPMD API where the mesh shards the global batch).

    ``data``: dict of equal-first-dim numpy arrays, e.g. {"image":…,
    "label":…}.  With ``rank``/``num_replicas`` each process sees its
    padded strided shard — the reference's DistributedSampler contract
    (wrapped indices keep per-rank step counts identical).
    """

    def __init__(self, data: dict[str, np.ndarray], batch_size: int,
                 shuffle: bool = True, seed: int = 0, drop_last: bool = True,
                 rank: int = 0, num_replicas: int = 1) -> None:
        self.data = data
        first = next(iter(data.values()))
        self.n = int(first.shape[0])
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.rank = rank
        self.num_replicas = num_replicas
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        per_rank = -(-self.n // self.num_replicas)   # padded: rank-uniform
        return _batch_count(per_rank, self.batch_size, self.drop_last)

    def _iterate(self) -> Iterator[dict[str, np.ndarray]]:
        rng = np.random.default_rng(self.seed + self.epoch)
        idx = _replica_indices(self.n, self.rank, self.num_replicas,
                               self.shuffle, rng)
        for sel in _iter_batches(idx, self.batch_size, self.drop_last):
            yield {k: v[sel] for k, v in self.data.items()}


def prefetch_to_device(iterator: Iterable[dict], size: int = 2,
                       sharding: Any | None = None) -> Iterator[dict]:
    """Device-prefetch pipeline: keep ``size`` batches in flight on the
    accelerator so the input pipeline overlaps the training step.

    ``sharding``: optional `jax.sharding.Sharding` (or pytree of shardings)
    applied on transfer — the global-batch layout over the mesh.
    """
    import jax

    buf: "queue.Queue" = queue.Queue()
    it = iter(iterator)

    def _put(batch: dict) -> None:
        if sharding is not None:
            batch = jax.device_put(batch, sharding)
        else:
            batch = jax.device_put(batch)
        buf.put(batch)

    # Prime the pipeline.
    primed = 0
    for _ in range(size):
        try:
            _put(next(it))
            primed += 1
        except StopIteration:
            break

    while primed:
        out = buf.get()
        primed -= 1
        try:
            _put(next(it))
            primed += 1
        except StopIteration:
            pass
        yield out


# ---------------------------------------------------------------------------
# Store-backed shard reader (the petastorm-reader slot)
# ---------------------------------------------------------------------------
def write_dataset_shards(store, base_path: str,
                         data: dict[str, np.ndarray],
                         num_shards: int = 8) -> list[str]:
    """Split a dataset dict into ``num_shards`` npz shards behind a Store
    (reference analogue: materializing the DataFrame to parquet row
    groups, spark/common/util.py); returns the shard keys in order."""
    n = int(next(iter(data.values())).shape[0])
    bounds = np.linspace(0, n, num_shards + 1).astype(int)
    keys = []
    for s in range(num_shards):
        lo, hi = bounds[s], bounds[s + 1]
        if lo == hi:
            continue
        key = store.join(base_path, f"shard_{s:05d}.npz")
        store.save_npz(key, **{k: v[lo:hi] for k, v in data.items()})
        keys.append(key)
    return keys


class StoreShardReader(BaseDataLoader):
    """Streams a dataset living as npz shards behind a :class:`Store`
    (filesystem or network blob) — the petastorm-backed loader's slot
    (reference: spark/data_loaders/pytorch_data_loaders.py over
    spark/common/store.py).

    One shard is resident at a time (the row-group memory contract:
    O(shard), not O(dataset)); shard ORDER shuffles per epoch, rows
    within each shard are padded-strided across ranks (the same wrapped
    DistributedSampler contract as ShardedBatchLoader — every rank gets
    identical step counts, the collective-lockstep requirement), and rows
    shuffle within the shard.  ``drop_last`` defaults True so SPMD mesh
    feeding never sees ragged tail batches.  Compose with
    ``AsyncDataLoaderMixin`` for background prefetch."""

    def __init__(self, store, shard_keys: Sequence[str], batch_size: int,
                 shuffle: bool = True, seed: int = 0,
                 rank: int = 0, num_replicas: int = 1,
                 drop_last: bool = True,
                 shard_rows: Sequence[int] | None = None) -> None:
        self.store = store
        self.shard_keys = list(shard_keys)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.rank = rank
        self.num_replicas = num_replicas
        self.drop_last = drop_last
        self.epoch = 0
        self._shard_rows: list[int] | None = \
            list(shard_rows) if shard_rows is not None else None

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def _rows(self) -> list[int]:
        if self._shard_rows is None:
            # One pass over the shards (loads each blob once; pass
            # shard_rows to the constructor to avoid it on remote stores).
            self._shard_rows = [
                int(next(iter(blob[k] for k in blob.files)).shape[0])
                for blob in (self.store.load_npz(key)
                             for key in self.shard_keys)]
        return self._shard_rows

    def __len__(self) -> int:
        return sum(
            _batch_count(-(-rows // self.num_replicas), self.batch_size,
                         self.drop_last)
            for rows in self._rows())

    def _iterate(self) -> Iterator[dict[str, np.ndarray]]:
        order = np.arange(len(self.shard_keys))
        rng = np.random.default_rng(self.seed + self.epoch)
        if self.shuffle:
            rng.shuffle(order)
        for si in order:
            blob = self.store.load_npz(self.shard_keys[si])
            arrays = {k: blob[k] for k in blob.files}
            n = int(next(iter(arrays.values())).shape[0])
            idx = _replica_indices(n, self.rank, self.num_replicas,
                                   self.shuffle, rng)
            for sel in _iter_batches(idx, self.batch_size,
                                     self.drop_last):
                yield {k: v[sel] for k, v in arrays.items()}
