"""Sharded + prefetching data loaders.

Reference: horovod/data/data_loader_base.py — ``BaseDataLoader`` is the
iterator contract, ``AsyncDataLoaderMixin`` moves batch production onto a
background thread with a bounded queue.  ``prefetch_to_device`` is the
TPU-specific piece: it pushes upcoming batches to device HBM (with the
mesh sharding applied) while the current step runs, hiding host→device
latency — the role the reference's pinned-memory loaders play for GPUs.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Sequence

import numpy as np


class BaseDataLoader:
    """Iterator contract (reference: data_loader_base.py BaseDataLoader)."""

    def __len__(self) -> int:
        raise NotImplementedError

    def _iterate(self) -> Iterator[Any]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[Any]:
        return self._iterate()


class AsyncDataLoaderMixin:
    """Prefetch batches on a background thread
    (reference: data_loader_base.py:48-130 AsyncDataLoaderMixin).

    Mix in BEFORE the loader class::

        class AsyncLoader(AsyncDataLoaderMixin, MyLoader): ...
    """

    def __init__(self, *args, async_loader_queue_size: int = 4,
                 **kwargs) -> None:
        self.async_loader_queue_size = async_loader_queue_size
        super().__init__(*args, **kwargs)

    def _iterate(self) -> Iterator[Any]:
        if self.async_loader_queue_size <= 0:
            yield from super()._iterate()
            return
        q: "queue.Queue" = queue.Queue(maxsize=self.async_loader_queue_size)
        done = object()
        stop = threading.Event()
        err: list[BaseException] = []

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer() -> None:
            try:
                for item in super(AsyncDataLoaderMixin, self)._iterate():
                    if not _put(item):
                        return     # consumer abandoned the iterator
            except BaseException as e:  # noqa: BLE001 - re-raised in consumer
                err.append(e)
            finally:
                _put(done)

        thread = threading.Thread(target=producer, daemon=True,
                                  name="hvd-data-prefetch")
        thread.start()
        try:
            while True:
                item = q.get()
                if item is done:
                    break
                yield item
        finally:
            # Early exit (break in the consumer loop): unblock and retire
            # the producer instead of leaking one thread per epoch.
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            thread.join(timeout=5)
        if err:
            raise err[0]


class ShardedBatchLoader(BaseDataLoader):
    """Batches a numpy dataset dict, sharded by rank (eager API) or whole
    (SPMD API where the mesh shards the global batch).

    ``data``: dict of equal-first-dim numpy arrays, e.g. {"image":…,
    "label":…}.  With ``rank``/``num_replicas`` each process sees its strided
    shard — the reference's DistributedSampler contract.
    """

    def __init__(self, data: dict[str, np.ndarray], batch_size: int,
                 shuffle: bool = True, seed: int = 0, drop_last: bool = True,
                 rank: int = 0, num_replicas: int = 1) -> None:
        self.data = data
        first = next(iter(data.values()))
        self.n = int(first.shape[0])
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.rank = rank
        self.num_replicas = num_replicas
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        # Strided shard size: rank r gets ceil((n - r) / num_replicas)
        # elements — must agree exactly with _iterate's idx[rank::replicas].
        per_rank = (self.n - self.rank + self.num_replicas - 1) \
            // self.num_replicas
        if self.drop_last:
            return per_rank // self.batch_size
        return (per_rank + self.batch_size - 1) // self.batch_size

    def _iterate(self) -> Iterator[dict[str, np.ndarray]]:
        idx = np.arange(self.n)
        if self.shuffle:
            np.random.default_rng(self.seed + self.epoch).shuffle(idx)
        idx = idx[self.rank::self.num_replicas]
        stop = len(idx) - (len(idx) % self.batch_size) if self.drop_last \
            else len(idx)
        for start in range(0, stop, self.batch_size):
            sel = idx[start:start + self.batch_size]
            yield {k: v[sel] for k, v in self.data.items()}


def prefetch_to_device(iterator: Iterable[dict], size: int = 2,
                       sharding: Any | None = None) -> Iterator[dict]:
    """Device-prefetch pipeline: keep ``size`` batches in flight on the
    accelerator so the input pipeline overlaps the training step.

    ``sharding``: optional `jax.sharding.Sharding` (or pytree of shardings)
    applied on transfer — the global-batch layout over the mesh.
    """
    import jax

    buf: "queue.Queue" = queue.Queue()
    it = iter(iterator)

    def _put(batch: dict) -> None:
        if sharding is not None:
            batch = jax.device_put(batch, sharding)
        else:
            batch = jax.device_put(batch)
        buf.put(batch)

    # Prime the pipeline.
    primed = 0
    for _ in range(size):
        try:
            _put(next(it))
            primed += 1
        except StopIteration:
            break

    while primed:
        out = buf.get()
        primed -= 1
        try:
            _put(next(it))
            primed += 1
        except StopIteration:
            pass
        yield out
