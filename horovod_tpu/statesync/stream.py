"""Peer-to-peer state streaming over PR 3's persistent duplex channels.

One join event gets one dedicated ``PeerMesh`` (scope
``sssync.<epoch>.<join id>``): donors are the current world's ranks
0..N-1, the joiner is mesh rank N.  The data/ctrl meshes never carry a
state byte — a donor's main thread keeps training while its
:class:`DonorServer` thread serves the frozen snapshot.

Pull protocol (all frames are the ``tcp_transport`` state verb —
``STATE_MAGIC`` framed, never interleavable with control frames):

1. joiner → every donor: ``HELLO {join, round}``;
2. donor → joiner: ``META {epoch, step, digest, nbytes, donor}`` — the
   snapshot stamp.  The joiner REJECTS the round unless every donor's
   stamp is identical (a torn snapshot: donors cut at different steps);
3. joiner → donor: ``REQ {o, n}`` for this donor's byte range — ranges
   partition ``[0, nbytes)`` disjointly across donors, so each donor
   streams a disjoint shard of the image;
4. donor → joiner: ``DATA {o, n, crc}`` chunks
   (``HOROVOD_STATESYNC_CHUNK_BYTES`` each, CRC-checked on arrival,
   independently addressed so a transfer resumes at chunk granularity),
   then ``END {o, n}``;
5. when a donor dies mid-stream, its unfinished tail is re-requested
   from the surviving donors (any donor can serve any range — the
   snapshot is replicated state);
6. joiner → donors: ``BYE`` once the assembled image digest-verifies.

Every blocking wait on the sync mesh is bounded by a
:class:`StreamGuard` (the round deadline), never by the process
ResilienceState — the sync mesh's peer indices are not world ranks, so
feeding its failures into the liveness table would blame innocents.
"""
from __future__ import annotations

import queue
import threading
import time
import zlib

from ..common import config
from ..common.logging import logger
from ..common.tcp_transport import (STATE_BYE, STATE_DATA, STATE_END,
                                    STATE_HELLO, STATE_META, STATE_REQ,
                                    pack_state_frame, unpack_state_frame)
from .snapshot import Snapshot, SnapshotStamp, state_digest

__all__ = ["DonorLostError", "DonorServer", "JoinerPuller", "StreamGuard",
           "StreamError", "TornSnapshotError", "sync_scope"]


def sync_scope(epoch: str, join_id: int) -> str:
    """The dedicated mesh scope of one join event's streaming channels."""
    return f"sssync.{epoch}.{join_id}"


class StreamError(RuntimeError):
    """A streaming round failed (deadline, torn stamp, bad digest)."""


class TornSnapshotError(StreamError):
    """Donors disagree on the snapshot stamp, or the assembled image
    does not reproduce the stamped digest."""


class DonorLostError(StreamError):
    """The channel to one donor died mid-round; the caller reassigns
    the donor's unfinished range to the survivors."""

    def __init__(self, peer: int, detail: str) -> None:
        super().__init__(f"donor {peer} lost mid-stream: {detail}")
        self.peer = peer


class StreamGuard:
    """Deadline policy for sync-mesh channel waits (duck-typed stand-in
    for the ResilienceState a PeerMesh normally captures): every recv or
    wedged send polls in short slices and aborts at the round deadline;
    a closed socket converts to :class:`DonorLostError` immediately."""

    def __init__(self, timeout: float) -> None:
        self.timeout = float(timeout)
        self.poll_interval = min(0.25, max(0.05, self.timeout / 40.0))

    def check(self, peer: int, waited: float, phase: str) -> None:
        if waited >= self.timeout:
            raise DonorLostError(
                peer, f"no bytes for {waited:.1f}s (> "
                      f"HOROVOD_STATESYNC_TIMEOUT_SECONDS="
                      f"{self.timeout:g}s) in {phase}")

    def peer_connection_lost(self, peer: int, phase: str,
                             detail: str) -> "DonorLostError":
        return DonorLostError(peer, f"{detail} ({phase})")


def _record_reject(name: str, detail: str) -> None:
    """A rejected round is a protocol transition (spec tid
    ``join.torn-reject``/``join.crc-reject``/``join.digest-reject``):
    it rides the flight ring so the hvdmc trace witness can replay it."""
    from ..telemetry import flight

    rec = flight.recorder()
    if rec.enabled:
        rec.record("torn-reject", name, detail=detail[:160])


def _statesync_bytes_counter(role: str):
    from ..telemetry import metrics

    return metrics().counter(
        "horovod_statesync_bytes_total",
        "State-snapshot payload bytes streamed between live peers, by "
        "role (donor = served, joiner = received and CRC-verified)",
        labels={"role": role})


class DonorServer(threading.Thread):
    """One incumbent's donor half for one join event.

    Runs as a daemon thread: forms the sync mesh (a collective act —
    every incumbent's donor thread plus the joiner), then answers the
    joiner's frames until BYE or the round deadline.  Snapshots arrive
    through :meth:`offer_snapshot` — round 0 is the bulk image taken
    when the join was first admitted, round 1 (optional) the final
    image taken at the grow boundary, streamed while the main thread is
    rebuilding channels anyway."""

    def __init__(self, kv, scope: str, donor_rank: int, num_donors: int,
                 *, chunk_bytes: int | None = None,
                 timeout: float | None = None) -> None:
        super().__init__(daemon=True,
                         name=f"hvd-statesync-donor-{donor_rank}")
        self.kv = kv
        self.scope = scope
        self.donor_rank = donor_rank
        self.num_donors = num_donors
        self.chunk_bytes = chunk_bytes or \
            config.STATESYNC_CHUNK_BYTES.get()
        self.timeout = timeout or config.STATESYNC_TIMEOUT_SECONDS.get()
        self._snapshots: queue.Queue = queue.Queue(maxsize=4)
        self.bytes_served = 0
        self.error: BaseException | None = None

    def offer_snapshot(self, round_idx: int, snap: Snapshot) -> None:
        self._snapshots.put((round_idx, snap), timeout=self.timeout)

    # -- thread body -----------------------------------------------------
    def run(self) -> None:
        try:
            self._serve()
        except StreamError as exc:
            # Joiner death / deadline: stand down quietly — the main
            # thread's world was never blocked on this transfer.
            logger.warning("statesync: donor %d round abandoned: %s",
                           self.donor_rank, exc)
            self.error = exc
        except Exception as exc:  # noqa: BLE001 - donor must never raise
            logger.warning("statesync: donor %d failed: %s",
                           self.donor_rank, exc)
            self.error = exc

    def _serve(self) -> None:
        from ..runner.network import PeerMesh

        guard = StreamGuard(self.timeout)
        counter = _statesync_bytes_counter("donor")
        mesh = PeerMesh(self.donor_rank, self.num_donors + 1, self.kv,
                        scope=self.scope, timeout=self.timeout,
                        resilience=guard)
        joiner = self.num_donors
        snap: Snapshot | None = None
        snap_round = -1
        try:
            while True:
                kind, meta, payload = unpack_state_frame(
                    mesh.recv(joiner))
                if kind == STATE_HELLO:
                    want = int(meta.get("round", 0))
                    while snap_round < want:
                        snap_round, snap = self._snapshots.get(
                            timeout=self.timeout)
                    mesh.send(joiner, pack_state_frame(
                        STATE_META,
                        {**snap.stamp.as_meta(), "round": snap_round,
                         "donor": self.donor_rank}))
                elif kind == STATE_REQ:
                    self._serve_range(mesh, joiner, snap,
                                      int(meta["o"]), int(meta["n"]),
                                      counter)
                elif kind == STATE_BYE:
                    return
                else:
                    raise StreamError(
                        f"unexpected state frame kind {kind} on the "
                        f"donor side")
        finally:
            mesh.close()

    def _serve_range(self, mesh, joiner: int, snap: Snapshot | None,
                     offset: int, length: int, counter) -> None:
        if snap is None:
            raise StreamError("REQ before any snapshot round opened")
        view = memoryview(snap.data)
        end = offset + length
        for o in range(offset, end, self.chunk_bytes):
            n = min(self.chunk_bytes, end - o)
            chunk = view[o:o + n]
            mesh.send(joiner, pack_state_frame(
                STATE_DATA, {"o": o, "n": n,
                             "crc": zlib.crc32(chunk)}, chunk))
            self.bytes_served += n
            counter.inc(n)
        mesh.send(joiner, pack_state_frame(STATE_END,
                                           {"o": offset, "n": length}))


class JoinerPuller:
    """The joining rank's pull half: assembles the donors' disjoint
    shards into one image and verifies it against the unanimous stamp
    before a single byte is interpreted."""

    def __init__(self, kv, scope: str, num_donors: int,
                 *, timeout: float | None = None) -> None:
        self.kv = kv
        self.scope = scope
        self.num_donors = num_donors
        self.timeout = timeout or config.STATESYNC_TIMEOUT_SECONDS.get()
        self._mesh = None
        self._dead: set[int] = set()
        # Per-round observability for the catch-up bound assertions:
        # donor -> (bytes pulled, wall seconds) of the last round.
        self.donor_stats: dict[int, tuple[int, float]] = {}

    def connect(self) -> None:
        from ..runner.network import PeerMesh

        guard = StreamGuard(self.timeout)
        self._mesh = PeerMesh(self.num_donors, self.num_donors + 1,
                              self.kv, scope=self.scope,
                              timeout=self.timeout, resilience=guard)

    # -- one round -------------------------------------------------------
    def pull_round(self, round_idx: int) -> tuple[bytearray,
                                                  SnapshotStamp]:
        """Pull one full snapshot round; returns the digest-verified
        image and its stamp.  Raises :class:`TornSnapshotError` when the
        donors' stamps disagree or the assembly fails verification, and
        :class:`StreamError` when too many donors die to finish."""
        mesh = self._mesh
        if mesh is None:
            raise StreamError("pull_round before connect")
        stamp = self._collect_metas(round_idx)
        image = bytearray(stamp.nbytes)
        donors = [d for d in range(self.num_donors)
                  if d not in self._dead]
        self.donor_stats = {}
        # Disjoint contiguous ranges, one per live donor.
        share = -(-stamp.nbytes // max(len(donors), 1))
        pending: list[tuple[int, int]] = []
        workers = []
        results: dict[int, tuple[int, int] | None] = {}
        for i, d in enumerate(donors):
            o = min(i * share, stamp.nbytes)
            n = min(share, stamp.nbytes - o)
            t = threading.Thread(
                target=self._pull_range, daemon=True,
                name=f"hvd-statesync-pull-{d}",
                args=(d, o, n, image, results))
            workers.append((d, t, o, n))
            t.start()
        for d, t, o, n in workers:
            t.join(timeout=self.timeout + 5.0)
            leftover = results.get(d)
            if t.is_alive() or leftover is None:
                # No progress record at all: re-pull the whole range
                # (chunk writes are idempotent, so overlap is safe).
                self._dead.add(d)
                leftover = (o, n)
            if leftover[1] > 0:
                pending.append(leftover)
        # Resume: reassign dead donors' unfinished tails to survivors
        # (chunk-granular — completed chunks are never re-pulled).
        while pending:
            alive = [d for d in range(self.num_donors)
                     if d not in self._dead]
            if not alive:
                raise StreamError(
                    "every donor died before the transfer finished")
            o, n = pending.pop()
            d = alive[0]
            results.pop(d, None)
            self._pull_range(d, o, n, image, results)
            leftover = results.get(d, (o, n))
            if leftover[1] > 0:
                pending.append(leftover)
        self.verify_round(image, stamp)
        return image, stamp

    def _collect_metas(self, round_idx: int) -> SnapshotStamp:
        mesh = self._mesh
        stamps: dict[int, SnapshotStamp] = {}
        for d in range(self.num_donors):
            if d in self._dead:
                continue
            try:
                mesh.send(d, pack_state_frame(
                    STATE_HELLO, {"round": round_idx}))
                kind, meta, _ = unpack_state_frame(mesh.recv(d))
            except (DonorLostError, ConnectionError, OSError) as exc:
                logger.warning("statesync: donor %d unreachable at "
                               "HELLO: %s", d, exc)
                self._dead.add(d)
                continue
            if kind != STATE_META:
                raise StreamError(
                    f"donor {d} answered HELLO with frame kind {kind}")
            stamps[d] = SnapshotStamp.from_meta(meta)
        if not stamps:
            raise StreamError("no live donors answered HELLO")
        stamp = next(iter(stamps.values()))
        for d, s in stamps.items():
            if s != stamp:
                _record_reject("torn-stamp",
                               f"donor {d}: {s} != {stamp}")
                raise TornSnapshotError(
                    f"torn snapshot: donor {d} stamped {s}, another "
                    f"donor stamped {stamp} — the donors cut at "
                    f"different steps; rejecting the round")
        return stamp

    def _pull_range(self, donor: int, offset: int, length: int,
                    image: bytearray, results: dict) -> None:
        """Pull [offset, offset+length) from one donor into the shared
        image (ranges are disjoint — no lock needed).  On donor death,
        records the unfinished tail in ``results`` for reassignment."""
        mesh = self._mesh
        counter = _statesync_bytes_counter("joiner")
        t0 = time.monotonic()
        next_offset = offset
        end = offset + length
        if length <= 0:
            results[donor] = (offset, 0)
            return
        try:
            mesh.send(donor, pack_state_frame(
                STATE_REQ, {"o": offset, "n": length}))
            view = memoryview(image)
            while True:
                kind, meta, payload = unpack_state_frame(
                    mesh.recv(donor))
                if kind == STATE_END:
                    break
                if kind != STATE_DATA:
                    raise StreamError(
                        f"donor {donor}: unexpected frame kind {kind} "
                        f"inside a range")
                o, n = int(meta["o"]), int(meta["n"])
                if zlib.crc32(payload) != int(meta["crc"]):
                    _record_reject("chunk-crc",
                                   f"donor {donor} offset {o}")
                    raise TornSnapshotError(
                        f"donor {donor}: chunk at offset {o} failed "
                        f"its CRC — rejecting the round")
                view[o:o + n] = payload
                counter.inc(n)
                if o == next_offset:
                    next_offset = o + n
            if next_offset != end:
                raise DonorLostError(
                    donor, f"range ended at {next_offset} of {end}")
            results[donor] = (end, 0)
        except TornSnapshotError:
            raise
        except (StreamError, ConnectionError, OSError) as exc:
            logger.warning("statesync: donor %d died mid-range "
                           "(resuming from %d): %s", donor,
                           next_offset, exc)
            self._dead.add(donor)
            results[donor] = (next_offset, end - next_offset)
        finally:
            self.donor_stats[donor] = (next_offset - offset,
                                       time.monotonic() - t0)

    @staticmethod
    def verify_round(image, stamp: SnapshotStamp) -> None:
        """The digest check gating every read of streamed state: the
        assembled image must reproduce the donors' unanimous stamp."""
        got = state_digest(image)
        if got != stamp.digest:
            _record_reject("digest",
                           f"{got:#x} != {stamp.digest:#x} (epoch "
                           f"{stamp.epoch}, step {stamp.step})")
            raise TornSnapshotError(
                f"assembled state digest {got:#x} != stamped "
                f"{stamp.digest:#x} (epoch {stamp.epoch}, step "
                f"{stamp.step}) — stale or corrupt transfer rejected")

    def close(self) -> None:
        mesh = self._mesh
        if mesh is None:
            return
        for d in range(self.num_donors):
            if d in self._dead:
                continue
            try:
                mesh.send(d, pack_state_frame(STATE_BYE, {}))
            except Exception:  # noqa: BLE001 - donor may be gone
                pass
        mesh.close()
        self._mesh = None
