"""statesync/ — zero-downtime elastic world grow: peer-to-peer live
state streaming, preemption grace, and the autoscale policy loop
(ISSUE 10; ROADMAP item 4; docs/statesync.md).

The missing half of elasticity: PR 5 proved the world can shrink past a
dead rank; this subsystem grows it back — and turns a preemption notice
into an orderly, failure-free departure — without a checkpoint file and
without incumbents failing a single step.

Module surface:

- :class:`~.service.StateSyncService` — one rank's membership agent:
  ``step_boundary()`` runs the per-step membership check (join
  admission → copy-on-write snapshot + donor thread, joiner-ready →
  grow transition, SIGTERM grace → proactive shrink), and
  ``shrink_on_failure()`` packages PR 5's confirmed-dead shrink.
- :func:`~.service.join_world` — the joiner side: announce, pull the
  bulk snapshot from every live donor (disjoint shards, chunked,
  resumable across a donor death, FNV-digest-verified), pull the final
  boundary image while the incumbents rebuild channels, enter as
  rank N.
- :mod:`.snapshot` — flat state images, stamps/digests, ring-shard
  (ZeRO) re-layout math shared with checkpoint.py.
- :mod:`.stream` — the donor/joiner streaming protocol over PR 3
  persistent duplex channels (``tcp_transport`` state-frame verb).
- :mod:`.autoscale` — the rank-0 policy loop driving the elastic
  driver's target world size from telemetry, with hysteresis.
"""
from __future__ import annotations

from .autoscale import (AutoscaleController, AutoscaleDecision,
                        AutoscalePolicy, registry_source)
from .service import (JoinInfo, StateSyncService, WorldChange,
                      fetch_donation, join_world, resync_replicated)
from .snapshot import (Snapshot, SnapshotStamp, concat_ring_shards,
                       flatten_state, reshard_ring_state, shard_for_rank,
                       state_digest, unflatten_state)
from .stream import (DonorLostError, DonorServer, JoinerPuller,
                     StreamError, TornSnapshotError)

__all__ = [
    "AutoscaleController", "AutoscaleDecision", "AutoscalePolicy",
    "DonorLostError", "DonorServer", "JoinInfo", "JoinerPuller",
    "Snapshot", "SnapshotStamp", "StateSyncService", "StreamError",
    "TornSnapshotError", "WorldChange", "concat_ring_shards",
    "fetch_donation", "flatten_state", "join_world", "registry_source",
    "reshard_ring_state", "resync_replicated", "shard_for_rank",
    "state_digest", "unflatten_state",
]
