"""The statesync membership service: zero-downtime world grow,
preemption grace, and the failure-shrink transition.

Every rank's training (or serving) loop calls
:meth:`StateSyncService.step_boundary` once per step.  The boundary
runs ONE tiny symmetric collective — an ``allgather_object`` of each
rank's locally observed membership events — so every rank reaches the
identical verdict at the identical step:

- **join seen** → every incumbent takes a copy-on-write
  :class:`~.snapshot.Snapshot` at THIS boundary (coherent by
  construction: same step everywhere) and spawns a
  :class:`~.stream.DonorServer` thread.  Training never pauses; the
  donors stream from the frozen image.
- **joiner ready** (its bulk image digest-verified) → the grow
  transition: incumbents take the final boundary snapshot, hand it to
  their donor threads (streamed while the channel rebuild below runs
  anyway), publish the ``go`` record, and rebuild the world one rank
  larger under a fresh rendezvous epoch.  Incumbents keep their ranks;
  the joiner enters as rank N with the exact final-boundary state —
  they never blocked on the joiner's bulk catch-up.
- **departure announced** (SIGTERM inside the
  ``HOROVOD_PREEMPT_GRACE_S`` window) → the preempted rank finishes
  this step, optionally fast-donates its ring-sharded optimizer shard
  to the KV, writes its ``bye|`` liveness stamp (via the monitor's
  orderly shutdown) and exits 0; the survivors renumber and rebuild one
  rank smaller at the SAME boundary — a proactive shrink with no
  ``RanksFailedError`` and no heartbeat deadline anywhere.

The hard failure path (a peer SIGKILLed mid-step) still surfaces as
``RanksFailedError`` from the training collective; the loop hands it to
:meth:`StateSyncService.shrink_on_failure`, which converges on the
heartbeat-confirmed dead set (resilience/policy.py) and rebuilds on the
survivors — PR 5's shrink, packaged next to the grow that undoes it.
"""
from __future__ import annotations

import dataclasses
import json
import os
import signal
import threading
import time
from typing import Any, Callable

from ..common import config
from ..common.logging import logger
from .snapshot import Snapshot, flatten_state, state_digest, unflatten_state
from .stream import DonorServer, JoinerPuller, sync_scope

__all__ = ["JoinInfo", "StateSyncService", "WorldChange", "fetch_donation",
           "join_world", "resync_replicated"]

_WORLD_SCOPE = "statesync"
_WORLD_KEY = "world"


def _world_key() -> str:
    # A fleet deployment runs two live worlds (train + serve) against
    # ONE coordinator KV, so the membership record is namespaced by
    # HOROVOD_STATESYNC_WORLD; join_world reads the same name to
    # target the right world (docs/fleet.md).
    return config.STATESYNC_WORLD.get() or _WORLD_KEY


def _grow_scope(epoch: str) -> str:
    return f"ssgrow.{epoch}"


def _donate_scope(epoch: str) -> str:
    return f"ssdonate.{epoch}"


@dataclasses.dataclass
class WorldChange:
    """What a step boundary (or failure) did to the world membership."""
    kind: str                      # "grow" | "shrink" | "departed"
    rank: int = 0
    size: int = 0
    dead: tuple = ()               # shrink: the removed launch ranks
    join_id: int = -1              # grow: the admitted join event


@dataclasses.dataclass
class JoinInfo:
    """The joiner's view of its own admission (join_world)."""
    rank: int
    size: int
    epoch: str
    join_id: int
    seq: int                       # boundary counter to resume from
    stamp: Any                     # final verified SnapshotStamp
    catch_up_ms: float             # bulk round wall time
    bulk_bytes: int
    donor_stats: dict              # donor -> (bytes, wall_s), bulk round


def _kv_client():
    from ..runner.network import RendezvousClient

    addr = config.RENDEZVOUS_ADDR.get()
    port = config.RENDEZVOUS_PORT.get()
    if not addr or port <= 0:
        raise RuntimeError(
            "statesync needs the rendezvous KV "
            "(HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT)")
    return RendezvousClient(addr, port,
                            config.GLOO_TIMEOUT_SECONDS.get())


class StateSyncService:
    """One rank's membership agent.  Create AFTER ``hvd.init()``; the
    service survives every world transition (it is not owned by core)."""

    def __init__(self, state_provider: Callable[[], Any], *,
                 static_state: bool = False,
                 donate_provider: Callable[[], Any] | None = None,
                 kv=None) -> None:
        self._provider = state_provider
        self._donate_provider = donate_provider
        # Static state (serving: params never change between steps)
        # skips the final round — the bulk image IS the entry state.
        self.static_state = static_state
        self._kv = kv if kv is not None else _kv_client()
        self._seq = 0
        self._lock = threading.Lock()
        self._pending_join = -1        # join id seen, not yet snapshotted
        self._ready_join = -1          # join id whose joiner verified
        self._join_cursor = 0
        self._active_join = -1
        self._donors: dict[int, DonorServer] = {}
        self._preempt_at: float | None = None
        self._departed = False
        self._grace_timer: threading.Timer | None = None
        # (donation-start, grow-done) wall pairs — the serving report's
        # goodput-during-grow window (serving/loadgen.py).
        self.grow_windows: list[tuple[float, float]] = []
        self._grow_t0 = 0.0
        self._stop = threading.Event()
        self._refresh_world()
        self._install_preempt_handler()
        self._watcher = threading.Thread(target=self._watch_loop,
                                         daemon=True,
                                         name="hvd-statesync-watch")
        self._watcher.start()

    # -- donor lifecycle -------------------------------------------------
    def _reap_donors(self, grace: float = 2.0) -> None:
        """Join and drop finished DonorServer threads.  Without the
        reap, one DonorServer object per admitted join survived every
        grow forever — the ``_donors`` dict pinned the thread AND its
        snapshot queue (a full state image per round) across all later
        epochs (hvdlife HVD701; the census witness shows the
        ``hvd-statesync-donor-*`` count ratcheting per cycle).  A donor
        still serving (the joiner pulls the final round while
        incumbents rebuild channels) gets a bounded join and is left
        for the next boundary's reap — never blocked on."""
        for join_id, donor in list(self._donors.items()):
            donor.join(timeout=grace if donor.is_alive() else 0.0)
            if not donor.is_alive():
                del self._donors[join_id]

    # -- world identity --------------------------------------------------
    def _refresh_world(self) -> None:
        from .. import core

        st = core.global_state()
        self._reap_donors()
        with self._lock:
            self.rank = st.rank
            self.size = st.size
            self._epoch = os.environ.get("HOROVOD_RENDEZVOUS_EPOCH", "0")
            # The boundary counter is EPOCH-SCOPED: every transition
            # resets it, so survivors that caught a failure at
            # different steps (and a joiner entering fresh) agree on
            # the next flag-exchange name without negotiation.
            self._seq = 0
            self._pending_join = -1
            self._ready_join = -1
            self._join_cursor = 0
            self._active_join = -1
        from ..telemetry import metrics

        metrics().gauge(
            "horovod_world_size",
            "Live world size as seen by this rank's statesync service "
            "(tracks every elastic grow/shrink transition)").set(self.size)
        if self.rank == 0:
            try:
                self._kv.put(_WORLD_SCOPE, _world_key(), json.dumps(
                    {"epoch": self._epoch, "size": self.size,
                     "seq": self._seq}).encode())
            except Exception as exc:  # noqa: BLE001 - KV hiccup
                logger.warning("statesync: world record publish "
                               "failed: %s", exc)

    # -- preemption grace ------------------------------------------------
    def _install_preempt_handler(self) -> None:
        self._grace = config.PREEMPT_GRACE_SECONDS.get()
        if self._grace <= 0:
            return
        if threading.current_thread() is not threading.main_thread():
            logger.warning("statesync: SIGTERM grace requested off the "
                           "main thread; handler not installed")
            return
        try:
            signal.signal(signal.SIGTERM, self._on_sigterm)
        except (ValueError, OSError):
            logger.debug("statesync: SIGTERM handler not installed",
                         exc_info=True)

    def _on_sigterm(self, signum, frame) -> None:
        if self._preempt_at is not None:
            return
        self._preempt_at = time.monotonic()
        from ..telemetry import flight

        rec = flight.recorder()
        if rec.enabled:
            rec.record("sigterm-grace",
                       detail=f"grace={self._grace:g}s; departing at "
                              f"the next step boundary")
        timer = threading.Timer(self._grace, self._grace_expired)
        timer.daemon = True
        # The ownership manifest (hvdsan/hvdlife THREAD_ROOTS) and the
        # census normalize by thread name; Timer defaults to Thread-N.
        timer.name = "hvd-preempt-backstop"
        timer.start()
        self._grace_timer = timer
        logger.warning("statesync: SIGTERM received; departing within "
                       "%.1fs grace (next step boundary)", self._grace)

    def _grace_expired(self) -> None:
        """Backstop: no step boundary arrived inside the grace window
        (a wedged step).  Stamp the orderly departure anyway, ship the
        flight evidence, and exit with the conventional SIGTERM status
        — strictly better than the SIGKILL the scheduler sends next."""
        if self._departed:
            return
        from ..resilience import active_state
        from ..telemetry import flight

        state = active_state()
        if state is not None:
            try:
                state.monitor.stop()   # writes the bye| stamp
            except Exception:  # noqa: BLE001 - best-effort stamp
                pass
        rec = flight.recorder()
        if rec.enabled:
            rec.record("sigterm-grace-expired")
            rec.dump(reason="SIGTERM grace expired before a step "
                            "boundary")
        os._exit(143)

    @property
    def preempt_requested(self) -> bool:
        return self._preempt_at is not None

    def request_depart(self) -> None:
        """Programmatic orderly departure: arm the same boundary path a
        SIGTERM preemption notice takes (announce via the ``depart``
        flag of the next membership exchange, fast-donate, depart with
        the ``bye|`` stamp — survivors shrink proactively, no
        RanksFailedError), minus the signal handler and the backstop
        timer.  The fleet controller's migration directive
        (fleet/controller.py) lands here: moving a rank between worlds
        IS a preemption from the donor world's point of view."""
        if self._preempt_at is not None:
            return
        self._preempt_at = time.monotonic()
        from ..telemetry import flight

        rec = flight.recorder()
        if rec.enabled:
            rec.record("fleet-depart",
                       detail="departing at the next step boundary "
                              "(fleet migration directive)")
        logger.info("statesync: departure requested; leaving at the "
                    "next step boundary")

    # -- watcher ---------------------------------------------------------
    def _watch_loop(self) -> None:
        poll = config.STATESYNC_POLL_SECONDS.get()
        kv_healthy = True
        while not self._stop.wait(poll):
            try:
                self._watch_once()
                if not kv_healthy:
                    kv_healthy = True
                    logger.warning(
                        "statesync: rendezvous KV reachable again "
                        "(endpoint %s); watcher resumed",
                        getattr(self._kv, "endpoint", "?"))
            except TimeoutError as exc:
                # Coordinator restart/failover window: the client's
                # bounded retry already rotated endpoints — keep the
                # watcher alive and name the outage once instead of
                # silently dropping membership events.
                if kv_healthy:
                    kv_healthy = False
                    logger.warning(
                        "statesync: rendezvous KV unreachable (%s); "
                        "watcher idling until an endpoint answers", exc)
            except Exception:  # noqa: BLE001 - never kill the watcher
                logger.debug("statesync: watcher poll failed",
                             exc_info=True)

    def _watch_once(self) -> None:
        with self._lock:
            epoch = self._epoch
            cursor = self._join_cursor
            active = self._active_join
        scope = _grow_scope(epoch)
        if active < 0:
            raw = self._kv.get(scope, f"join:{cursor}")
            if raw is not None:
                with self._lock:
                    if self._epoch == epoch:
                        self._pending_join = cursor
        else:
            raw = self._kv.get(scope, f"ready:{active}")
            if raw is not None:
                with self._lock:
                    if self._epoch == epoch:
                        self._ready_join = active

    # -- the boundary ----------------------------------------------------
    def step_boundary(self) -> WorldChange | None:
        """Run the membership check for one step boundary.  Returns a
        :class:`WorldChange` when this boundary changed the world (the
        caller must re-read rank/size and, on ``departed``, exit its
        loop), else None.  Cheap steady state: one small
        allgather_object on the existing collective plane."""
        import horovod_tpu as hvd

        seq = self._seq
        self._seq += 1
        with self._lock:
            local = {"join": self._pending_join,
                     "ready": self._ready_join,
                     "depart": self.rank if self._preempt_at is not None
                     else -1}
        # Unconditionally allgather'd — at size 1 the collective is a
        # local no-op returning [local], byte-identical to the old
        # ``else: views = [local]`` fallback arm, and the service is
        # documented (and constructed everywhere in-tree) to exist only
        # inside initialized worlds.  The payoff: ``views`` provably
        # derives from a collective exchange on EVERY path, so the
        # boundary decisions below are world-symmetric by dataflow and
        # need no HVD601 suppressions (the old size==1 ternary was the
        # only taint source).
        views = hvd.allgather_object(
            local, name=f"statesync.flag.{seq}")
        departing = sorted({v["depart"] for v in views
                            if v["depart"] >= 0})
        ready_id = max(v["ready"] for v in views)
        join_id = max(v["join"] for v in views)
        if departing:
            return self._transition_depart(departing)
        if ready_id >= 0:
            return self._transition_grow(ready_id)
        if join_id >= 0:
            self._start_donation(join_id)
        return None

    # -- donation --------------------------------------------------------
    def _start_donation(self, join_id: int) -> None:
        with self._lock:
            if self._active_join >= 0 or join_id in self._donors:
                return
            self._active_join = join_id
            self._pending_join = -1
            self._join_cursor = join_id + 1
            epoch = self._epoch
        self._grow_t0 = time.monotonic()
        snap = Snapshot(self._provider(), epoch, self._seq)
        donor = DonorServer(self._kv, sync_scope(epoch, join_id),
                            self.rank, self.size)
        donor.offer_snapshot(0, snap)
        donor.start()
        self._donors[join_id] = donor
        from ..telemetry import flight

        rec = flight.recorder()
        if rec.enabled:
            rec.record("donate", f"join {join_id}",
                       detail=f"{len(snap)} bytes from the step-"
                              f"{self._seq} boundary snapshot")
        logger.info("statesync: join %d admitted; donating %d bytes "
                    "from the step-%d boundary snapshot", join_id,
                    len(snap), self._seq)

    # -- transitions -----------------------------------------------------
    def _transition_grow(self, join_id: int) -> WorldChange:
        from .. import core

        with self._lock:
            epoch = self._epoch
            old_rank, old_size = self.rank, self.size
        donor = self._donors.get(join_id)
        final = not self.static_state
        if final:
            if donor is None or not donor.is_alive():
                # The donor thread died (joiner vanished after ready?):
                # a fresh one serves the final round alone.
                donor = DonorServer(self._kv,
                                    sync_scope(epoch, join_id),
                                    old_rank, old_size)
                donor.start()
                self._donors[join_id] = donor
            donor.offer_snapshot(
                1, Snapshot(self._provider(), epoch, self._seq))
        new_epoch = f"{epoch}~g{join_id}"
        new_size = old_size + 1
        if old_rank == 0:
            self._kv.put(_grow_scope(epoch), f"go:{join_id}",
                         json.dumps({"epoch": new_epoch,
                                     "size": new_size,
                                     "rank": old_size,
                                     "seq": self._seq,
                                     "final": final}).encode())
        logger.warning("statesync: grow %d->%d (join %d) at boundary "
                       "%d; rebuilding channels", old_size, new_size,
                       join_id, self._seq)
        from ..telemetry import flight

        rec = flight.recorder()
        if rec.enabled:
            rec.record("grow", f"join {join_id}",
                       detail=f"{old_size}->{new_size} seq={self._seq}")
        core.reinit_world(rank=old_rank, size=new_size, epoch=new_epoch)
        self.grow_windows.append((self._grow_t0, time.monotonic()))
        self._refresh_world()
        return WorldChange("grow", rank=self.rank, size=self.size,
                           join_id=join_id)

    def _transition_depart(self, departing: list[int]) -> WorldChange:
        from .. import core

        with self._lock:
            epoch = self._epoch
            old_rank, old_size = self.rank, self.size
        if old_rank in departing:
            if self._grace_timer is not None:
                # Cancel AND reap: cancel() only marks the timer; the
                # backstop thread itself must be gone before the census
                # around the clean departure (hvdlife HVD701).
                self._grace_timer.cancel()
                self._grace_timer.join(timeout=2.0)
                self._grace_timer = None
            self._fast_donate(epoch)
            from ..telemetry import flight

            rec = flight.recorder()
            if rec.enabled:
                rec.record("departed",
                           detail=f"orderly SIGTERM departure at "
                                  f"boundary {self._seq}")
            self._departed = True
            # core.shutdown stops the heartbeat monitor, which writes
            # the bye| stamp — peers read an orderly goodbye, never
            # heartbeat silence.
            core.shutdown()
            logger.warning("statesync: departed cleanly (preemption "
                           "grace) at boundary %d", self._seq)
            return WorldChange("departed", rank=old_rank, size=old_size)
        survivors = [r for r in range(old_size) if r not in departing]
        new_rank = survivors.index(old_rank)
        tag = "_".join(str(r) for r in departing)
        new_epoch = f"{epoch}~p{tag}"
        from ..telemetry import flight

        rec = flight.recorder()
        if rec.enabled:
            rec.record("shrink-proactive", f"departed {departing}",
                       detail=f"{old_size}->{len(survivors)} at "
                              f"boundary {self._seq}; no "
                              f"RanksFailedError anywhere")
        logger.warning("statesync: proactive shrink %d->%d (preempted "
                       "rank(s) %s); this rank %d -> %d", old_size,
                       len(survivors), departing, old_rank, new_rank)
        core.reinit_world(rank=new_rank, size=len(survivors),
                          epoch=new_epoch)
        self._refresh_world()
        return WorldChange("shrink", rank=self.rank, size=self.size,
                           dead=tuple(departing))

    def shrink_on_failure(self, exc) -> WorldChange:
        """Hard-failure shrink: converge on the heartbeat-confirmed
        dead set (never a merely-slow peer), renumber deterministically,
        rebuild on the survivors.  Re-raises ``exc`` when the failure
        cannot be confirmed."""
        from .. import core
        from ..resilience import converge_confirmed_dead

        dead = converge_confirmed_dead(exc)
        with self._lock:
            epoch = self._epoch
            old_rank, old_size = self.rank, self.size
        if old_rank in dead:
            raise exc
        survivors = [r for r in range(old_size) if r not in dead]
        new_rank = survivors.index(old_rank)
        tag = "_".join(str(r) for r in sorted(dead))
        from ..telemetry import flight

        rec = flight.recorder()
        if rec.enabled:
            rec.record("shrink", f"dead {sorted(dead)}",
                       detail=f"{old_size}->{len(survivors)}; "
                              f"heartbeat-confirmed set")
        logger.warning("statesync: failure shrink %d->%d (dead=%s); "
                       "this rank %d -> %d", old_size, len(survivors),
                       sorted(dead), old_rank, new_rank)
        core.reinit_world(rank=new_rank, size=len(survivors),
                          epoch=f"{epoch}~f{tag}")
        self._refresh_world()
        return WorldChange("shrink", rank=self.rank, size=self.size,
                           dead=tuple(sorted(dead)))

    # -- fast donation on departure --------------------------------------
    def _fast_donate(self, epoch: str) -> None:
        if self._donate_provider is None or \
                not config.PREEMPT_DONATE.get():
            return
        try:
            tree = self._donate_provider()
            image = flatten_state(tree)
            self._kv.put(_donate_scope(epoch), f"{self.rank}.meta",
                         json.dumps({"digest": state_digest(image),
                                     "nbytes": len(image),
                                     "seq": self._seq}).encode())
            self._kv.put(_donate_scope(epoch), str(self.rank),
                         bytes(image))
            logger.info("statesync: fast-donated %d state bytes before "
                        "departure", len(image))
        except Exception as exc:  # noqa: BLE001 - donation best-effort
            logger.warning("statesync: fast-donate failed: %s", exc)

    def notify_world_changed(self) -> None:
        """Re-read the world identity after a transition the service
        did not drive itself (the serving shrink path reinits the world
        from its own failure handler)."""
        self._refresh_world()

    def close(self) -> None:
        self._stop.set()
        if self._grace_timer is not None:
            self._grace_timer.cancel()
            self._grace_timer.join(timeout=2.0)
            self._grace_timer = None
        self._watcher.join(timeout=2.0)
        self._reap_donors()


def resync_replicated(state_tree: Any, version: int,
                      name: str = "statesync.resync") -> Any:
    """Realign replicated training state after a failure shrink.

    Survivors can catch a peer's death on DIFFERENT steps — one applied
    the last update before its collective raised, its neighbor did not —
    so after the world rebuild the most-advanced rank (highest
    ``version``; ties break to the lowest rank) broadcasts its state and
    everyone adopts it.  One broadcast, symmetric on every rank; call it
    once right after ``shrink_on_failure`` returns.  (The preemption and
    grow paths never need it: their transitions are step-synchronous.)"""
    import horovod_tpu as hvd

    views = hvd.allgather_object(int(version), name=f"{name}.v")
    best = max(range(len(views)), key=lambda r: (views[r], -r))
    return hvd.broadcast_object(state_tree, root_rank=best,
                                name=f"{name}.state")


def fetch_donation(epoch: str, rank: int, template: Any,
                   kv=None) -> Any | None:
    """Fetch a departed rank's fast-donated state from the KV, verify
    its digest, and unflatten against ``template``.  Returns None when
    nothing (valid) was donated."""
    kv = kv if kv is not None else _kv_client()
    meta_raw = kv.get(_donate_scope(epoch), f"{rank}.meta")
    image = kv.get(_donate_scope(epoch), str(rank))
    if meta_raw is None or image is None:
        return None
    meta = json.loads(meta_raw)
    if state_digest(image) != int(meta["digest"]) or \
            len(image) != int(meta["nbytes"]):
        logger.warning("statesync: donated state from rank %d failed "
                       "its digest check; ignoring", rank)
        return None
    return unflatten_state(image, template)


# ---------------------------------------------------------------------------
# The joiner side
# ---------------------------------------------------------------------------
def join_world(template_state: Any, *, timeout: float | None = None,
               max_attempts: int = 3) -> tuple[Any, JoinInfo]:
    """Join a live world as rank N by streaming state from its peers.

    Announces through the rendezvous KV, pulls the bulk snapshot from
    every incumbent (disjoint shards, resumable), posts ``ready`` once
    the image digest-verifies, pulls the final boundary image while the
    incumbents rebuild channels, then enters the world via
    ``core.init``.  Returns ``(state_tree, JoinInfo)`` — the tree is
    shaped like ``template_state`` and bit-identical to the donors'
    final snapshot."""
    import socket

    from .. import core

    kv = _kv_client()
    timeout = timeout if timeout is not None \
        else config.STATESYNC_TIMEOUT_SECONDS.get()
    last_exc: Exception | None = None
    for attempt in range(max_attempts):
        world = json.loads(kv.wait(_WORLD_SCOPE, _world_key(), timeout))
        epoch, size = world["epoch"], int(world["size"])
        scope = _grow_scope(epoch)
        join_id = kv.claim(scope, "joins",
                           task_key=f"{socket.gethostname()}:"
                                    f"{os.getpid()}:{attempt}")
        kv.put(scope, f"join:{join_id}",
               json.dumps({"id": join_id, "epoch": epoch}).encode())
        from ..telemetry import flight

        rec = flight.recorder()
        if rec.enabled:
            rec.record("join-announce", f"join {join_id}",
                       detail=f"epoch {epoch}, {size} donors, "
                              f"attempt {attempt}")
        puller = JoinerPuller(kv, sync_scope(epoch, join_id), size,
                              timeout=timeout)
        try:
            t0 = time.monotonic()
            puller.connect()
            image, stamp = puller.pull_round(0)
            catch_up_ms = (time.monotonic() - t0) * 1e3
            bulk_stats = dict(puller.donor_stats)
            kv.put(scope, f"ready:{join_id}",
                   json.dumps(stamp.as_meta()).encode())
            if rec.enabled:
                # Ready is posted ONLY after pull_round digest-verified
                # the bulk image (the spec guard "ready-after-verify").
                rec.record("join-ready", f"join {join_id}",
                           detail=f"bulk {stamp.nbytes} bytes verified "
                                  f"in {catch_up_ms:.0f} ms")
            go = json.loads(kv.wait(scope, f"go:{join_id}", timeout))
            if go["final"]:
                image, stamp = puller.pull_round(1)
            puller.close()
        except Exception as exc:  # noqa: BLE001 - round failed: retry
            logger.warning("statesync: join attempt %d failed: %s",
                           attempt, exc)
            last_exc = exc
            try:
                puller.close()
                # Consume the stale announcement so a later watcher
                # pass never re-admits this dead attempt.
                kv.delete(scope, f"join:{join_id}")
                kv.delete(scope, f"ready:{join_id}")
            except Exception:  # noqa: BLE001 - already torn down
                pass
            time.sleep(min(2.0 ** attempt, 5.0))
            continue
        # Entry: the image is digest-verified (pull_round) — unflatten
        # and form the new world.  Incumbents are blocked only on this
        # mesh formation, never on the bulk transfer above.
        tree = unflatten_state(image, template_state)
        core.reinit_world(rank=int(go["rank"]), size=int(go["size"]),
                          epoch=go["epoch"])
        rec = flight.recorder()
        if rec.enabled:
            rec.record("join-entered",
                       f"rank {go['rank']}/{go['size']}",
                       detail=f"epoch {go['epoch']} seq {go['seq']}")
        from ..telemetry import metrics

        metrics().histogram(
            "horovod_catch_up_ms",
            "Wall time of a joiner's bulk peer-streaming catch-up "
            "(announce to digest-verified image)").observe(catch_up_ms)
        metrics().gauge(
            "horovod_world_size",
            "Live world size as seen by this rank's statesync service "
            "(tracks every elastic grow/shrink transition)"
        ).set(int(go["size"]))
        # go["seq"] is the incumbents' NEXT boundary index (they bumped
        # theirs before the grow transition ran) — start exactly there.
        info = JoinInfo(rank=int(go["rank"]), size=int(go["size"]),
                        epoch=go["epoch"], join_id=join_id,
                        seq=int(go["seq"]),
                        stamp=stamp, catch_up_ms=catch_up_ms,
                        bulk_bytes=stamp.nbytes,
                        donor_stats=bulk_stats)
        logger.warning("statesync: joined as rank %d/%d (epoch %s); "
                       "bulk catch-up %.0f ms for %d bytes",
                       info.rank, info.size, info.epoch,
                       catch_up_ms, stamp.nbytes)
        return tree, info
    raise RuntimeError(
        f"statesync: could not join after {max_attempts} attempts"
    ) from last_exc
