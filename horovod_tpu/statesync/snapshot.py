"""Copy-on-write state snapshots: one flat byte image of the training
state, stamped for integrity, plus the ring-shard (ZeRO) re-layout math.

A donor never streams live tensors: at a step boundary the service takes
a :class:`Snapshot` — a single contiguous copy of the provider's pytree
(the copy IS the copy-on-write: training mutates the live arrays freely
while donor threads stream the frozen image).  The snapshot is stamped
with ``(epoch, step, digest, nbytes)``; the digest is an FNV-1a 64-bit
fold over per-block CRCs (block size 64 KiB — the FNV fold keeps the
stamp one word, the C-speed CRC inner loop keeps multi-MB states cheap
to stamp).  A joiner rejects any assembly whose donors disagree on the
stamp (torn snapshot: donors cut at different steps) or whose assembled
bytes do not reproduce the digest (corrupt or stale transfer).

Flattening reuses the ``grad_sync`` discipline: ``jax.tree_util``
leaf order (deterministic for dicts), leaves laid out back to back in
their own dtypes.  The template-driven :func:`unflatten_state` is the
only read surface for streamed bytes — hvdlint HVD1007 flags statesync
code that consumes a frame payload without a verify call in scope.

Ring-shard math: :func:`reshard_ring_state` re-cuts PR 6's
optimizer-in-ring (ZeRO) shard layout for a new world size — the
checkpoint round-trip (checkpoint.py) and the joiner's post-entry state
layout both use it.
"""
from __future__ import annotations

import dataclasses
import zlib
from typing import Any

import numpy as np

# FNV-1a 64-bit (the fingerprint subsystem's constants,
# analysis/fingerprint.py — one digest family across the tree).
_FNV_OFFSET = 0xcbf29ce484222325
_FNV_PRIME = 0x100000001b3
_MASK = (1 << 64) - 1
_DIGEST_BLOCK = 64 * 1024


def fnv1a_fold(data: bytes, h: int = _FNV_OFFSET) -> int:
    """Plain FNV-1a 64 over ``data`` (stamp-sized inputs only)."""
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK
    return h


def state_digest(view) -> int:
    """FNV-1a 64-bit fold over per-64KiB-block CRC32s of ``view``.

    The outer fold is byte-for-byte FNV-1a (over the 4-byte big-endian
    block CRCs), so the stamp stays one 64-bit word and any single-bit
    flip anywhere in the image changes it; the inner CRC loop runs in C
    (zlib), so stamping a multi-MB optimizer state costs milliseconds,
    not the seconds a pure-Python FNV over every byte would."""
    mv = memoryview(view)
    if mv.format != "B" or mv.ndim != 1:
        mv = mv.cast("B")
    h = _FNV_OFFSET
    for off in range(0, mv.nbytes, _DIGEST_BLOCK):
        crc = zlib.crc32(mv[off:off + _DIGEST_BLOCK])
        h = fnv1a_fold(crc.to_bytes(4, "big"), h)
    return h


@dataclasses.dataclass(frozen=True)
class SnapshotStamp:
    """Integrity stamp every donor attaches to its META frame and the
    joiner verifies before any streamed byte is interpreted."""
    epoch: str
    step: int
    digest: int
    nbytes: int

    def as_meta(self) -> dict:
        return {"epoch": self.epoch, "step": self.step,
                "digest": self.digest, "nbytes": self.nbytes}

    @classmethod
    def from_meta(cls, meta: dict) -> "SnapshotStamp":
        return cls(epoch=str(meta["epoch"]), step=int(meta["step"]),
                   digest=int(meta["digest"]), nbytes=int(meta["nbytes"]))


def _leaves(tree: Any) -> list:
    import jax

    return jax.tree_util.tree_leaves(tree)


def state_nbytes(tree: Any) -> int:
    return sum(np.asarray(leaf).nbytes for leaf in _leaves(tree))


def flatten_state(tree: Any) -> bytearray:
    """One contiguous byte image of the pytree's leaves in
    ``jax.tree_util`` order, each leaf in its own dtype.  The returned
    buffer is a COPY — the caller's live arrays are never aliased, which
    is what lets donors stream while training keeps mutating."""
    out = bytearray(state_nbytes(tree))
    view = memoryview(out)
    offset = 0
    for leaf in _leaves(tree):
        arr = np.ascontiguousarray(np.asarray(leaf))
        n = arr.nbytes
        view[offset:offset + n] = arr.view(np.uint8).reshape(-1).data
        offset += n
    return out


def unflatten_state(buf, template: Any) -> Any:
    """Rebuild a pytree shaped like ``template`` from a flat byte image
    (the inverse of :func:`flatten_state`).  Every caller must have
    digest-verified ``buf`` first — see HVD1007."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(template)
    view = memoryview(buf)
    if view.format != "B" or view.ndim != 1:
        view = view.cast("B")
    total = sum(np.asarray(leaf).nbytes for leaf in leaves)
    if view.nbytes != total:
        raise ValueError(
            f"state image is {view.nbytes} bytes but the template "
            f"flattens to {total}; the streamed state does not match "
            f"this rank's model")
    out = []
    offset = 0
    for leaf in leaves:
        ref = np.asarray(leaf)
        n = ref.nbytes
        arr = np.frombuffer(view[offset:offset + n],
                            dtype=ref.dtype).reshape(ref.shape).copy()
        out.append(arr)
        offset += n
    return jax.tree_util.tree_unflatten(treedef, out)


class Snapshot:
    """A frozen, stamped state image taken at one step boundary."""

    def __init__(self, tree: Any, epoch: str, step: int) -> None:
        self.data = flatten_state(tree)
        self.stamp = SnapshotStamp(epoch=epoch, step=int(step),
                                   digest=state_digest(self.data),
                                   nbytes=len(self.data))

    def __len__(self) -> int:
        return len(self.data)


# ---------------------------------------------------------------------------
# Ring-shard (ZeRO) re-layout (PR 6 sync_and_apply shard discipline)
# ---------------------------------------------------------------------------
def ring_chunk(n_params: int, world: int, config=None) -> int:
    """Per-rank flat shard length for a given world size — delegates to
    grad_sync.ring_chunk_size so checkpoint/statesync and the live
    optimizer-in-ring path can never disagree on the layout."""
    from ..parallel.grad_sync import GradSyncConfig, ring_chunk_size

    return ring_chunk_size(n_params, world,
                           config if config is not None
                           else GradSyncConfig())


def concat_ring_shards(shards: list, n_params: int) -> np.ndarray:
    """Concatenate per-rank 1-D shard arrays back into the unpadded
    flat buffer (drops the world x chunk padding tail)."""
    full = np.concatenate([np.asarray(s).reshape(-1) for s in shards])
    if full.size < n_params:
        raise ValueError(
            f"shards cover {full.size} elements < n_params={n_params}")
    return full[:n_params]


def shard_for_rank(full: np.ndarray, n_params: int, world: int,
                   rank: int, config=None) -> np.ndarray:
    """Rank ``rank``'s shard of the flat buffer under the ``world``-way
    ring layout (zero-padded tail on the last shard, exactly like
    sync_and_apply's padded reduce-scatter)."""
    chunk = ring_chunk(n_params, world, config)
    padded = np.zeros(chunk * world, dtype=full.dtype)
    padded[:n_params] = np.asarray(full).reshape(-1)[:n_params]
    return padded[rank * chunk:(rank + 1) * chunk].copy()


def reshard_ring_state(shards: list, n_params: int, new_world: int,
                       new_rank: int, config=None) -> Any:
    """Re-cut a full set of per-rank optimizer-state shard pytrees
    (old world = ``len(shards)``) into ``new_rank``'s shard for a
    ``new_world``-way layout.

    Array leaves whose first dimension equals the OLD chunk length are
    ring-sharded state (adam's m/v, master params): their per-rank
    pieces concatenate to the full flat buffer, which is re-padded and
    re-sliced for the new layout.  Everything else (step counters,
    scalar hyperparameters) is replicated state: taken from shard 0 and
    asserted identical across shards."""
    import jax

    old_world = len(shards)
    if old_world == 0:
        raise ValueError("need at least one shard to reshard")
    chunk_old = ring_chunk(n_params, old_world, config)
    leaves_by_rank = [jax.tree_util.tree_flatten(s) for s in shards]
    treedef = leaves_by_rank[0][1]
    for _, td in leaves_by_rank[1:]:
        if td != treedef:
            raise ValueError("shard pytrees disagree on structure")
    out = []
    for i, leaf0 in enumerate(leaves_by_rank[0][0]):
        ref = np.asarray(leaf0)
        if ref.ndim >= 1 and ref.shape[0] == chunk_old:
            full = concat_ring_shards(
                [lv[0][i] for lv in leaves_by_rank], n_params)
            out.append(shard_for_rank(full, n_params, new_world,
                                      new_rank, config))
        else:
            for lv, _ in leaves_by_rank[1:]:
                if not np.array_equal(np.asarray(lv[i]), ref):
                    raise ValueError(
                        "replicated optimizer-state leaf differs "
                        "across shards (index %d); the shard files are "
                        "from different steps" % i)
            out.append(ref.copy())
    return jax.tree_util.tree_unflatten(treedef, out)
