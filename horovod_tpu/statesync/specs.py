"""Protocol specs for the statesync membership machinery (hvdmc DSL).

Three specs, co-located with the implementation they bind to so a
protocol change and its spec change land in one diff — the HVD506
conformance pass (``analysis/hvdmc/conformance.py``) fails the tree
when they drift in either direction:

- :func:`stream_spec` — the STATE_MAGIC peer-streaming wire protocol
  (``stream.py`` over the frame verbs in ``common/tcp_transport.py``);
- :func:`grow_spec` — the step-synchronous membership boundary and the
  zero-downtime grow transition (``service.py`` + the joiner half of
  ``join_world``);
- :func:`preempt_spec` — SIGTERM preemption grace: boundary departure,
  proactive survivor shrink, and the backstop timer.

The specs are pure data (no runtime imports): the model checker
(``python -m horovod_tpu.analysis.mc``) explores executable semantics
labeled with these transition ids, and the trace witness replays mp
battery flight logs against them via the ``observe`` event kinds.
"""
from __future__ import annotations

from ..analysis.hvdmc.spec import ProtocolSpec, Transition, Verb

__all__ = ["grow_spec", "preempt_spec", "stream_spec"]

_TCPT = "common/tcp_transport.py"
_SERVICE = "statesync.service"
_STREAM = "statesync.stream"
_SVC = f"{_SERVICE}.StateSyncService"


def stream_spec() -> ProtocolSpec:
    """STATE_MAGIC streaming: joiner pulls disjoint, CRC-checked shards
    of a stamped snapshot from every donor; nothing is *state* until the
    assembled image reproduces the donors' unanimous stamp."""
    verbs = (
        Verb("HELLO", "frame", "STATE_HELLO", _TCPT,
             "joiner -> donor: open a snapshot round"),
        Verb("META", "frame", "STATE_META", _TCPT,
             "donor -> joiner: the snapshot stamp + byte total"),
        Verb("REQ", "frame", "STATE_REQ", _TCPT,
             "joiner -> donor: request a byte range"),
        Verb("DATA", "frame", "STATE_DATA", _TCPT,
             "donor -> joiner: one CRC-stamped chunk"),
        Verb("END", "frame", "STATE_END", _TCPT,
             "donor -> joiner: requested range fully streamed"),
        Verb("BYE", "frame", "STATE_BYE", _TCPT,
             "joiner -> donor: transfer complete, stand down"),
    )
    transitions = (
        Transition("donor.hello", "donor", "serving", "serving",
                   "recv:HELLO",
                   binds=(f"{_STREAM}.DonorServer._serve",),
                   doc="block until the wanted snapshot round arrives"),
        Transition("donor.send-meta", "donor", "serving", "serving",
                   "send:META",
                   binds=(f"{_STREAM}.DonorServer._serve",)),
        Transition("donor.serve-range", "donor", "serving", "serving",
                   "recv:REQ",
                   binds=(f"{_STREAM}.DonorServer._serve",)),
        Transition("donor.send-data", "donor", "serving", "serving",
                   "send:DATA",
                   binds=(f"{_STREAM}.DonorServer._serve_range",)),
        Transition("donor.send-end", "donor", "serving", "serving",
                   "send:END",
                   binds=(f"{_STREAM}.DonorServer._serve_range",)),
        Transition("donor.bye", "donor", "serving", "done", "recv:BYE",
                   binds=(f"{_STREAM}.DonorServer._serve",)),
        Transition("donor.round-timeout", "donor", "serving", "done",
                   "fault:joiner-lost",
                   binds=(f"{_STREAM}.DonorServer.run",),
                   doc="joiner death/deadline: stand down quietly; the "
                       "main thread's world was never blocked on this"),
        Transition("join.hello", "joiner", "connect", "hello",
                   "send:HELLO",
                   binds=(f"{_STREAM}.JoinerPuller._collect_metas",)),
        Transition("join.meta", "joiner", "hello", "metas", "recv:META",
                   binds=(f"{_STREAM}.JoinerPuller._collect_metas",)),
        Transition("join.stamps-ok", "joiner", "metas", "pull",
                   "internal:stamps-unanimous",
                   guard="stamps-unanimous",
                   binds=(f"{_STREAM}.JoinerPuller._collect_metas",)),
        Transition("join.torn-reject", "joiner", "metas", "aborted",
                   "internal:torn-stamp",
                   guard="stamps-unanimous", observe="torn-reject",
                   binds=(f"{_STREAM}.JoinerPuller._collect_metas",),
                   doc="donors cut at different steps: reject the whole "
                       "round before a single byte is interpreted"),
        Transition("join.req", "joiner", "pull", "pull", "send:REQ",
                   binds=(f"{_STREAM}.JoinerPuller._pull_range",)),
        Transition("join.data", "joiner", "pull", "pull", "recv:DATA",
                   guard="chunk-crc",
                   binds=(f"{_STREAM}.JoinerPuller._pull_range",)),
        Transition("join.end", "joiner", "pull", "pull", "recv:END",
                   binds=(f"{_STREAM}.JoinerPuller._pull_range",)),
        Transition("join.crc-reject", "joiner", "pull", "aborted",
                   "internal:crc-mismatch", guard="chunk-crc",
                   observe="torn-reject",
                   binds=(f"{_STREAM}.JoinerPuller._pull_range",)),
        Transition("join.donor-died", "joiner", "pull", "pull",
                   "fault:donor-death",
                   binds=(f"{_STREAM}.JoinerPuller.pull_round",),
                   doc="reassign the dead donor's unfinished tail to a "
                       "survivor (chunk-granular resume)"),
        Transition("join.verify", "joiner", "pull", "verified",
                   "internal:digest-verifies", guard="digest-verifies",
                   binds=(f"{_STREAM}.JoinerPuller.pull_round",
                          f"{_STREAM}.JoinerPuller.verify_round")),
        Transition("join.digest-reject", "joiner", "pull", "aborted",
                   "internal:digest-mismatch", guard="digest-verifies",
                   observe="torn-reject",
                   binds=(f"{_STREAM}.JoinerPuller.verify_round",)),
        Transition("join.bye", "joiner", "verified", "done", "send:BYE",
                   binds=(f"{_STREAM}.JoinerPuller.close",)),
    )
    return ProtocolSpec(
        name="statesync-stream",
        doc="STATE_MAGIC peer state streaming (docs/statesync.md)",
        roles=("donor", "joiner"),
        states={"donor": ("idle", "serving", "done"),
                "joiner": ("connect", "hello", "metas", "pull",
                           "verified", "done", "aborted")},
        verbs=verbs,
        transitions=(
            Transition("donor.mesh-join", "donor", "idle", "serving",
                       "internal:mesh-formed",
                       binds=(f"{_STREAM}.DonorServer._serve",)),
        ) + transitions,
        anchor_modules=(_STREAM, "common.tcp_transport"),
        properties={
            "no-torn-commit":
                "an image is consumed only after it reproduces the "
                "donors' unanimous (epoch, step, digest) stamp",
            "resumable":
                "a donor death mid-stream never loses committed chunks",
        })


def grow_spec() -> ProtocolSpec:
    """Step-synchronous membership boundary + the grow transition."""
    verbs = (
        Verb("JOIN", "kv", "join:", doc="joiner's announcement record"),
        Verb("READY", "kv", "ready:",
             doc="joiner's bulk image digest-verified"),
        Verb("GO", "kv", "go:",
             doc="rank 0's grow commit: new epoch/size/rank/seq"),
        Verb("WORLD", "kv", "world",
             doc="rank 0's world identity record"),
        Verb("JOINFLAG", "flag", "join",
             doc="boundary-allgather field: locally watched join id"),
        Verb("READYFLAG", "flag", "ready",
             doc="boundary-allgather field: locally watched ready id"),
        Verb("DEPARTFLAG", "flag", "depart",
             doc="boundary-allgather field: SIGTERM departure intent"),
    )
    transitions = (
        # -- incumbent ---------------------------------------------------
        Transition("inc.step", "incumbent", "run", "bound",
                   "internal:step", binds=(f"{_SVC}.step_boundary",)),
        Transition("inc.watch-join", "incumbent", "run", "run",
                   "kv:JOIN", binds=(f"{_SVC}._watch_once",)),
        Transition("inc.watch-ready", "incumbent", "run", "run",
                   "kv:READY", binds=(f"{_SVC}._watch_once",)),
        Transition("inc.boundary-idle", "incumbent", "bound", "run",
                   "boundary", binds=(f"{_SVC}.step_boundary",)),
        Transition("inc.boundary-admit", "incumbent", "bound", "run",
                   "boundary", guard="single-active-join",
                   observe="donate",
                   binds=(f"{_SVC}.step_boundary",
                          f"{_SVC}._start_donation"),
                   doc="every rank snapshots at the SAME boundary the "
                       "merged exchange admitted the join at"),
        Transition("inc.boundary-grow", "incumbent", "bound", "rebuild",
                   "boundary", guard="joiner-ready-verified",
                   requires_calls=("reinit_world",), observe="grow",
                   binds=(f"{_SVC}._transition_grow",)),
        Transition("inc.post-go", "incumbent", "rebuild", "rebuild",
                   "kv:GO", binds=(f"{_SVC}._transition_grow",)),
        Transition("inc.world-formed", "incumbent", "rebuild", "run",
                   "internal:mesh-formed",
                   binds=(f"{_SVC}._refresh_world",)),
        Transition("inc.publish-world", "incumbent", "run", "run",
                   "kv:WORLD", binds=(f"{_SVC}._refresh_world",)),
        Transition("inc.formation-timeout", "incumbent", "rebuild",
                   "failed", "fault:joiner-lost",
                   binds=(f"{_SVC}._transition_grow",),
                   doc="joiner died after GO: the N+1 mesh formation "
                       "times out into a structured, detected failure "
                       "(never a silent wedge)"),
        # -- joiner ------------------------------------------------------
        Transition("join.announce", "joiner", "idle", "announced",
                   "kv:JOIN", observe="join-announce",
                   binds=(f"{_SERVICE}.join_world",)),
        Transition("join.bulk", "joiner", "announced", "bulk",
                   "internal:bulk-stream",
                   binds=(f"{_SERVICE}.join_world",),
                   doc="the statesync-stream machine runs here"),
        Transition("join.bulk-abort", "joiner", "bulk", "aborted",
                   "internal:stream-failed",
                   binds=(f"{_SERVICE}.join_world",)),
        Transition("join.post-ready", "joiner", "bulk", "ready",
                   "kv:READY", guard="ready-after-verify",
                   observe="join-ready",
                   binds=(f"{_SERVICE}.join_world",),
                   doc="ready is posted ONLY after the bulk image "
                       "digest-verified — the boundary ack mutation "
                       "the checker must catch drops this guard"),
        Transition("join.see-go", "joiner", "ready", "final", "kv:GO",
                   binds=(f"{_SERVICE}.join_world",)),
        Transition("join.final-abort", "joiner", "final", "aborted",
                   "internal:stream-failed",
                   binds=(f"{_SERVICE}.join_world",)),
        Transition("join.enter", "joiner", "final", "entered",
                   "internal:enter-world",
                   requires_calls=("reinit_world",),
                   observe="join-entered",
                   binds=(f"{_SERVICE}.join_world",)),
        # -- injected faults ---------------------------------------------
        Transition("net.flag-drop", "net", "env", "env",
                   "fault:flag-drop",
                   doc="one rank's boundary-exchange receipt is lost: "
                       "it admits the join one boundary late and "
                       "donates a later-step snapshot (the torn hazard "
                       "the stamp-equality guard contains)"),
        Transition("net.chunk-corrupt", "net", "env", "env",
                   "fault:chunk-corrupt"),
        Transition("net.donor-death", "net", "env", "env",
                   "fault:donor-death"),
        Transition("net.crash-joiner", "net", "env", "env",
                   "fault:crash"),
    )
    return ProtocolSpec(
        name="statesync-grow",
        doc="membership boundary + zero-downtime grow "
            "(docs/statesync.md)",
        roles=("incumbent", "joiner", "net"),
        states={"incumbent": ("run", "bound", "rebuild", "failed"),
                "joiner": ("idle", "announced", "bulk", "ready",
                           "final", "entered", "aborted", "crashed"),
                "net": ("env",)},
        verbs=verbs,
        transitions=transitions,
        anchor_modules=(_SERVICE,),
        properties={
            "torn-commit":
                "the joiner never enters the world with an image whose "
                "donor stamps disagree",
            "premature-boundary-ack":
                "incumbents commit the grow boundary only after the "
                "joiner's bulk image digest-verified",
            "boundary-agreement":
                "all live ranks converge on the same membership at the "
                "same boundary seq",
            "resolution-reachable":
                "from every reachable state the join can still "
                "complete, abort cleanly, or fail detected",
        })


def preempt_spec() -> ProtocolSpec:
    """SIGTERM preemption grace: announce at the boundary, donate,
    depart with a ``bye|`` stamp; survivors shrink proactively; the
    backstop timer bounds a wedged step."""
    verbs = (
        Verb("DEPARTFLAG", "flag", "depart",
             doc="boundary-allgather field: departure intent"),
        Verb("DONATE", "kv", "ssdonate.",
             doc="fast-donated opt-shard records (digest-stamped)"),
    )
    transitions = (
        Transition("pre.sigterm", "preemptee", "run", "grace",
                   "internal:sigterm", observe="sigterm-grace",
                   binds=(f"{_SVC}._on_sigterm",)),
        Transition("pre.sigterm-dup", "preemptee", "grace", "grace",
                   "internal:sigterm",
                   binds=(f"{_SVC}._on_sigterm",),
                   doc="a second SIGTERM mid-grace is idempotent"),
        Transition("pre.finish-step", "preemptee", "grace", "bound",
                   "internal:step", binds=(f"{_SVC}.step_boundary",)),
        Transition("pre.fast-donate", "preemptee", "bound", "bound",
                   "kv:DONATE", binds=(f"{_SVC}._fast_donate",)),
        Transition("pre.depart", "preemptee", "bound", "departed",
                   "boundary", guard="depart-at-boundary",
                   requires_calls=("shutdown",), observe="departed",
                   binds=(f"{_SVC}._transition_depart",),
                   doc="orderly: the monitor stop writes the bye| "
                       "stamp; peers read a goodbye, never silence"),
        Transition("pre.wedge", "preemptee", "grace", "wedged",
                   "fault:wedge",
                   binds=(f"{_SVC}._grace_expired",),
                   doc="the in-flight step never reaches a boundary"),
        Transition("pre.backstop", "preemptee", "wedged", "exited143",
                   "internal:grace-expired",
                   requires_calls=("_exit",),
                   observe="sigterm-grace-expired",
                   binds=(f"{_SVC}._grace_expired",)),
        Transition("sur.step", "survivor", "run", "bound",
                   "internal:step", binds=(f"{_SVC}.step_boundary",)),
        Transition("sur.boundary-idle", "survivor", "bound", "run",
                   "boundary", binds=(f"{_SVC}.step_boundary",)),
        Transition("sur.proactive-shrink", "survivor", "bound", "run",
                   "boundary", guard="depart-announced",
                   requires_calls=("reinit_world",),
                   observe="shrink-proactive",
                   binds=(f"{_SVC}._transition_depart",)),
        Transition("sur.deadline-fail", "survivor", "bound",
                   "failcaught", "fault:peer-dead",
                   binds=(f"{_SVC}.shrink_on_failure",)),
        Transition("sur.converge-shrink", "survivor", "failcaught",
                   "run", "internal:confirmed-dead",
                   guard="confirmed-only",
                   requires_calls=("converge_confirmed_dead",
                                   "reinit_world"),
                   observe="shrink",
                   binds=(f"{_SVC}.shrink_on_failure",)),
    )
    return ProtocolSpec(
        name="statesync-preempt",
        doc="SIGTERM preemption grace (docs/statesync.md)",
        roles=("preemptee", "survivor"),
        states={"preemptee": ("run", "grace", "bound", "wedged",
                              "departed", "exited143"),
                "survivor": ("run", "bound", "failcaught")},
        verbs=verbs,
        transitions=transitions,
        anchor_modules=(_SERVICE,),
        properties={
            "bye-before-exit":
                "the preempted rank never exits without its bye| stamp "
                "(orderly boundary departure or the backstop)",
            "no-failure-on-clean-path":
                "when the departure is announced at a boundary, no "
                "survivor ever raises RanksFailedError",
            "survivors-converge":
                "survivors always reach the N-1 world",
        })
