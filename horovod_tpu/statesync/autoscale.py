"""Autoscale policy loop: drive the elastic driver's target world size
from live telemetry, with hysteresis.

The policy half (:class:`AutoscalePolicy`) is pure decision logic —
unit-testable with scripted observations.  The controller half
(:class:`AutoscaleController`) is a rank-0 daemon thread that samples a
gauge source every ``HOROVOD_AUTOSCALE_INTERVAL_S`` seconds and applies
decisions to the elastic driver (``ElasticDriver.set_target_np``).

Inputs (ISSUE 10 / ROADMAP item 4):

- **queue depth** — the controller tensor-queue gauge or the serving
  ingress depth: a persistently deep queue means the world is
  under-provisioned for the offered load → scale UP;
- **shed rate** — the serving admission controller's load sheds per
  interval: sustained shedding is the capacity signal SLOs care about
  → scale UP;
- **straggler lag** — PR 4's coordinator arrival-lag gauge: one rank
  persistently dragging the whole world while the queue is idle means
  the marginal rank costs more step time than its share of the work is
  worth → scale DOWN (past the straggler).

Hysteresis: a condition must hold ``HOROVOD_AUTOSCALE_HYSTERESIS_ROUNDS``
consecutive intervals to fire, and every decision starts an equal
cooldown — one burst never flaps the world size.  Every decision is
itself observable: a ``horovod_autoscale_decisions_total{direction}``
counter, a ``horovod_autoscale_target`` gauge, and a flight-recorder
event (kind ``autoscale``), so a post-mortem can replay why the world
resized.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable

from ..common import config
from ..common.logging import logger

__all__ = ["AutoscaleController", "AutoscaleDecision", "AutoscalePolicy",
           "registry_source"]


@dataclasses.dataclass(frozen=True)
class AutoscaleDecision:
    direction: str                 # "up" | "down"
    target: int
    reason: str


class AutoscalePolicy:
    """Hysteresis-gated target-size decisions from gauge observations."""

    def __init__(self, min_np: int, max_np: int, *,
                 up_shed_rate: float | None = None,
                 up_queue_fraction: float | None = None,
                 down_lag_ms: float | None = None,
                 hysteresis_rounds: int | None = None,
                 queue_depth_limit: int | None = None) -> None:
        self.min_np = int(min_np)
        self.max_np = int(max_np)
        self.up_shed_rate = config.AUTOSCALE_UP_SHED_RATE.get() \
            if up_shed_rate is None else float(up_shed_rate)
        self.up_queue_fraction = config.AUTOSCALE_UP_QUEUE_FRACTION.get() \
            if up_queue_fraction is None else float(up_queue_fraction)
        self.down_lag_ms = config.AUTOSCALE_DOWN_LAG_MS.get() \
            if down_lag_ms is None else float(down_lag_ms)
        self.hysteresis_rounds = \
            config.AUTOSCALE_HYSTERESIS_ROUNDS.get() \
            if hysteresis_rounds is None else int(hysteresis_rounds)
        self.queue_depth_limit = config.SERVE_QUEUE_DEPTH.get() \
            if queue_depth_limit is None else int(queue_depth_limit)
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = 0

    def observe(self, current: int, *, queue_depth: float = 0.0,
                shed_rate: float = 0.0,
                straggler_lag_ms: float = 0.0) -> AutoscaleDecision | None:
        """Feed one interval's gauges; returns a decision when the
        hysteresis gate opens, else None."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        queue_frac = queue_depth / max(self.queue_depth_limit, 1)
        overload = (shed_rate > self.up_shed_rate
                    or queue_frac > self.up_queue_fraction)
        dragging = (straggler_lag_ms > self.down_lag_ms
                    and shed_rate == 0.0
                    and queue_frac < self.up_queue_fraction / 2.0)
        self._up_streak = self._up_streak + 1 if overload else 0
        self._down_streak = self._down_streak + 1 if dragging else 0
        if self._up_streak >= self.hysteresis_rounds \
                and current < self.max_np:
            self._reset_streaks()
            return AutoscaleDecision(
                "up", current + 1,
                f"shed_rate={shed_rate:.3f} queue_frac={queue_frac:.2f} "
                f"sustained {self.hysteresis_rounds} intervals")
        if self._down_streak >= self.hysteresis_rounds \
                and current > self.min_np:
            self._reset_streaks()
            return AutoscaleDecision(
                "down", current - 1,
                f"straggler_lag={straggler_lag_ms:.1f}ms with idle "
                f"queue, sustained {self.hysteresis_rounds} intervals")
        return None

    def _reset_streaks(self) -> None:
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = self.hysteresis_rounds


def registry_source(registry) -> Callable[[], dict]:
    """Build a gauge source over a telemetry registry: reads the
    queue-depth and straggler-lag gauges plus the serving outcome
    counters (shed rate computed as the per-interval delta)."""
    state = {"shed": 0.0, "offered": 0.0}

    def _value(name: str, labels: dict | None = None) -> float:
        try:
            if labels:
                return registry.counter(name, labels=labels).value
            return registry.gauge(name).value
        except Exception:  # noqa: BLE001 - absent metric reads as 0
            return 0.0

    def _sample() -> dict:
        shed = _value("horovod_serve_requests_total",
                      {"outcome": "shed"}) + \
            _value("horovod_serve_requests_total",
                   {"outcome": "expired"})
        served = _value("horovod_serve_requests_total",
                        {"outcome": "served"})
        offered = shed + served
        d_shed = shed - state["shed"]
        d_offered = offered - state["offered"]
        state["shed"], state["offered"] = shed, offered
        return {
            "queue_depth": max(
                _value("horovod_serve_queue_depth"),
                _value("horovod_controller_tensor_queue_depth")),
            "shed_rate": (d_shed / d_offered) if d_offered > 0 else 0.0,
            "straggler_lag_ms": _value(
                "horovod_controller_straggler_lag_ms"),
        }

    return _sample


def http_source(url: str, timeout: float = 2.0) -> Callable[[], dict]:
    """Build a gauge source over a rank's Prometheus exposition endpoint
    (`HOROVOD_METRICS_PORT`) — what the LAUNCHER-side controller uses:
    the gauges live in the rank processes, not the driver process.
    Unreachable scrapes read as all-zero (the policy simply observes an
    idle interval)."""
    state = {"shed": 0.0, "offered": 0.0}

    def _scrape() -> dict[str, float]:
        from urllib import request as urlrequest

        out: dict[str, float] = {}
        try:
            with urlrequest.urlopen(url, timeout=timeout) as resp:
                text = resp.read().decode(errors="replace")
        except Exception:  # noqa: BLE001 - endpoint down: idle sample
            return out
        for line in text.splitlines():
            if line.startswith("#") or " " not in line:
                continue
            name_part, _, value = line.rpartition(" ")
            try:
                out[name_part] = float(value)
            except ValueError:
                continue
        return out

    def _sample() -> dict:
        m = _scrape()

        def total(prefix: str, label: str) -> float:
            return sum(v for k, v in m.items()
                       if k.startswith(prefix) and label in k)

        shed = total("horovod_serve_requests_total",
                     'outcome="shed"') + \
            total("horovod_serve_requests_total", 'outcome="expired"')
        served = total("horovod_serve_requests_total",
                       'outcome="served"')
        offered = shed + served
        d_shed = shed - state["shed"]
        d_offered = offered - state["offered"]
        state["shed"], state["offered"] = shed, offered
        return {
            "queue_depth": max(
                m.get("horovod_serve_queue_depth", 0.0),
                m.get("horovod_controller_tensor_queue_depth", 0.0)),
            "shed_rate": (d_shed / d_offered) if d_offered > 0 else 0.0,
            "straggler_lag_ms": m.get(
                "horovod_controller_straggler_lag_ms", 0.0),
        }

    return _sample


class AutoscaleController(threading.Thread):
    """Rank-0 daemon: sample → decide → drive the elastic driver."""

    def __init__(self, driver, source: Callable[[], dict],
                 policy: AutoscalePolicy, *,
                 interval: float | None = None,
                 current_size: Callable[[], int] | None = None) -> None:
        super().__init__(daemon=True, name="hvd-autoscale")
        self.driver = driver
        self.source = source
        self.policy = policy
        self.interval = config.AUTOSCALE_INTERVAL_SECONDS.get() \
            if interval is None else float(interval)
        self._current_size = current_size or driver.world_size
        self._stop = threading.Event()
        self.decisions: list[AutoscaleDecision] = []
        from ..telemetry import flight, metrics

        self._flight = flight.recorder()
        tm = metrics()
        self._m_decisions = {
            d: tm.counter(
                "horovod_autoscale_decisions_total",
                "Autoscale policy decisions applied to the elastic "
                "driver's target world size", labels={"direction": d})
            for d in ("up", "down")}
        self._m_target = tm.gauge(
            "horovod_autoscale_target",
            "World size the autoscale policy currently asks the "
            "elastic driver for")

    def tick(self) -> AutoscaleDecision | None:
        """One sample→decide→apply round (called by the loop, and
        directly by tests)."""
        gauges = self.source()
        current = self._current_size()
        decision = self.policy.observe(
            current, queue_depth=float(gauges.get("queue_depth", 0.0)),
            shed_rate=float(gauges.get("shed_rate", 0.0)),
            straggler_lag_ms=float(gauges.get("straggler_lag_ms", 0.0)))
        if decision is None:
            return None
        self.decisions.append(decision)
        self.driver.set_target_np(decision.target)
        self._m_decisions[decision.direction].inc()
        self._m_target.set(decision.target)
        if self._flight.enabled:
            self._flight.record("autoscale", decision.direction,
                                detail=f"target={decision.target}: "
                                       f"{decision.reason}")
        logger.warning("autoscale: scale %s -> target %d (%s)",
                       decision.direction, decision.target,
                       decision.reason)
        return decision

    def run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - controller must survive
                logger.debug("autoscale: tick failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        # Reap the loop (hvdlife HVD701): the event is its wakeup (the
        # run loop polls it every interval).  tick() can call into
        # code that stops the controller — never self-join.
        if self.is_alive() and \
                self is not threading.current_thread():
            self.join(timeout=self.interval + 5.0)
