"""Elastic training on Ray: cluster-state discovery + actor-based workers.

Reference: horovod/ray/elastic.py:38-465 — ``RayHostDiscovery`` feeds the
ElasticDriver from Ray's live node table instead of a discovery script,
and ``ElasticRayExecutor`` bridges driver slot lifecycle to Ray actors
(one per slot, re-created on membership changes). The driver machinery —
rounds, blacklist, stable rank re-assignment, worker notification — is the
same stack the CLI elastic path uses (elastic/driver.py).
"""
from __future__ import annotations

import os
import pickle
from collections import OrderedDict
from typing import Any, Callable

from ..elastic.discovery import HostDiscovery
from ..elastic.driver import ElasticDriver
from ..elastic.rpc import RpcServer, make_secret
from ..elastic.worker import DRIVER_ADDR_ENV, DRIVER_PORT_ENV, SECRET_ENV
from ..runner.hosts import SlotInfo
from ..runner.network import RendezvousServer

__all__ = ["RayHostDiscovery", "ElasticRayExecutor"]


class RayHostDiscovery(HostDiscovery):
    """Discover hosts/slots from Ray's live cluster state
    (reference: ray/elastic.py:38-83 RayHostDiscovery)."""

    def __init__(self, use_gpu: bool = False, cpus_per_slot: int = 1,
                 gpus_per_slot: int = 1) -> None:
        self.use_gpu = use_gpu
        self.cpus_per_slot = cpus_per_slot
        self.gpus_per_slot = gpus_per_slot

    def find_available_hosts_and_slots(self) -> "OrderedDict[str, int]":
        import ray

        hosts: "OrderedDict[str, int]" = OrderedDict()
        for node in ray.nodes():
            if not node.get("Alive", False):
                continue
            resources = node.get("Resources", {})
            slots = int(resources.get("CPU", 0)) // self.cpus_per_slot
            if self.use_gpu:
                gpu_slots = int(resources.get("GPU", 0)) \
                    // self.gpus_per_slot
                slots = min(slots, gpu_slots)
            if slots > 0:
                hostname = node.get("NodeManagerHostname") \
                    or node.get("NodeManagerAddress")
                hosts[hostname] = slots
        return hosts


class ElasticRayExecutor:
    """Run an elastic training function over Ray actors
    (reference: ray/elastic.py:86-465 ElasticRayExecutor).

    >>> executor = ElasticRayExecutor(min_np=2, max_np=4)
    >>> executor.start()
    >>> results = executor.run(train_fn)
    """

    def __init__(self, min_np: int = 1, max_np: int | None = None,
                 cpus_per_slot: int = 1, use_gpu: bool = False,
                 reset_limit: int | None = None,
                 elastic_timeout: float = 600.0,
                 override_discovery: HostDiscovery | None = None) -> None:
        self.min_np = min_np
        self.max_np = max_np
        self.cpus_per_slot = cpus_per_slot
        self.use_gpu = use_gpu
        self.reset_limit = reset_limit
        self.elastic_timeout = elastic_timeout
        self.discovery = override_discovery or RayHostDiscovery(
            use_gpu=use_gpu, cpus_per_slot=cpus_per_slot)
        self.driver: ElasticDriver | None = None
        self._rendezvous: RendezvousServer | None = None
        self._rpc: RpcServer | None = None
        self._secret = make_secret()
        self._results: list = []

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self.driver = ElasticDriver(
            self.discovery, min_np=self.min_np, max_np=self.max_np,
            timeout=self.elastic_timeout, reset_limit=self.reset_limit,
            secret=self._secret)
        self._rendezvous = RendezvousServer()
        self._rendezvous.start()
        self._rpc = RpcServer(self.driver, self._secret)

    def _slot_env(self, slot: SlotInfo, addr: str) -> dict:
        return {
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_CONTROLLER": "tcp",
            "HOROVOD_HOSTNAME": slot.hostname,
            "HOROVOD_LOCAL_RANK": str(slot.local_rank),
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": addr,
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(self._rendezvous.port),
            DRIVER_ADDR_ENV: addr,
            DRIVER_PORT_ENV: str(self._rpc.port),
            SECRET_ENV: self._secret,
        }

    def _make_create_worker(self, fn: Callable, addr: str) -> Callable:
        """create_worker_fn for the driver: one Ray actor per slot, pinned
        to the slot's node, blocking until the actor's run completes."""
        import ray

        executor = self

        def create_worker(slot: SlotInfo) -> int:
            options: dict = {
                "num_cpus": executor.cpus_per_slot,
                "num_gpus": executor.gpus_per_slot
                if executor.use_gpu else 0,
                "max_restarts": 0,
            }
            if executor._pin_by_node:
                # Ray's per-node custom resource pins the actor to the
                # slot's host (reference: ray/elastic.py actor placement).
                options["resources"] = {f"node:{slot.hostname}": 0.001}

            @ray.remote
            class _ElasticWorker:
                def run(self, payload: bytes, env: dict):
                    import os as _os
                    _os.environ.update(env)
                    func = pickle.loads(payload)
                    return func()

            actor = _ElasticWorker.options(**options).remote()
            try:
                result = ray.get(actor.run.remote(
                    pickle.dumps(fn), executor._slot_env(slot, addr)))
                executor._results.append((slot.rank, result))
                return 0
            except Exception:  # noqa: BLE001 - actor/worker death = retry
                return 1
            finally:
                ray.kill(actor, no_restart=True)

        return create_worker

    _pin_by_node = True

    def run(self, fn: Callable) -> list:
        """Run ``fn()`` on every slot until the job completes; returns
        results rank-ordered from the final successful round."""
        import socket

        assert self.driver is not None, "call start() first"
        hosts = self.discovery.find_available_hosts_and_slots()
        local_only = all(h in ("localhost", "127.0.0.1", socket.gethostname())
                         for h in hosts)
        addr = "127.0.0.1" if local_only else socket.getfqdn()

        np0 = min(self.max_np or self.min_np, self.min_np)
        try:
            self.driver.start(np0, self._make_create_worker(fn, addr))
            self.driver.join()
        finally:
            self.shutdown(stop_driver=False)
        self._results.sort(key=lambda pair: pair[0])
        return [value for _rank, value in self._results]

    def shutdown(self, stop_driver: bool = True) -> None:
        if self.driver is not None and stop_driver:
            self.driver.shutdown()
        if self._rpc is not None:
            self._rpc.close()
            self._rpc = None
        if self._rendezvous is not None:
            self._rendezvous.stop()
            self._rendezvous = None
