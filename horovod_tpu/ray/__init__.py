"""Ray integration (reference: horovod/ray/runner.py RayExecutor).

Gated on ray being importable.  The executor places one worker actor per
slot, computes the same HOROVOD_RANK/LOCAL_RANK/CROSS_RANK env contract as
the CLI launcher from actor hostnames, starts an in-driver rendezvous
server, and runs the user function on every actor.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable

from ..runner.hosts import HostInfo, get_host_assignments

__all__ = ["RayExecutor", "RayHostDiscovery", "ElasticRayExecutor"]


def __getattr__(item: str):
    # Elastic surfaces live in .elastic; resolve lazily (no ray needed
    # until an executor actually starts).
    if item in ("RayHostDiscovery", "ElasticRayExecutor"):
        from . import elastic
        return getattr(elastic, item)
    raise AttributeError(item)


def _require_ray():
    try:
        import ray
        return ray
    except ImportError as exc:
        raise ImportError(
            "horovod_tpu.ray requires ray, which is not installed in this "
            "environment. Use horovod_tpu.run() or the horovodrun-tpu CLI "
            "for local/ssh launches.") from exc


class RayExecutor:
    """Run a function on a Ray cluster with the eager runtime initialized
    (reference: ray/runner.py:41-535)."""

    def __init__(self, num_workers: int, cpus_per_worker: int = 1,
                 use_gpu: bool = False, settings: Any = None) -> None:
        _require_ray()
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self.use_gpu = use_gpu
        self._workers: list = []
        self._server = None

    def start(self) -> None:
        ray = _require_ray()

        @ray.remote
        class _Worker:
            def hostname(self):
                import socket
                return socket.gethostname()

            def set_env(self, env: dict):
                import os
                os.environ.update(env)

            def run(self, fn, args, kwargs):
                return fn(*args, **kwargs)

        worker_cls = _Worker.options(num_cpus=self.cpus_per_worker,
                                     num_gpus=1 if self.use_gpu else 0)
        self._workers = [worker_cls.remote()
                         for _ in range(self.num_workers)]

        # Coordinator: group actors by host, compute the rank contract
        # (reference: ray/runner.py Coordinator.establish_rendezvous).
        hostnames = ray.get([w.hostname.remote() for w in self._workers])
        by_host: "OrderedDict[str, int]" = OrderedDict()
        for h in hostnames:
            by_host[h] = by_host.get(h, 0) + 1
        hosts = [HostInfo(hostname=h, slots=n) for h, n in by_host.items()]
        slots = get_host_assignments(hosts, self.num_workers)

        from ..runner.network import RendezvousServer
        import socket as pysocket
        self._server = RendezvousServer()
        port = self._server.start()
        addr = pysocket.getfqdn()

        # Pair actors (in hostname order) with slots (host-major order).
        pool: dict[str, list[int]] = {}
        for idx, h in enumerate(hostnames):
            pool.setdefault(h, []).append(idx)
        envs: list[dict] = [{} for _ in self._workers]
        for slot in slots:
            actor_idx = pool[slot.hostname].pop(0)
            env = slot.to_env()
            env.update({
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": addr,
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
                "HOROVOD_CONTROLLER": "tcp",
            })
            envs[actor_idx] = env
        ray.get([w.set_env.remote(envs[i])
                 for i, w in enumerate(self._workers)])

    def run(self, fn: Callable, args: tuple = (), kwargs: dict | None = None
            ) -> list:
        ray = _require_ray()
        kwargs = kwargs or {}
        return ray.get([w.run.remote(fn, args, kwargs)
                        for w in self._workers])

    def shutdown(self) -> None:
        ray = _require_ray()
        for w in self._workers:
            ray.kill(w)
        self._workers = []
        if self._server is not None:
            self._server.stop()
            self._server = None
