"""SPMD training loop construction: the TPU-native DistributedOptimizer.

Reference shape: horovod's per-framework `DistributedOptimizer` wraps a
local optimizer and splices a gradient allreduce between backward and step
(reference: horovod/torch/optimizer.py:173-292,
horovod/tensorflow/__init__.py:427-502). On TPU the idiomatic equivalent
compiles the whole train step — forward, backward, fused gradient
allreduce, optimizer update — into ONE XLA program over the device mesh:
`shard_map` gives each device its batch shard, `sync_gradients` emits the
fused AllReduce HLOs that ride ICI, and the optimizer update runs
replicated. Zero host round-trips per step; negotiation cost is zero by
SPMD construction (every rank runs the identical program — the invariant
the reference's controller protocol exists to establish dynamically).
"""
from __future__ import annotations

import dataclasses
import os
import time
from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import optax
from .common.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .common.logging import logger
from .parallel.collectives import allreduce
from .parallel.grad_sync import (GradSyncConfig, init_ring_optimizer_state,
                                 sync_and_apply, sync_gradients)
from .parallel.mesh import data_axes
from .parallel.sharding import ShardingRules


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """Replicated training state (params + optimizer + BN statistics)."""
    step: jax.Array
    params: Any
    opt_state: Any
    batch_stats: Any


# Above this size the loss streams over the vocab axis instead of
# materializing an fp32 log_softmax of the whole logits tensor (see
# ops/loss.py). Streaming is the memory-survival path, NOT a speed win:
# the on-TPU A/B at gpt-small benchmark scale (824M-element logits,
# v5e) measured dense 80.1k tok/s vs streaming 72.3k — the vocab-chunk
# scan serializes work XLA otherwise fuses. So the default threshold
# sits where the dense path's fp32 logits copy (4 bytes/elem, plus the
# bf16 logits and their gradient alongside) stops plausibly fitting:
# on a 16 GB chip that is 2^30 elements = 4 GiB fp32, i.e. HBM/16
# bytes-per-element of headroom — and the default SCALES by the local
# device's discoverable memory so a sub-16GB device (v5e-1-slice dev
# boxes, trimmed GPU partitions) streams earlier instead of OOMing.
# The benchmark config (824M) stays dense on 16 GB; the 8k-sequence
# long-context recipe (1.6G) stays streaming. Override via
# HOROVOD_STREAMING_CE_MIN_ELEMENTS (0 forces streaming everywhere).
_DEVICE_MEMORY_SENTINEL = object()
_device_memory_cache: Any = _DEVICE_MEMORY_SENTINEL


def _device_memory_bytes() -> int | None:
    """Discoverable memory of the first local device (None when the
    backend doesn't report it — e.g. the CPU backend)."""
    global _device_memory_cache
    if _device_memory_cache is _DEVICE_MEMORY_SENTINEL:
        limit = None
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
            limit = stats.get("bytes_limit") \
                or stats.get("bytes_reservable_limit")
        except Exception:  # noqa: BLE001 - stats are best-effort
            limit = None
        _device_memory_cache = int(limit) if limit else None
    return _device_memory_cache


def _ce_threshold() -> int:
    # Read per call (trace-time Python, so this is free): the documented
    # env override must work even when set after `import horovod_tpu`.
    raw = os.environ.get("HOROVOD_STREAMING_CE_MIN_ELEMENTS")
    if raw is not None:
        try:
            return int(raw)
        except ValueError as exc:
            raise ValueError(
                "HOROVOD_STREAMING_CE_MIN_ELEMENTS must be a plain "
                f"integer (got {raw!r})") from exc
    hbm = _device_memory_bytes()
    if hbm is not None:
        return max(hbm // 16, 1 << 20)
    return 1 << 30


def _track_accuracy() -> bool:
    from .common import config
    return bool(config.TRACK_ACCURACY.get())


def cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                       label_smoothing: float = 0.0) -> jax.Array:
    """Mean softmax cross entropy over integer labels (fp32 math)."""
    if logits.size >= _ce_threshold():
        from .ops.loss import streaming_softmax_cross_entropy
        return streaming_softmax_cross_entropy(logits, labels,
                                               label_smoothing)
    num_classes = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32)
    if label_smoothing > 0.0:
        onehot = (1.0 - label_smoothing) * onehot \
            + label_smoothing / num_classes
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


class Trainer:
    """Builds and owns a compiled SPMD train step.

    >>> trainer = Trainer(model, optax.sgd(0.1), mesh)
    >>> state = trainer.init(jax.random.key(0), sample_batch)
    >>> state, metrics = trainer.step(state, batch)

    `sync` controls the gradient data plane exactly like the reference's
    env knobs control its fusion pipeline: fusion threshold bytes,
    fp16/bf16 wire compression (reference: torch/compression.py:46-63),
    and sum/average/adasum reduction.
    """

    def __init__(self, model: Any, tx: optax.GradientTransformation,
                 mesh: Mesh, *,
                 sync: GradSyncConfig | None = None,
                 param_rules: ShardingRules | None = None,
                 loss_fn: Callable = cross_entropy_loss,
                 batch_spec: P | None = None) -> None:
        self.model = model
        self.tx = tx
        self.mesh = mesh
        axes = data_axes(mesh) or ("dp",)
        self.sync = sync or GradSyncConfig(axes=axes, op="average")
        self.param_rules = param_rules or ShardingRules()
        self.loss_fn = loss_fn
        self.batch_spec = batch_spec if batch_spec is not None else P(axes)
        self._step_fn: Callable | None = None
        # AOT executable from the compile→barrier→dispatch path: dispatched
        # directly so the warm-up compile is never repeated (see step()).
        self._compiled: Callable | None = None
        # perfscope MFU ledger (telemetry/perfmodel.py): analytic FLOPs
        # per step, resolved once from the first batch's shape, timed by
        # the wall clock between step() dispatches (steady-state pipeline
        # throughput — blocking on the result here would serialize the
        # async dispatch the fit loop is careful to preserve).
        self._step_flops: float | None = None
        self._peak_flops: float | None = None
        self._last_dispatch: float | None = None
        # Fleet continuous deployment (fleet/deploy.py): rank 0 wires a
        # WeightPublisher in via attach_fleet_publisher; the host-side
        # step counter drives the publish cadence (the device step
        # number lives in donated buffers — syncing it every step to
        # test a modulus would serialize the async dispatch).
        self._fleet_publisher = None
        self._fleet_step = 0

    # -- initialization ----------------------------------------------------
    def init(self, rng: jax.Array, sample_batch: dict) -> TrainState:
        images = _model_input(sample_batch)
        variables = jax.eval_shape(
            partial(self.model.init, train=False), rng,
            jnp.zeros((1,) + images.shape[1:], images.dtype))
        # Runtime twin of hvdshard's HVD801/802 (same rule_coverage/
        # missing_axes core, real mesh + real param tree): a dead rule
        # or unknown-axis spec surfaces at init, loudly, instead of as
        # a silently replicated layout three days into a run.
        for problem in self.param_rules.validate(self.mesh,
                                                 variables["params"]):
            logger.warning("sharding rules: %s", problem)
        param_specs = self.param_rules.tree_specs(variables["params"])

        def _init():
            variables = self.model.init(
                rng, jnp.zeros((1,) + images.shape[1:], images.dtype),
                train=False)
            params = variables["params"]
            batch_stats = variables.get("batch_stats", {})
            return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=self._init_opt_state(params),
                              batch_stats=batch_stats)

        if self.sync.optimizer_in_ring:
            opt_specs = _ring_opt_state_specs(
                self.tx, variables["params"], self._ring_world(),
                self.sync)
        else:
            opt_specs = _opt_state_specs(self.tx, variables["params"],
                                         param_specs)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s),
            TrainState(step=P(),
                       params=param_specs,
                       opt_state=opt_specs,
                       batch_stats=jax.tree_util.tree_map(
                           lambda _: P(),
                           variables.get("batch_stats", {}))),
            is_leaf=lambda x: isinstance(x, P))
        return jax.jit(_init, out_shardings=shardings)()

    def _ring_world(self) -> int:
        """World size of the optimizer-in-ring shard layout: the product
        of the sync axes' mesh sizes."""
        world = 1
        for a in self.sync.axes:
            world *= int(self.mesh.shape[a])
        return world

    def _init_opt_state(self, params):
        """Optimizer state: replicated tx.init(params) normally; with
        optimizer_in_ring, per-rank flat-shard states stacked on a
        leading world axis (sharded over the sync axes — ZeRO-style,
        each rank physically holds 1/world of the moments)."""
        if not self.sync.optimizer_in_ring:
            return self.tx.init(params)
        world = self._ring_world()
        base = init_ring_optimizer_state(self.tx, params, world,
                                         self.sync)
        return jax.tree_util.tree_map(
            lambda l: jnp.broadcast_to(l, (world,) + l.shape)
            if getattr(l, "ndim", 0) >= 1 else l, base)

    # -- the compiled step -------------------------------------------------
    def _build(self, state: TrainState) -> Callable:
        sync_cfg = self.sync
        # Manual-map only the data axes; model axes (tp/sp/ep/pp) stay in
        # GSPMD-automatic mode so the model code keeps global shapes and
        # XLA inserts the tensor-parallel collectives from the arrays' own
        # shardings (set at init).
        manual_axes = frozenset(sync_cfg.axes)
        state_specs = jax.tree_util.tree_map(lambda _: P(), state)
        if sync_cfg.optimizer_in_ring:
            if not manual_axes:
                raise ValueError(
                    "optimizer_in_ring needs explicit sync axes "
                    "(pure-GSPMD mode has no manual axis to shard the "
                    "update over)")
            # Stacked opt-state leaves ride sharded over the sync axes:
            # inside the manual region each rank sees its (1, chunk)
            # shard — the ZeRO layout sync_and_apply updates in place.
            state_specs = dataclasses.replace(
                state_specs,
                opt_state=jax.tree_util.tree_map(
                    lambda l: P(sync_cfg.axes)
                    if getattr(l, "ndim", 0) >= 2 else P(),
                    state.opt_state))

        def local_step(state: TrainState, batch: dict):
            def loss_of(params):
                variables = {"params": params}
                mutable: Any = False
                if state.batch_stats:
                    variables["batch_stats"] = state.batch_stats
                    mutable = ["batch_stats"]
                out = self.model.apply(variables, _model_input(batch),
                                       train=True, mutable=mutable)
                logits, updated = out if mutable else (out, {})
                loss = self.loss_fn(logits, batch["label"])
                return loss, (logits, updated)

            grad_fn = jax.value_and_grad(loss_of, has_aux=True)
            (loss, (logits, updated)), grads = grad_fn(state.params)

            if sync_cfg.optimizer_in_ring:
                # The fused horovod moment: reduce-scatter the gradient
                # pytree, apply the optax update on this rank's shard
                # (optimizer state sharded ZeRO-style), and all-gather
                # the UPDATED PARAMS instead of gradients.
                opt_local = jax.tree_util.tree_map(
                    lambda l: l[0] if getattr(l, "ndim", 0) >= 2 else l,
                    state.opt_state)
                params, opt_local = sync_and_apply(
                    self.tx, grads, state.params, opt_local, sync_cfg)
                opt_state = jax.tree_util.tree_map(
                    lambda l: l[None] if getattr(l, "ndim", 0) >= 1
                    else l, opt_local)
            else:
                # The horovod moment: fused, compressed allreduce of the
                # gradient pytree over the data axes.
                grads = sync_gradients(grads, sync_cfg)

                updates, opt_state = self.tx.update(grads,
                                                    state.opt_state,
                                                    state.params)
                params = optax.apply_updates(state.params, updates)

            metrics = {"loss": allreduce(loss, sync_cfg.axes, "average")}
            if _track_accuracy():
                # For LM-head-sized logits the argmax is a full extra
                # read of a multi-GB tensor per step; the knob lets a
                # throughput run drop it (HOROVOD_TRACK_ACCURACY=0).
                acc = jnp.mean(
                    (jnp.argmax(logits, -1) == batch["label"]).astype(
                        jnp.float32))
                metrics["accuracy"] = allreduce(acc, sync_cfg.axes,
                                                "average")
            new_stats = updated.get("batch_stats", state.batch_stats)
            if state.batch_stats and getattr(self.model, "axis_name",
                                             None) is None:
                # Per-replica BN stats must stay replicated state: average
                # them over the data axes (what the reference achieves by
                # broadcasting rank 0's stats at checkpoints).
                new_stats = jax.tree_util.tree_map(
                    lambda x: allreduce(x, sync_cfg.axes, "average"),
                    new_stats)
            return dataclasses.replace(
                state, step=state.step + 1, params=params,
                opt_state=opt_state, batch_stats=new_stats), metrics

        if not manual_axes:
            # Pure-GSPMD mode (sync.axes == ()): no manual axes at all —
            # XLA derives every collective (incl. gradient reductions)
            # from the arrays' shardings. Required when the model embeds
            # its own shard_map regions (e.g. MoE expert-parallel over
            # "ep"), which cannot nest inside a manual region.
            def gspmd_step(state, batch):
                batch = {k: jax.lax.with_sharding_constraint(
                    v, NamedSharding(self.mesh, self.batch_spec))
                    for k, v in batch.items()}
                return local_step(state, batch)

            return jax.jit(gspmd_step, donate_argnums=(0,))

        # Manual over ALL mesh axes, not just the sync axes: Mosaic
        # (Pallas) custom calls reject partial-manual lowering — a
        # shard_map manual over {"dp"} inside a mesh that also carries
        # size-1 tp/pp/sp axes would raise "cannot be automatically
        # partitioned" on TPU. Models that embed their own shard_map
        # regions use the pure-GSPMD mode above instead.
        mapped = shard_map(
            local_step, mesh=self.mesh,
            in_specs=(state_specs, self.batch_spec),
            out_specs=(state_specs, P()),
            axis_names=frozenset(self.mesh.axis_names),
            check_vma=False)
        return jax.jit(mapped, donate_argnums=(0,))

    def _note_step(self, batch: dict, first: bool) -> None:
        """Fold one dispatched step into the MFU ledger gauges.  The
        first call (carrying the compile) only arms the clock."""
        from .telemetry import metrics as _telemetry_metrics
        tm = _telemetry_metrics()
        if not tm.enabled:
            return
        from .telemetry import perfmodel
        now = time.monotonic()
        prev, self._last_dispatch = self._last_dispatch, now
        if self._step_flops is None:
            x = _model_input(batch)
            ndim = getattr(x, "ndim", 0)
            self._step_flops = perfmodel.model_step_flops(
                self.model, int(x.shape[0]) if ndim else 1,
                seq=int(x.shape[1]) if ndim == 2 else 0,
                image_size=int(x.shape[1]) if ndim == 4 else 224,
                train=True)
            tm.gauge("horovod_train_step_flops").set(self._step_flops)
        if self._peak_flops is None:
            kind = ""
            try:
                kind = jax.local_devices()[0].device_kind
            except Exception:  # noqa: BLE001 - backend probing only
                pass
            # The step consumes the GLOBAL batch, so the denominator is
            # the whole mesh's peak, not one chip's.
            self._peak_flops = perfmodel.peak_flops(kind) \
                * max(jax.device_count(), 1)
        if first or prev is None:
            return
        dt = now - prev
        tm.histogram("horovod_train_step_ms").observe(dt * 1e3)
        tm.gauge("horovod_train_mfu").set(
            perfmodel.mfu(self._step_flops, dt, self._peak_flops))

    # -- fleet continuous deployment (fleet/) ------------------------------
    def attach_fleet_publisher(self, publisher) -> None:
        """Wire a fleet ``WeightPublisher`` in (rank 0 only — the
        publisher is the single writer of the ``fleet.pub`` scope):
        every ``step`` offers the params snapshot on the publish cadence
        and the serving world pulls it (docs/fleet.md)."""
        self._fleet_publisher = publisher

    def _fleet_publish(self, state: TrainState) -> None:
        # Called with the step's OUTPUT state: the input state's
        # buffers are donated to the step executable and deleted by
        # the time this runs.
        if self._fleet_publisher is None:
            return
        self._fleet_step += 1
        version = self._fleet_publisher.maybe_publish(
            self._fleet_step, {"params": state.params})
        if version is not None:
            logger.info("fleet: offered params snapshot v%d at host "
                        "step %d", version, self._fleet_step)

    def step(self, state: TrainState, batch: dict):
        first = self._step_fn is None
        if self._step_fn is None:
            self._step_fn = self._build(state)
            from .parallel import multihost
            if multihost.sync_compile_needed():
                # Compile → KV-barrier → dispatch: gloo's per-program
                # transport context connects at the program's first
                # collective, and per-rank compile skew beyond its
                # ~30 s connect timeout would fail the step outright
                # (multihost.kv_barrier docstring). The AOT executable
                # is KEPT and dispatched directly below — discarding it
                # and re-dispatching through jit would repeat the whole
                # compile whenever the persistent cache doesn't engage
                # (fast-compiling programs, cold cache dir), exactly the
                # skew the barrier exists to remove.
                try:
                    self._compiled = self._step_fn.lower(state,
                                                         batch).compile()
                finally:
                    multihost.kv_barrier("trainer-step-compile")
        if self._compiled is not None:
            try:
                result = self._compiled(state, batch)
                self._note_step(batch, first)
                self._fleet_publish(result[0])
                return result
            except TypeError:
                # Shape/dtype drift vs the AOT signature (e.g. a ragged
                # final batch): the executable rejects the call before
                # dispatch (donated buffers untouched), so fall back to
                # the jit path, which re-specializes per signature.
                self._compiled = None
        result = self._step_fn(state, batch)
        self._note_step(batch, first)
        self._fleet_publish(result[0])
        return result

    # -- fit loop with callbacks ------------------------------------------
    def fit(self, state: TrainState, data, epochs: int = 1,
            callbacks: Sequence[Any] = (), steps_per_epoch: int | None = None):
        """Minimal epoch loop hosting the reference's callback surface
        (reference: horovod/_keras/callbacks.py): ``data`` is either an
        iterable of batches (re-iterated per epoch) or a callable
        ``epoch -> iterable``. Returns (state, history)."""
        for cb in callbacks:
            if hasattr(cb, "set_trainer"):
                cb.set_trainer(self)
            if hasattr(cb, "set_state"):
                cb.set_state(state)
        history: list[dict] = []
        from .common import config
        fleet_runtime = None
        if config.FLEET.get():
            # --fleet runtime wiring: rank 0 hosts the controller and
            # the weight publisher; every rank's loop drives the
            # throttled train-gauge publish (fleet/wiring.py).
            from .fleet.wiring import attach_trainer, trainer_gauges
            fleet_runtime = attach_trainer(self)
        try:
            for cb in callbacks:
                cb.on_train_begin()
            for epoch in range(epochs):
                for cb in callbacks:
                    cb.on_epoch_begin(epoch)
                batches = data(epoch) if callable(data) else data
                sums: dict[str, Any] = {}
                count = 0
                for i, batch in enumerate(batches):
                    if steps_per_epoch is not None \
                            and i >= steps_per_epoch:
                        break
                    for cb in callbacks:
                        cb.on_batch_begin(i)
                    state, metrics = self.step(state, batch)
                    # Keep metrics as device arrays through the epoch:
                    # float() here would sync host↔device every step and
                    # serialize the async dispatch pipeline.
                    for cb in callbacks:
                        cb.on_batch_end(i, metrics)
                    for k, v in metrics.items():
                        sums[k] = v if k not in sums else sums[k] + v
                    count += 1
                    if fleet_runtime is not None:
                        from . import core
                        fleet_runtime.publish_gauge(
                            lambda: core.global_state().size,
                            trainer_gauges)
                epoch_logs = {k: float(v) / max(count, 1)
                              for k, v in sums.items()}
                for cb in callbacks:
                    if hasattr(cb, "set_state"):
                        cb.set_state(state)
                    cb.on_epoch_end(epoch, epoch_logs)
                history.append(epoch_logs)
            for cb in callbacks:
                cb.on_train_end()
        finally:
            if fleet_runtime is not None:
                fleet_runtime.close()
        return state, history

    # -- evaluation --------------------------------------------------------
    def eval_step(self, state: TrainState, batch: dict):
        @partial(jax.jit, static_argnums=())
        def _eval(state, batch):
            variables = {"params": state.params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
            logits = self.model.apply(variables, _model_input(batch),
                                      train=False)
            loss = self.loss_fn(logits, batch["label"])
            acc = jnp.mean((jnp.argmax(logits, -1)
                            == batch["label"]).astype(jnp.float32))
            return {"loss": loss, "accuracy": acc}
        return _eval(state, batch)


def _opt_state_specs(tx: optax.GradientTransformation, params: Any,
                     param_specs: Any) -> Any:
    """Optimizer-state PartitionSpecs: moment-like leaves mirror the param
    layout, scalars replicate."""
    shapes = jax.eval_shape(tx.init, params)
    flat_params, _ = jax.tree_util.tree_flatten(params)
    by_shape = {}
    specs_flat, _ = jax.tree_util.tree_flatten(param_specs)
    for leaf, spec in zip(flat_params, specs_flat):
        by_shape.setdefault(leaf.shape, spec)

    def spec_for(leaf):
        return by_shape.get(getattr(leaf, "shape", ()), P())

    return jax.tree_util.tree_map(spec_for, shapes)


def _ring_opt_state_specs(tx: optax.GradientTransformation, params: Any,
                          world: int, sync: GradSyncConfig) -> Any:
    """PartitionSpecs for the stacked optimizer-in-ring state: leaves
    stacked on the leading world axis shard over the sync axes, scalars
    (step counts) replicate."""
    shapes = jax.eval_shape(
        lambda: jax.tree_util.tree_map(
            lambda l: jnp.broadcast_to(l, (world,) + l.shape)
            if getattr(l, "ndim", 0) >= 1 else l,
            init_ring_optimizer_state(tx, params, world, sync)))
    return jax.tree_util.tree_map(
        lambda l: P(sync.axes) if len(l.shape) >= 2 else P(), shapes)


def _model_input(batch: dict):
    """The model's input tensor: "image" for vision batches, "input" for
    token batches."""
    return batch["image"] if "image" in batch else batch["input"]


def synthetic_text_batch(batch_size: int, seq_len: int = 2048,
                         vocab_size: int = 32000, seed: int = 0) -> dict:
    """Random next-token-prediction batch: label[t] = input[t+1]."""
    tokens = jax.random.randint(jax.random.key(seed),
                                (batch_size, seq_len + 1), 0, vocab_size)
    return {"input": tokens[:, :-1], "label": tokens[:, 1:]}


def synthetic_image_batch(batch_size: int, image_size: int = 224,
                          num_classes: int = 1000,
                          seed: int = 0) -> dict:
    """Random batch matching the reference's synthetic benchmark inputs
    (reference: examples/pytorch/pytorch_synthetic_benchmark.py:55-58)."""
    k1, k2 = jax.random.split(jax.random.key(seed))
    return {
        "image": jax.random.normal(
            k1, (batch_size, image_size, image_size, 3), jnp.float32),
        "label": jax.random.randint(k2, (batch_size,), 0, num_classes),
    }
