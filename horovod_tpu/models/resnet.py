"""ResNet v1.5 family, TPU-first.

The reference benchmarks ResNet-50/101 through torchvision/tf-slim models
(reference: examples/pytorch/pytorch_synthetic_benchmark.py:24-38,
docs/benchmarks.rst:13-43); this is a from-scratch flax implementation
shaped for the TPU MXU:

- NHWC layout (XLA's native conv layout on TPU);
- bf16 compute / fp32 params by default — convolutions and the final
  matmul hit the MXU at full rate, batch-norm statistics accumulate in
  fp32;
- v1.5 stride placement (stride on the 3x3, not the 1x1) matching the
  torchvision models the reference benchmarks;
- optional cross-replica batch norm over a mesh axis (the reference ships
  SyncBatchNorm as an opt-in, reference: torch/sync_batch_norm.py:40-218);
  flax's BatchNorm takes `axis_name` and lowers to a psum on ICI;
- optional space-to-depth stem (`stem="space_to_depth"`): the 7x7/s2
  conv on a 3-channel input maps poorly onto the 128-lane MXU; folding
  2x2 spatial blocks into channels turns it into an exactly-equivalent
  4x4/s1 conv on 12 channels (`fold_conv7_stem_weights` converts
  trained conv7 weights into the folded layout bit-for-bit in fp32).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """[N, H, W, C] → [N, H/b, W/b, b*b*C], folding b×b spatial cells
    into channels (cell-major, then input-row, input-col, channel)."""
    n, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(
            f"space_to_depth needs H and W divisible by {block} "
            f"(got {h}x{w}); pad or resize the input")
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, block * block * c)


def fold_conv7_stem_weights(w7: jnp.ndarray) -> jnp.ndarray:
    """[7, 7, C, F] conv7/s2/p3 kernel → the equivalent [4, 4, 4C, F]
    kernel for a stride-1 conv over the 2×2 space-to-depth input with
    cell padding ((2,1),(2,1)).

    out(i) = Σ_{a=0..6} x[2i−3+a]·W[a] = Σ_{a=0..7} x[2i−4+a]·W8[a]
    with a zero row/col padded at the FRONT; rows 2i−4..2i+3 span s2d
    cells i−2..i+1 — a 4-cell window starting at cell i−2."""
    kh, kw, c, f = w7.shape
    assert (kh, kw) == (7, 7), (kh, kw)
    w8 = jnp.pad(w7, ((1, 0), (1, 0), (0, 0), (0, 0)))
    w8 = w8.reshape(4, 2, 4, 2, c, f)
    w8 = w8.transpose(0, 2, 1, 3, 4, 5)          # [4, 4, 2, 2, C, F]
    return w8.reshape(4, 4, 4 * c, f)


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (ResNet-18/34)."""
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 reduce → 3x3 (strided: v1.5) → 1x1 expand (ResNet-50+)."""
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last norm scale so each block starts as identity —
        # standard large-batch ResNet trick (Goyal et al.), good for the
        # large global batches data-parallel TPU training runs at.
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """Configurable ResNet v1.5 over NHWC inputs.

    `axis_name` enables cross-replica (sync) batch norm over that mesh
    axis; leave None for per-replica statistics (the reference's default
    DP behavior).
    """
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    act: Callable = nn.relu
    axis_name: str | None = None
    # "conv7" (torchvision-identical stem) | "space_to_depth" (MXU-
    # friendly folded stem; same function class — conv7 checkpoints
    # convert via fold_conv7_stem_weights).
    stem: str = "conv7"

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=self.param_dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=self.param_dtype,
                       axis_name=self.axis_name if train else None)

        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            x = space_to_depth(x, 2)
            x = conv(self.num_filters, (4, 4),
                     padding=[(2, 1), (2, 1)], name="conv_init")(x)
        elif self.stem == "conv7":
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r} "
                             "(expected 'conv7' or 'space_to_depth')")
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i, conv=conv,
                                   norm=norm, act=self.act,
                                   strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        # Classifier in fp32: small matmul, and fp32 logits keep the
        # softmax/cross-entropy numerically stable.
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=self.param_dtype, name="head")(
                         x.astype(jnp.float32))
        return x


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3),
                   block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3),
                    block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3),
                    block_cls=BottleneckBlock)
