"""Inception V3, TPU-first.

Inception V3 is the reference's first headline scaling benchmark
(reference: README.rst:102-109, docs/benchmarks.rst:13-14 — ~90%
efficiency at 512 GPUs). From-scratch flax implementation of the
Szegedy et al. 2015 architecture (the tf-slim/torchvision layer plan),
shaped for the TPU MXU:

- NHWC, bf16 compute / fp32 params; every branch is conv+BN+ReLU so XLA
  fuses the elementwise tail into the conv;
- the factorized 1xN/Nx1 and parallel-branch structure produces MANY
  small-ish gradient tensors — with ResNet's few large ones and VGG's
  giant dense ones, the three reference benchmarks bracket the tensor-
  fusion design space;
- aux classifier omitted (inference parity not affected; the reference
  benchmarks train the main head only).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    features: int
    kernel: tuple[int, int]
    strides: tuple[int, int] = (1, 1)
    padding: str | Sequence = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype)(x)
        return nn.relu(x)


def _pool(x, window=(3, 3), strides=(1, 1), kind="avg"):
    fn = nn.avg_pool if kind == "avg" else nn.max_pool
    return fn(x, window, strides=strides, padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cbn = partial(ConvBN, dtype=self.dtype)
        b1 = cbn(64, (1, 1))(x, train)
        b2 = cbn(64, (5, 5))(cbn(48, (1, 1))(x, train), train)
        b3 = cbn(96, (3, 3))(
            cbn(96, (3, 3))(cbn(64, (1, 1))(x, train), train), train)
        b4 = cbn(self.pool_features, (1, 1))(_pool(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionA(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cbn = partial(ConvBN, dtype=self.dtype)
        b1 = cbn(384, (3, 3), (2, 2), padding="VALID")(x, train)
        b2 = cbn(96, (3, 3), (2, 2), padding="VALID")(
            cbn(96, (3, 3))(cbn(64, (1, 1))(x, train), train), train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionB(nn.Module):
    """Factorized 7x7 block (1x7 / 7x1 pairs)."""
    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cbn = partial(ConvBN, dtype=self.dtype)
        c = self.channels_7x7
        b1 = cbn(192, (1, 1))(x, train)
        b2 = cbn(192, (7, 1))(
            cbn(c, (1, 7))(cbn(c, (1, 1))(x, train), train), train)
        b3 = x
        for kern, feats in (((1, 1), c), ((7, 1), c), ((1, 7), c),
                            ((7, 1), c), ((1, 7), 192)):
            b3 = cbn(feats, kern)(b3, train)
        b4 = cbn(192, (1, 1))(_pool(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionB(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cbn = partial(ConvBN, dtype=self.dtype)
        b1 = cbn(320, (3, 3), (2, 2), padding="VALID")(
            cbn(192, (1, 1))(x, train), train)
        b2 = cbn(192, (1, 1))(x, train)
        b2 = cbn(192, (1, 7))(b2, train)
        b2 = cbn(192, (7, 1))(b2, train)
        b2 = cbn(192, (3, 3), (2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    """Expanded-filter-bank output block (split 1x3 / 3x1 branches)."""
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cbn = partial(ConvBN, dtype=self.dtype)
        b1 = cbn(320, (1, 1))(x, train)
        b2 = cbn(384, (1, 1))(x, train)
        b2 = jnp.concatenate([cbn(384, (1, 3))(b2, train),
                              cbn(384, (3, 1))(b2, train)], axis=-1)
        b3 = cbn(384, (3, 3))(cbn(448, (1, 1))(x, train), train)
        b3 = jnp.concatenate([cbn(384, (1, 3))(b3, train),
                              cbn(384, (3, 1))(b3, train)], axis=-1)
        b4 = cbn(192, (1, 1))(_pool(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cbn = partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        # Stem: 299x299x3 → 35x35x192.
        x = cbn(32, (3, 3), (2, 2), padding="VALID")(x, train)
        x = cbn(32, (3, 3), padding="VALID")(x, train)
        x = cbn(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = cbn(80, (1, 1), padding="VALID")(x, train)
        x = cbn(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # 3x InceptionA → ReductionA → 4x InceptionB → ReductionB →
        # 2x InceptionC (the V3 layer plan).
        for pool_features in (32, 64, 64):
            x = InceptionA(pool_features, dtype=self.dtype)(x, train)
        x = ReductionA(dtype=self.dtype)(x, train)
        for c77 in (128, 160, 160, 192):
            x = InceptionB(c77, dtype=self.dtype)(x, train)
        x = ReductionB(dtype=self.dtype)(x, train)
        for _ in range(2):
            x = InceptionC(dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))              # global average pool
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x
