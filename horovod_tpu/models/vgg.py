"""VGG family, TPU-first.

VGG-16 is one of the reference's three headline scaling benchmarks
(reference: docs/benchmarks.rst:13-14 — ~68% efficiency at 512 GPUs; its
huge dense gradient tensors are the classic tensor-fusion stress test).
From-scratch flax implementation shaped for the TPU MXU:

- NHWC layout, bf16 compute / fp32 params (conv + the 4096-wide dense
  layers all hit the MXU);
- optional batch norm (the "VGG-BN" torchvision variant) — plain VGG's
  scale drift is hostile to bf16, BN keeps activations tame;
- the classifier head keeps the two 4096-unit layers: their ~100M dense
  parameters are WHY VGG is the fusion/communication benchmark.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# Stage plan: (convs per stage, filters); 'M' pools between stages.
_VGG16_STAGES: tuple[tuple[int, int], ...] = (
    (2, 64), (2, 128), (3, 256), (3, 512), (3, 512))
_VGG19_STAGES: tuple[tuple[int, int], ...] = (
    (2, 64), (2, 128), (4, 256), (4, 512), (4, 512))


class VGG(nn.Module):
    stages: Sequence[tuple[int, int]]
    num_classes: int = 1000
    batch_norm: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, kernel_size=(3, 3), padding="SAME",
                       use_bias=not self.batch_norm, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        for n_convs, filters in self.stages:
            for _ in range(n_convs):
                x = conv(features=filters)(x)
                if self.batch_norm:
                    x = norm()(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        for width in (4096, 4096):
            x = nn.Dense(width, dtype=self.dtype)(x)
            x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


VGG16 = partial(VGG, stages=_VGG16_STAGES)
VGG19 = partial(VGG, stages=_VGG19_STAGES)
