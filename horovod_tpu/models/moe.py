"""Mixture-of-Experts FFN with expert parallelism over the "ep" mesh axis.

The reference exposes ``alltoall`` as a user primitive explicitly for
MoE-style workloads but ships no routing layer (SURVEY §2.6).  This is the
TPU-native layer on top: Switch-style top-1 routing with capacity, dense
dispatch/combine einsums (mask-based, fully static shapes for XLA), and an
expert-parallel execution mode where tokens travel to their expert's rank
and back via two ``lax.all_to_all``s over "ep" — the exact communication
pattern the reference's alltoall primitive was added for.
"""
from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax
from jax.sharding import PartitionSpec as P


def _dispatch_combine(router_logits: jax.Array, capacity: int):
    """Top-1 dispatch/combine tensors. router_logits: [N, E] (N tokens).

    Returns dispatch [N, E, C] bool and combine [N, E, C] f32; tokens past
    an expert's capacity are dropped (output 0 for them, Switch behavior).
    """
    n, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                       # [N]
    mask = jax.nn.one_hot(expert, e, dtype=jnp.float32)       # [N, E]
    # Position of each token within its expert's queue.
    pos = jnp.cumsum(mask, axis=0) * mask                     # [N, E]
    keep = (pos > 0) & (pos <= capacity)
    pos_clamped = jnp.clip(pos - 1, 0, capacity - 1).astype(jnp.int32)
    dispatch = jax.nn.one_hot(pos_clamped, capacity,
                              dtype=jnp.float32) * keep[..., None]
    gate = jnp.sum(probs * mask, axis=-1)                     # [N]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


class MoEMLP(nn.Module):
    """Switch-style MoE feed-forward. Input [B, T, D] → [B, T, D].

    ``ep_mesh``/``ep_axis``: when set (and axis size > 1) experts shard
    over "ep" and tokens are exchanged with two all_to_alls; otherwise all
    experts run replicated (dense einsum).  ``capacity_factor`` scales the
    per-expert token budget.
    """
    num_experts: int = 8
    d_ff: int = 256
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    ep_mesh: Any = None
    ep_axis: str = "ep"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        e = self.num_experts
        router = nn.Dense(e, use_bias=False, dtype=jnp.float32,
                          param_dtype=self.param_dtype, name="router")
        wi = self.param("wi", nn.initializers.lecun_normal(),
                        (e, d, self.d_ff), self.param_dtype)
        wo = self.param("wo", nn.initializers.lecun_normal(),
                        (e, self.d_ff, d), self.param_dtype)

        n_ep = 1
        if self.ep_mesh is not None:
            n_ep = self.ep_mesh.shape.get(self.ep_axis, 1)
        if self.is_initializing() or n_ep == 1:
            return self._dense_moe(router, wi, wo, x)

        # Expert-parallel: batch sharded over ep, experts sharded over ep.
        # Router logits compute outside the shard_map (replicated weights,
        # batch-parallel math); only dispatch + expert FFN go manual.
        logits = router(x)                                    # [B, T, E]
        from ..common.jax_compat import shard_map
        return shard_map(
            partial(_expert_parallel_moe_with_logits,
                    axis=self.ep_axis, axis_size=n_ep,
                    capacity_factor=self.capacity_factor,
                    dtype=self.dtype),
            mesh=self.ep_mesh,
            in_specs=(P(self.ep_axis), P(self.ep_axis), P(self.ep_axis),
                      P(self.ep_axis)),
            out_specs=P(self.ep_axis), check_vma=False)(
            x, logits, wi, wo)

    def _dense_moe(self, router, wi, wo, x):
        b, t, d = x.shape
        tokens = x.reshape(b * t, d)
        logits = router(x).reshape(b * t, self.num_experts)
        capacity = _capacity(b * t, self.num_experts, self.capacity_factor)
        dispatch, combine = _dispatch_combine(logits, capacity)
        expert_in = jnp.einsum("nec,nd->ecd", dispatch,
                               tokens.astype(jnp.float32))
        h = jnp.einsum("ecd,edf->ecf", expert_in,
                       wi.astype(jnp.float32))
        h = nn.gelu(h)
        expert_out = jnp.einsum("ecf,efd->ecd", h, wo.astype(jnp.float32))
        out = jnp.einsum("nec,ecd->nd", combine, expert_out)
        return out.reshape(b, t, d).astype(self.dtype)


def _capacity(n_tokens: int, num_experts: int, factor: float) -> int:
    return max(int(factor * n_tokens / num_experts), 1)


def _expert_parallel_moe_with_logits(x, logits, wi, wo, *, axis: str,
                                     axis_size: int, capacity_factor: float,
                                     dtype):
    """Per-ep-shard MoE: local batch shard [Bl, T, D], local expert shards
    wi [El, D, F] / wo [El, F, D], logits [Bl, T, E]."""
    bl, t, d = x.shape
    e = logits.shape[-1]
    el = wi.shape[0]
    assert el * axis_size == e, (el, axis_size, e)
    tokens = x.reshape(bl * t, d).astype(jnp.float32)
    capacity = _capacity(bl * t, e, capacity_factor)
    dispatch, combine = _dispatch_combine(logits.reshape(bl * t, e),
                                          capacity)
    # Local dispatch for ALL experts: [E, C, D]
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, tokens)
    # To expert ranks: split expert dim over ep, gather the token groups —
    # each rank ends with [El, n*C, D]: its experts, every rank's tokens.
    expert_in = lax.all_to_all(expert_in, axis, split_axis=0,
                               concat_axis=1, tiled=True)
    h = jnp.einsum("ecd,edf->ecf", expert_in, wi.astype(jnp.float32))
    h = jax.nn.gelu(h)
    expert_out = jnp.einsum("ecf,efd->ecd", h, wo.astype(jnp.float32))
    # Send results home: inverse reshard.
    expert_out = lax.all_to_all(expert_out, axis, split_axis=1,
                                concat_axis=0, tiled=True)
    out = jnp.einsum("nec,ecd->nd", combine, expert_out)
    return out.reshape(bl, t, d).astype(dtype)
