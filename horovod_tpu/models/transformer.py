"""Decoder-only Transformer LM family, TPU-first.

The reference framework ships CNN benchmark models but no attention code at
all (SURVEY §5.7); long-context training is a first-class goal here, so the
flagship language model supports four attention execution strategies:

- ``dense``:   fused-by-XLA einsum softmax attention;
- ``flash``:   the Pallas MXU kernel (ops/flash_attention.py);
- ``ring``:    exact ring attention over the "sp" mesh axis — sequence
               sharded, KV rotating over ICI neighbors (parallel/ring_attention.py);
- ``ulysses``: all-to-all head/sequence reshard over "sp", full-sequence
               flash locally (parallel/ulysses.py).

Design notes (TPU-first, not a port): bf16 activations with fp32 params and
fp32 softmax/log-softmax; RoPE positions are *global* so sequence sharding
never changes the math; all shapes static; per-block ``jax.checkpoint``
(remat) trades FLOPs for HBM on long sequences.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int | None = None           # default 4 * d_model (SwiGLU-scaled)
    max_seq_len: int = 8192
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16         # activation/compute dtype
    param_dtype: Any = jnp.float32
    attention: str = "dense"          # dense | flash | ring | ulysses
    causal: bool = True
    remat: bool = False               # checkpoint each block
    # Remat granularity when remat=True: "full" recomputes the whole
    # block; "dots" saves matmul outputs and recomputes only the cheap
    # elementwise work (jax.checkpoint_policies.checkpoint_dots) — less
    # recompute for modestly more HBM, the middle point of the
    # memory/FLOPs trade (SURVEY: jax.checkpoint for remat).
    remat_policy: str = "full"        # full | dots
    # flash kernel tiling (bwd defaults to the fwd blocks; the backward
    # kernel holds more live VMEM tiles so its optimum is often smaller)
    block_q: int = 128
    block_k: int = 128
    block_q_bwd: int | None = None
    block_k_bwd: int | None = None
    flash_interpret: bool = False     # run Pallas kernels interpreted (tests)
    # sequence-parallel wiring (ring/ulysses)
    mesh: Any = None
    sp_axis: str = "sp"
    batch_spec: Any = None            # PartitionSpec for the batch dim
    # Mixture-of-Experts FFN (0 = dense MLP). With a mesh carrying an
    # "ep" axis > 1, experts shard over it (two all_to_alls per layer).
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    ep_axis: str = "ep"
    # Incremental (KV-cache) decoding for inference serving: each
    # Attention layer keeps cached_key/cached_value [B, max_seq_len, H, D]
    # plus a per-batch-element write index in the mutable "cache"
    # collection, so continuous batching (serving/batcher.py) pays one
    # token of compute per step instead of re-running the full forward.
    # Parameters are identical to the decode=False model; see prefill()
    # and decode_step() below.  Mutually exclusive with ring/ulysses.
    decode: bool = False
    # Paged KV cache (ISSUE 14, serving/kvpool.py): with decode=True and
    # paged=True each layer's KV state is a shared block pool
    # [kv_pool_blocks + 1, kv_block_tokens, H, D] (the last row is a
    # write sink for padded positions) instead of dense per-slot
    # arrays; every apply takes explicit block_tables [B, M] (logical
    # block i of row b lives in pool row block_tables[b, i]) and
    # cursors [B] (each row's write position).  Storage scales with
    # live token residency; parameters are unchanged, and the math is
    # parity-tested against the dense decode path.
    paged: bool = False
    kv_pool_blocks: int = 0
    kv_block_tokens: int = 16

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.num_heads == 0
        return self.d_model // self.num_heads

    @property
    def ff_dim(self) -> int:
        return self.d_ff if self.d_ff is not None else 4 * self.d_model


# ---------------------------------------------------------------------------
# RoPE (global positions — invariant under sequence sharding)
# ---------------------------------------------------------------------------
def rope_frequencies(head_dim: int, theta: float) -> jax.Array:
    return 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                       dtype=jnp.float32) / head_dim))


def apply_rope(x: jax.Array, positions: jax.Array,
               theta: float) -> jax.Array:
    """x: [B, T, H, D]; positions: [T] global token positions shared by
    the batch, or [B, T] per-element positions (KV-cache decode, where
    every sequence in the continuous batch sits at its own depth)."""
    freqs = rope_frequencies(x.shape[-1], theta)          # [D/2]
    if positions.ndim == 1:
        positions = positions[None, :]                    # [1,T]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B|1,T,D/2]
    cos = jnp.cos(angles)[:, :, None, :]                  # [B|1,T,1,D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# Attention dispatch
# ---------------------------------------------------------------------------
def _axis_is_manual(axis: str) -> bool:
    """True when tracing inside a shard_map manual region over ``axis``."""
    try:
        jax.lax.axis_index(axis)
        return True
    except Exception:  # noqa: BLE001 - unbound axis name
        return False


def _make_attention(cfg: TransformerConfig) -> Callable:
    """Returns attn(q, k, v) for global [B, T, H, D] BTHD tensors."""
    if cfg.attention == "dense":
        from ..ops.flash_attention import mha_reference
        return partial(mha_reference, causal=cfg.causal)
    if cfg.attention == "flash":
        from ..ops.flash_attention import flash_attention
        return partial(flash_attention, causal=cfg.causal,
                       block_q=cfg.block_q, block_k=cfg.block_k,
                       block_q_bwd=cfg.block_q_bwd,
                       block_k_bwd=cfg.block_k_bwd,
                       interpret=cfg.flash_interpret)
    if cfg.attention in ("ring", "ulysses"):
        if cfg.mesh is None:
            raise ValueError(
                f"attention='{cfg.attention}' needs cfg.mesh to shard the "
                f"sequence over axis '{cfg.sp_axis}'")
        n = cfg.mesh.shape.get(cfg.sp_axis, 1)
        # cfg.batch_spec names the mesh axis (or axis tuple) the batch dim
        # is sharded over, e.g. "dp" — None means replicated batch.
        spec = P(cfg.batch_spec, cfg.sp_axis, None, None)
        if cfg.attention == "ring":
            from ..parallel.ring_attention import ring_attention
            inner = partial(ring_attention, axis=cfg.sp_axis,
                            causal=cfg.causal, axis_size=n)
        else:
            from ..parallel.ulysses import ulysses_attention
            inner = partial(ulysses_attention, axis=cfg.sp_axis,
                            causal=cfg.causal, axis_size=n,
                            attn_fn=partial(_bthd_attn_adapter,
                                            cfg=cfg))

        if _axis_is_manual(cfg.sp_axis):
            # Already inside a manual region over sp (the Trainer maps the
            # whole step over (dp, sp)): q/k/v are local sequence shards,
            # call the SP algorithm directly.
            return inner

        def dispatch(q, k, v):
            from ..common.jax_compat import shard_map
            return shard_map(inner, mesh=cfg.mesh,
                             in_specs=(spec, spec, spec),
                             out_specs=spec, check_vma=True)(q, k, v)
        return dispatch
    raise ValueError(f"Unknown attention impl: {cfg.attention}")


def _bthd_attn_adapter(q, k, v, causal=False, sm_scale=None, *,
                       cfg: TransformerConfig):
    """Full-sequence attention used inside Ulysses' head shard: flash on
    TPU, dense elsewhere."""
    if jax.default_backend() == "tpu" or cfg.flash_interpret:
        from ..ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               block_q=cfg.block_q, block_k=cfg.block_k,
                               block_q_bwd=cfg.block_q_bwd,
                               block_k_bwd=cfg.block_k_bwd,
                               interpret=cfg.flash_interpret)
    from ..ops.flash_attention import mha_reference
    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)


# ---------------------------------------------------------------------------
# Modules
# ---------------------------------------------------------------------------
class RMSNorm(nn.Module):
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param("scale", nn.initializers.ones,
                           (x.shape[-1],), self.param_dtype)
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(self.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jax.Array, block_tables=None, cursors=None,
                 lengths=None) -> jax.Array:
        cfg = self.cfg
        b, t, _ = x.shape
        dense = partial(nn.DenseGeneral, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype)
        qkv_shape = (cfg.num_heads, cfg.head_dim)
        q = dense(features=qkv_shape, name="wq")(x)
        k = dense(features=qkv_shape, name="wk")(x)
        v = dense(features=qkv_shape, name="wv")(x)

        if cfg.decode and not self.is_initializing():
            if cfg.attention in ("ring", "ulysses"):
                raise ValueError(
                    "cfg.decode is incompatible with sequence-parallel "
                    f"attention ('{cfg.attention}'): the KV cache is a "
                    "whole-sequence structure")
            if cfg.paged:
                if block_tables is None or cursors is None:
                    raise ValueError(
                        "paged decode needs block_tables [B, M] and "
                        "cursors [B] on every apply")
                out = self._decode_attend_paged(q, k, v, block_tables,
                                                cursors, lengths)
            else:
                out = self._decode_attend(q, k, v)
        else:
            if cfg.attention in ("ring", "ulysses") and \
                    _axis_is_manual(cfg.sp_axis) and \
                    not self.is_initializing():
                # Sequence dim is a local shard: RoPE positions are global.
                positions = jax.lax.axis_index(cfg.sp_axis) * t \
                    + jnp.arange(t)
            else:
                positions = jnp.arange(t)
            q = apply_rope(q, positions, cfg.rope_theta)
            k = apply_rope(k, positions, cfg.rope_theta)

            if self.is_initializing() and \
                    cfg.attention in ("ring", "ulysses"):
                # Shape-only trace with a tiny batch: parameter shapes
                # don't depend on the attention execution strategy.
                attn = _make_attention(
                    dataclasses.replace(cfg, attention="dense"))
            else:
                attn = _make_attention(cfg)
            out = attn(q, k, v)                           # [B,T,H,D]
        out = out.astype(cfg.dtype)
        return dense(features=cfg.d_model, axis=(-2, -1), name="wo")(out)

    def _decode_attend(self, q: jax.Array, k: jax.Array,
                       v: jax.Array) -> jax.Array:
        """Incremental attention over the mutable KV cache: write this
        call's K/V at each batch element's own cache depth, attend
        causally over the cached prefix.  Positions are absolute, so the
        RoPE math matches the full forward pass exactly; fp32 softmax
        like every other path in this file."""
        cfg = self.cfg
        b, t, h, d = q.shape
        s = cfg.max_seq_len
        cached_k = self.variable("cache", "cached_key", jnp.zeros,
                                 (b, s, h, d), cfg.dtype)
        cached_v = self.variable("cache", "cached_value", jnp.zeros,
                                 (b, s, h, d), cfg.dtype)
        index = self.variable("cache", "cache_index",
                              lambda: jnp.zeros((b,), jnp.int32))
        idx = index.value                                   # [B]
        positions = idx[:, None] + jnp.arange(t)[None, :]   # [B,T]
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
        write = jax.vmap(lambda cache, new, i:
                         jax.lax.dynamic_update_slice(cache, new,
                                                      (i, 0, 0)))
        cached_k.value = write(cached_k.value, k.astype(cfg.dtype), idx)
        cached_v.value = write(cached_v.value, v.astype(cfg.dtype), idx)
        index.value = idx + t
        # Causal mask over absolute positions.  Right-padded prefill
        # garbage always sits at key positions strictly greater than the
        # current query position (prefill() rewinds the write cursor to
        # the true length, and decode overwrites forward from there), so
        # key_pos <= q_pos alone keeps it invisible.
        key_pos = jnp.arange(s)
        mask = key_pos[None, None, :] <= positions[:, :, None]  # [B,T,S]
        qf = q.astype(jnp.float32)
        kf = cached_k.value.astype(jnp.float32)
        vf = cached_v.value.astype(jnp.float32)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) / math.sqrt(d)
        logits = jnp.where(mask[:, None, :, :], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, vf)

    def _decode_attend_paged(self, q: jax.Array, k: jax.Array,
                             v: jax.Array, block_tables, cursors,
                             lengths) -> jax.Array:
        """Incremental attention over the shared block pool (ISSUE 14):
        this call's K/V scatter into pool rows addressed through each
        row's block table, then the table gathers the sequence back as
        [B, M*bt, H, D] (one block-table-indexed gather — logical
        position p of row b lives at pool[tables[b, p//bt], p%bt]) for
        the same absolute-position causal attention as the dense path.
        ``lengths`` masks right-padded prefill calls: padded positions
        write to the pool's sink row (never a real block) and padded
        logits are garbage the caller ignores, exactly like the dense
        path's masked tail."""
        cfg = self.cfg
        b, t, h, d = q.shape
        bt = cfg.kv_block_tokens
        if cfg.kv_pool_blocks <= 0:
            raise ValueError(
                "cfg.paged needs kv_pool_blocks > 0 (the per-layer "
                "block pool size)")
        sink = cfg.kv_pool_blocks                    # the write sink row
        key_pool = self.variable("cache", "key_pool", jnp.zeros,
                                 (sink + 1, bt, h, d), cfg.dtype)
        value_pool = self.variable("cache", "value_pool", jnp.zeros,
                                   (sink + 1, bt, h, d), cfg.dtype)
        tables = jnp.asarray(block_tables, jnp.int32)      # [B, M]
        cursors = jnp.asarray(cursors, jnp.int32)          # [B]
        m = tables.shape[1]
        if lengths is None:
            valid = jnp.ones((b, t), bool)
        else:
            valid = jnp.arange(t)[None, :] \
                < jnp.asarray(lengths, jnp.int32)[:, None]
        positions = cursors[:, None] + jnp.arange(t)[None, :]   # [B,T]
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
        logical = jnp.minimum(positions // bt, m - 1)
        phys = jnp.take_along_axis(tables, logical, axis=1)     # [B,T]
        phys = jnp.where(valid, phys, sink)
        offs = positions % bt
        kp = key_pool.value.at[phys.reshape(-1), offs.reshape(-1)].set(
            k.astype(cfg.dtype).reshape(b * t, h, d))
        vp = value_pool.value.at[phys.reshape(-1), offs.reshape(-1)].set(
            v.astype(cfg.dtype).reshape(b * t, h, d))
        key_pool.value, value_pool.value = kp, vp
        # Gather each row's sequence back in logical order; positions
        # past the cursor (stale or sink-backed) are masked exactly like
        # the dense path's not-yet-overwritten tail.
        k_seq = jnp.take(kp, tables, axis=0).reshape(b, m * bt, h, d)
        v_seq = jnp.take(vp, tables, axis=0).reshape(b, m * bt, h, d)
        key_pos = jnp.arange(m * bt)
        mask = key_pos[None, None, :] <= positions[:, :, None]  # [B,T,S]
        qf = q.astype(jnp.float32)
        kf = k_seq.astype(jnp.float32)
        vf = v_seq.astype(jnp.float32)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) / math.sqrt(d)
        logits = jnp.where(mask[:, None, :, :], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, vf)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        dense = partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype)
        gate = dense(cfg.ff_dim, name="gate")(x)
        up = dense(cfg.ff_dim, name="up")(x)
        return dense(cfg.d_model, name="down")(nn.silu(gate) * up)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jax.Array, block_tables=None, cursors=None,
                 lengths=None) -> jax.Array:
        cfg = self.cfg
        x = x + Attention(cfg, name="attn")(
            RMSNorm(cfg.dtype, cfg.param_dtype, name="attn_norm")(x),
            block_tables, cursors, lengths)
        if cfg.moe_experts > 0:
            from .moe import MoEMLP
            ffn = MoEMLP(num_experts=cfg.moe_experts, d_ff=cfg.ff_dim,
                         capacity_factor=cfg.moe_capacity_factor,
                         dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         ep_mesh=cfg.mesh, ep_axis=cfg.ep_axis,
                         name="moe")
        else:
            ffn = MLP(cfg, name="mlp")
        x = x + ffn(RMSNorm(cfg.dtype, cfg.param_dtype, name="mlp_norm")(x))
        return x


class TransformerLM(nn.Module):
    """Decoder-only LM. ``apply(variables, tokens[B,T] int32) -> logits
    [B, T, vocab]`` in ``cfg.dtype``.

    Logits stay in the compute dtype on purpose: at benchmark scale the
    fp32 copy of a [B, S, vocab] tensor is gigabytes of HBM traffic,
    and the loss (`training.cross_entropy_loss` → ops/loss.py streaming
    CE) does its math in fp32 without needing an fp32 input tensor."""
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens: jax.Array, train: bool = False,
                 block_tables=None, cursors=None,
                 lengths=None) -> jax.Array:
        cfg = self.cfg
        embed = nn.Embed(cfg.vocab_size, cfg.d_model,
                         dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         name="embed")
        x = embed(tokens)
        block = Block
        if cfg.remat:
            policy = None
            if cfg.remat_policy == "dots":
                policy = jax.checkpoint_policies.checkpoint_dots
            elif cfg.remat_policy != "full":
                raise ValueError(
                    f"unknown remat_policy {cfg.remat_policy!r} "
                    "(expected 'full' or 'dots')")
            block = nn.remat(Block, prevent_cse=False, policy=policy)
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"layer_{i}")(x, block_tables, cursors,
                                              lengths)
        x = RMSNorm(cfg.dtype, cfg.param_dtype, name="final_norm")(x)
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="lm_head")(x)


# ---------------------------------------------------------------------------
# KV-cache incremental decoding (inference serving; serving/replica.py)
# ---------------------------------------------------------------------------
def _with_cache_index(cache: dict, lengths) -> dict:
    """Return ``cache`` with every layer's write cursor set to
    ``lengths`` (scalar or [B] int32) — prefill() rewinds past padding
    with it, and the serving replica resets recycled batch slots."""
    lengths = jnp.asarray(lengths, jnp.int32)

    def fix(node):
        if not isinstance(node, dict):
            return node
        return {key: (jnp.broadcast_to(lengths, val.shape).astype(val.dtype)
                      if key == "cache_index" else fix(val))
                for key, val in node.items()}
    from flax.core import unfreeze
    return fix(unfreeze(cache))


def prefill(model: TransformerLM, variables: dict, tokens: jax.Array,
            lengths=None) -> tuple[jax.Array, dict]:
    """Run the prompt through a ``decode=True`` model and return
    ``(logits [B, T, vocab], cache)``.

    ``lengths`` ([B] or scalar) gives each row's true prompt length when
    ``tokens`` is right-padded to a shared bucket: the KV write cursor
    rewinds to it so the first decode_step overwrites the pad garbage,
    and the causal mask keeps the not-yet-overwritten tail invisible
    (it sits at strictly greater positions than every live query).  The
    next-token logits of row b are ``logits[b, lengths[b] - 1]``."""
    from flax.core import unfreeze
    logits, mut = model.apply(variables, tokens, mutable=["cache"])
    cache = unfreeze(mut["cache"])
    if lengths is not None:
        cache = _with_cache_index(cache, lengths)
    return logits, cache


def decode_step(model: TransformerLM, variables: dict, cache: dict,
                tokens: jax.Array) -> tuple[jax.Array, dict]:
    """One incremental step of a ``decode=True`` model: ``tokens``
    [B, 1] (or [B]) → ``(logits [B, 1, vocab], updated cache)``.  Each
    batch element advances at its own cache depth, which is what lets
    continuous batching admit a fresh prefill into a half-decoded
    batch."""
    from flax.core import unfreeze
    if tokens.ndim == 1:
        tokens = tokens[:, None]
    logits, mut = model.apply({**variables, "cache": cache}, tokens,
                              mutable=["cache"])
    return logits, unfreeze(mut["cache"])


def paged_apply(model: TransformerLM, variables: dict, cache: dict,
                tokens: jax.Array, block_tables, cursors,
                lengths=None) -> tuple[jax.Array, dict]:
    """One paged-cache apply (``decode=True, paged=True``): prefill and
    decode are the SAME call — ``tokens [B, T]`` (T = 1 for a decode
    step, a padded prompt bucket for prefill) write into the pool
    through each row's ``block_tables`` entry at its ``cursors``
    position and attend over the gathered prefix.  No write-cursor
    rewinding: ``lengths`` keeps padded positions out of real blocks
    entirely (they land in the pool's sink row)."""
    from flax.core import unfreeze
    if tokens.ndim == 1:
        tokens = tokens[:, None]
    logits, mut = model.apply({**variables, "cache": cache}, tokens,
                              block_tables=block_tables,
                              cursors=cursors, lengths=lengths,
                              mutable=["cache"])
    return logits, unfreeze(mut["cache"])


def paged_copy_block(cache: dict, src: int, dst: int) -> dict:
    """The tensor half of a copy-on-write: copy pool row ``src`` to
    ``dst`` in every layer's key/value pool (the id half lives in
    serving/kvpool.py ``cow``)."""
    def fix(node):
        if not isinstance(node, dict):
            return node
        return {key: (val.at[dst].set(val[src])
                      if key in ("key_pool", "value_pool") else fix(val))
                for key, val in node.items()}
    from flax.core import unfreeze
    return fix(unfreeze(cache))


# ---------------------------------------------------------------------------
# Presets
# ---------------------------------------------------------------------------
def gpt_small(**overrides) -> TransformerConfig:
    """~124M params (GPT-2 small shape)."""
    return TransformerConfig(**{**dict(
        vocab_size=50304, num_layers=12, num_heads=12, d_model=768,
        max_seq_len=1024), **overrides})


def gpt_medium(**overrides) -> TransformerConfig:
    """~350M params."""
    return TransformerConfig(**{**dict(
        vocab_size=50304, num_layers=24, num_heads=16, d_model=1024,
        max_seq_len=2048), **overrides})


def gpt_tiny(**overrides) -> TransformerConfig:
    """Test-sized config."""
    return TransformerConfig(**{**dict(
        vocab_size=256, num_layers=2, num_heads=4, d_model=64,
        max_seq_len=256), **overrides})
