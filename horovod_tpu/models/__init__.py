"""Flagship model families for horovod_tpu benchmarks and examples.

The reference frames its headline numbers around ImageNet CNNs
(ResNet-50/101, Inception V3, VGG-16 — reference: docs/benchmarks.rst:13-43)
trained data-parallel via its synthetic/ImageNet example scripts
(reference: examples/pytorch/pytorch_synthetic_benchmark.py,
examples/pytorch/pytorch_imagenet_resnet50.py). These are TPU-native
re-implementations in flax, bf16-first, designed so every FLOP-heavy op
lands on the MXU.
"""
from .inception import InceptionV3
from .resnet import (ResNet, ResNet18, ResNet34, ResNet50, ResNet101,
                     ResNet152)
from .transformer import (TransformerConfig, TransformerLM, gpt_medium,
                          gpt_small, gpt_tiny)
from .vgg import VGG, VGG16, VGG19

__all__ = ["ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101",
           "ResNet152", "TransformerConfig", "TransformerLM", "gpt_small",
           "gpt_medium", "gpt_tiny", "VGG", "VGG16", "VGG19",
           "InceptionV3"]
