"""hvdsan — whole-program concurrency verification (ISSUE 8).

Static half: an interprocedural lock-acquisition graph over the package
(:mod:`.lockgraph`) checked for lock-order inversion cycles (HVD501),
locks held across blocking/collective calls (HVD502), orphan condition
waits (HVD503); a declarative thread-ownership manifest
(:mod:`.ownership`, HVD504) that also feeds hvdlint's HVD401; and a
wire-schema drift check between ``common/message.py`` and
``common/wire.py`` (HVD505, :mod:`.san`).

Runtime half: under ``HOROVOD_SAN=1`` lightweight lock wrappers record
actual per-thread acquisition orders (:mod:`.san`) and dump the
observed lock-order graph at shutdown; CI diffs it against the static
graph — observed edges missing statically fail the build, static
cycles never observed demote to warnings.

CLI: ``python -m horovod_tpu.analysis.hvdsan`` (report mode) or
``python -m horovod_tpu.analysis.lint --san`` (alongside the per-file
rules, sharing one parse per file).  Rule table: docs/analysis.md.

This ``__init__`` stays import-light: :func:`maybe_enable` runs at
``horovod_tpu`` import before any package lock exists.
"""
from .san import (apply_witness, dump_witness, enable,  # noqa: F401
                  disable, enabled, maybe_enable, witness,
                  witness_diff)

__all__ = ["maybe_enable", "enable", "disable", "enabled", "witness",
           "dump_witness", "witness_diff", "apply_witness"]
