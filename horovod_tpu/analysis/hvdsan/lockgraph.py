"""hvdsan lock graph — whole-program static lock-acquisition analysis.

hvdlint's concurrency rules (HVD301/HVD401) pattern-match single call
sites; this module builds the *interprocedural* model those rules cannot
see:

1. every ``threading.Lock/RLock/Condition`` creation site is resolved to
   a **stable lock identity** (``module.Class.attr`` keyed by its
   creation ``file:line`` — the same key the runtime witness records, so
   the two graphs diff exactly);
2. a **call graph** over the package (self/annotation/constructor-typed
   receivers resolve confidently; a bounded method-name index fills the
   gaps at lower confidence);
3. a fixpoint computes **which locks can be held at each call site**,
   yielding the lock-order graph: edge ``A → B`` when some thread can
   acquire ``B`` while holding ``A`` (directly nested ``with`` blocks,
   or through any call chain).

On top of that model:

- **HVD501 lock-order-inversion** — a cycle in the lock-order graph:
  two threads taking the same locks in opposite orders deadlock the
  world the first time their schedules interleave.
- **HVD502 lock-held-across-blocking** — a lock held across a blocking
  primitive (socket recv/send, ``urlopen``, thread join, ``wait``, …)
  or a collective, found through any call depth — the interprocedural
  generalization of HVD301.  A ``Condition.wait`` on the held
  condition's own lock is the sanctioned idiom and exempt.
- **HVD503 orphan-condition-wait** — a ``Condition`` some thread waits
  on but **no** code path ever notifies: the wait can only ever end by
  timeout (or never).

Confidence model: edges proven through typed resolution are
*confident*; edges that needed the name-index fallback are demoted, and
findings that depend on them report as warnings, not errors.  The
runtime witness (:mod:`.san`) closes the gap from the other side:
observed edges missing from this graph fail CI (the analyzer is
unsound there), and static cycles never observed demote to warnings.

Suppressions reuse hvdlint's comment form at the anchor line::

    with self._lock:  # hvdlint: disable=HVD502 -- <ordering guarantee>
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from ..rules import RULES, Rule, parse_suppressions

# Callables treated as lock constructors (threading module).
_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}

# Blocking primitives for HVD502 (lexical, like hvdlint's HVD1003 —
# bounded or not, the lock is held for the wait's duration).
BLOCKING_NAMES = frozenset({
    "recv", "recv_into", "recv_bytes", "accept", "select", "urlopen",
    "wait", "wait_for", "join", "sendall", "sendmsg", "connect",
    "create_connection", "communicate", "sleep", "serve_forever",
})

# Collective vocabulary (shared with hvdlint's HVD301).
from ..lint import COLLECTIVE_NAMES  # noqa: E402

# Method-name-index fallback: resolve an untyped `obj.m(...)` to the
# package's definitions of `m` only when few enough exist to be a
# plausible bind — anything wider is noise, and the runtime witness
# covers what the static graph then misses.
_INDEX_FALLBACK_LIMIT = 3


# ---------------------------------------------------------------------------
# Model dataclasses
# ---------------------------------------------------------------------------
@dataclass
class LockInfo:
    key: str                 # "core._init_lock", "runner.network.PeerMesh._lock"
    path: str
    line: int
    kind: str                # lock | rlock | condition
    canonical: str           # != key only for Condition(existing_lock)
    cond_arg: tuple | None = None   # unresolved wrapped-lock spine

    @property
    def site(self) -> str:
        return f"{self.path}:{self.line}"


@dataclass
class CallEvent:
    spine: tuple             # function-expression spine (see _spine)
    held: tuple              # spines of lexically held locks, outer->inner
    line: int
    kwnames: tuple = ()      # keyword argument names (Thread(name=...))
    thread_target: tuple | None = None   # Thread(target=X) spine
    thread_name: str | None = None


@dataclass
class AcquireEvent:
    spine: tuple             # lock expression spine
    held: tuple
    line: int
    via: str                 # "with" | "acquire" | "wait"


@dataclass
class SimpleEvent:
    name: str
    held: tuple
    line: int
    bounded: bool = False


@dataclass
class WriteEvent:
    spine: tuple             # full attribute spine of the write target
    line: int


@dataclass
class FuncRaw:
    key: str
    module: str
    cls: str | None
    name: str
    path: str
    line: int
    acquires: list = field(default_factory=list)     # [AcquireEvent]
    calls: list = field(default_factory=list)        # [CallEvent]
    blocking: list = field(default_factory=list)     # [SimpleEvent]
    collectives: list = field(default_factory=list)  # [SimpleEvent]
    writes: list = field(default_factory=list)       # [WriteEvent]
    local_types: dict = field(default_factory=dict)  # name -> type spine
    param_types: dict = field(default_factory=dict)
    # Protocol-conformance facts (hvdmc HVD506): frame-verb constants
    # this function compares on / packs, and its string literals (KV
    # key prefixes and boundary-flag fields).
    state_compares: set = field(default_factory=set)
    state_packs: set = field(default_factory=set)
    strs: set = field(default_factory=set)


@dataclass
class ClassRaw:
    module: str
    name: str
    bases: list = field(default_factory=list)        # base-class spines
    methods: dict = field(default_factory=dict)      # name -> funckey
    attr_types: dict = field(default_factory=dict)   # attr -> type spine
    attr_elem_types: dict = field(default_factory=dict)  # attr -> dict-value type


@dataclass
class ModuleRaw:
    label: str
    path: str
    is_package: bool
    aliases: dict = field(default_factory=dict)      # name -> ("mod"|"sym", ...)
    classes: dict = field(default_factory=dict)      # name -> ClassRaw
    functions: dict = field(default_factory=dict)    # name -> funckey
    threading_names: set = field(default_factory=set)  # from threading import X
    global_types: dict = field(default_factory=dict)   # module var -> type spine
    int_consts: dict = field(default_factory=dict)   # NAME -> (value, line)
    struct_fmts: dict = field(default_factory=dict)  # name -> (fmt, line)
    strs: set = field(default_factory=set)           # module-level literals


@dataclass
class LockCreation:
    module: str
    cls: str | None
    func: str | None
    target: tuple
    kind: str
    path: str
    line: int
    cond_arg: tuple | None


@dataclass
class Finding:
    rule: Rule
    severity: str            # "error" | "warning"
    path: str
    line: int
    message: str
    sites: tuple = ()        # extra (path, line) anchors (cycle edges)

    def text(self) -> str:
        sev = "" if self.severity == "error" else " (warning)"
        return (f"{self.path}:{self.line}:1: {self.rule.id} "
                f"[{self.rule.slug}]{sev} {self.message}")

    def json(self) -> dict:
        return {"path": self.path, "line": self.line,
                "rule": self.rule.id, "slug": self.rule.slug,
                "severity": self.severity, "message": self.message,
                "sites": [f"{p}:{ln}" for p, ln in self.sites]}


@dataclass
class Edge:
    src: str
    dst: str
    confident: bool
    sites: list = field(default_factory=list)   # [(path, line, via-label)]


# ---------------------------------------------------------------------------
# Spine extraction
# ---------------------------------------------------------------------------
_SUBSCRIPT = "[]"
_CALLMARK = "()"

# Method names so pervasive on builtins (str/bytes/dict/set) that the
# name-index fallback would bind them to unrelated package classes —
# `coordinator_address.encode()` is not `Request.encode`.
_INDEX_DENY = frozenset({
    "encode", "decode", "get", "put", "set", "items", "keys", "values",
    "update", "pop", "append", "extend", "clear", "copy", "split",
    "strip", "format", "setdefault", "discard", "add", "remove",
    "read", "write", "close", "open", "sort", "index", "count",
})


def _spine(node: ast.AST) -> tuple | None:
    """Dotted access chain as a tuple of names, left to right:
    ``self._channels[peer].send_sync`` -> ("self", "_channels", "[]",
    "send_sync"); chains through calls keep a "()" marker
    (``f(...).inc`` -> ("f", "()", "inc")).  None for anything not a
    plain chain."""
    parts: list[str] = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Subscript):
            parts.append(_SUBSCRIPT)
            node = node.value
        elif isinstance(node, ast.Call):
            parts.append(_CALLMARK)
            node = node.func
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            return tuple(reversed(parts))
        else:
            return None


def _ann_spine(node: ast.AST | None) -> tuple | None:
    """Type spine from an annotation: Name/Attribute directly;
    ``X | None`` takes X; ``dict[k, v]``/``list[v]`` handled by
    :func:`_ann_elem_spine`."""
    if node is None:
        return None
    if isinstance(node, ast.BinOp):           # X | None
        left = _ann_spine(node.left)
        return left if left else _ann_spine(node.right)
    if isinstance(node, ast.Subscript):       # Optional[X], dict[...]
        base = _spine(node.value)
        if base and base[-1] == "Optional":
            return _ann_spine(node.slice)
        return base
    if isinstance(node, ast.Constant):
        return None
    return _spine(node)


def _ann_elem_spine(node: ast.AST | None) -> tuple | None:
    """Container value-type from ``dict[K, V]`` / ``list[V]``."""
    if not isinstance(node, ast.Subscript):
        return None
    base = _spine(node.value)
    if not base:
        return None
    sl = node.slice
    if base[-1] == "dict" and isinstance(sl, ast.Tuple) and \
            len(sl.elts) == 2:
        return _ann_spine(sl.elts[1])
    if base[-1] in ("list", "deque", "set", "frozenset"):
        return _ann_spine(sl)
    return None


def module_label(path: str) -> str:
    """Module label relative to the horovod_tpu package root:
    horovod_tpu/runner/network.py -> "runner.network"; files outside the
    package (fixtures) use their basename."""
    norm = os.path.normpath(path).replace(os.sep, "/")
    parts = norm.split("/")
    if "horovod_tpu" in parts:
        rel = parts[parts.index("horovod_tpu") + 1:]
    else:
        rel = parts[-1:]
    if not rel:
        return ""
    rel = list(rel)
    rel[-1] = rel[-1][:-3] if rel[-1].endswith(".py") else rel[-1]
    if rel[-1] == "__init__":
        rel = rel[:-1]
    return ".".join(rel)


def norm_path(path: str) -> str:
    """Stable display path: from the horovod_tpu component when present
    (matches the runtime witness's creation-site normalization)."""
    norm = os.path.normpath(os.path.abspath(path)).replace(os.sep, "/")
    idx = norm.find("horovod_tpu/")
    return norm[idx:] if idx >= 0 else os.path.normpath(path)


# ---------------------------------------------------------------------------
# Collection (one AST walk per file)
# ---------------------------------------------------------------------------
class Program:
    """Whole-program raw facts, accumulated one file at a time."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleRaw] = {}
        self.functions: dict[str, FuncRaw] = {}
        self.lock_creations: list[LockCreation] = []
        self.suppressions: dict[str, object] = {}    # path -> Suppressions
        self.wire_codecs: list = []                  # per-class encode/decode seqs
        self.wire_prims: dict[str, set] = {}         # Encoder/Decoder method names
        self.state_frames: list = []                 # pack/unpack_state_frame facts

    def collect_source(self, path: str, source: str,
                       tree: ast.AST | None = None) -> None:
        if tree is None:
            tree = ast.parse(source, filename=path)
        disp = norm_path(path)
        self.suppressions[disp] = parse_suppressions(source)
        label = module_label(path)
        mod = ModuleRaw(label=label, path=disp,
                        is_package=os.path.basename(path) == "__init__.py")
        self.modules[label] = mod
        _Collector(self, mod).visit(tree)

    def collect_paths(self, paths) -> None:
        from ..lint import iter_python_files
        for p in iter_python_files(list(paths)):
            try:
                with open(p, encoding="utf-8") as f:
                    src = f.read()
                self.collect_source(p, src)
            except (OSError, SyntaxError):
                continue


class _Collector(ast.NodeVisitor):
    """Single-pass per-file fact extractor."""

    def __init__(self, program: Program, mod: ModuleRaw) -> None:
        self.p = program
        self.mod = mod
        self._cls_stack: list[ClassRaw] = []
        self._fn_stack: list[FuncRaw] = []
        self._held: list[tuple] = []     # spines of lexically held locks

    # -- context helpers -------------------------------------------------
    @property
    def _cls(self) -> ClassRaw | None:
        return self._cls_stack[-1] if self._cls_stack else None

    @property
    def _fn(self) -> FuncRaw | None:
        return self._fn_stack[-1] if self._fn_stack else None

    def _qual(self, name: str) -> str:
        parts = [self.mod.label] if self.mod.label else []
        if self._cls_stack:
            parts.append(self._cls_stack[-1].name)
        parts.extend(f.name for f in self._fn_stack)
        parts.append(name)
        return ".".join(parts)

    # -- imports ---------------------------------------------------------
    def _module_package(self) -> list[str]:
        parts = self.mod.label.split(".") if self.mod.label else []
        return parts if self.mod.is_package else parts[:-1]

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.name
            asname = alias.asname or name.split(".")[0]
            if name == "threading":
                self.mod.aliases.setdefault(asname, ("mod", "~threading"))
            elif name.startswith("horovod_tpu"):
                target = name[len("horovod_tpu"):].lstrip(".")
                self.mod.aliases[alias.asname or name] = ("mod", target)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.level == 0:
            if node.module == "threading":
                for alias in node.names:
                    self.mod.threading_names.add(alias.asname or alias.name)
            elif node.module and node.module.startswith("horovod_tpu"):
                base = node.module[len("horovod_tpu"):].lstrip(".")
                for alias in node.names:
                    self.mod.aliases[alias.asname or alias.name] = \
                        ("sym", base, alias.name)
            return
        pkg = self._module_package()
        up = node.level - 1
        base_parts = pkg[:len(pkg) - up] if up else pkg
        base = ".".join(base_parts + (node.module.split(".")
                                      if node.module else []))
        for alias in node.names:
            local = alias.asname or alias.name
            if node.module is None:
                # from . import x [as y]  -> module alias
                target = ".".join(filter(None, [base, alias.name]))
                self.mod.aliases[local] = ("mod", target)
            else:
                self.mod.aliases[local] = ("sym", base, alias.name)

    # -- classes / functions ---------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        cls = ClassRaw(module=self.mod.label, name=node.name,
                       bases=[s for s in map(_spine, node.bases) if s])
        self.mod.classes[node.name] = cls
        if node.name in ("Encoder", "Decoder") and \
                self.mod.label.endswith("wire"):
            from .san import note_wire_class
            note_wire_class(self.p, self.mod, node)
        self._cls_stack.append(cls)
        # Class-body AnnAssigns type instance attrs via __slots__-style
        # annotations (dataclasses): X: SomeType
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                t = _ann_spine(stmt.annotation)
                if t:
                    cls.attr_types.setdefault(stmt.target.id, t)
                elem = _ann_elem_spine(stmt.annotation)
                if elem:
                    cls.attr_elem_types.setdefault(stmt.target.id, elem)
        self.generic_visit(node)
        self._cls_stack.pop()

    def _visit_function(self, node) -> None:
        key = self._qual(node.name)
        fn = FuncRaw(key=key, module=self.mod.label,
                     cls=self._cls.name if (self._cls and
                                            not self._fn_stack) else None,
                     name=node.name, path=self.mod.path, line=node.lineno)
        args = node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            t = _ann_spine(a.annotation)
            if t:
                fn.param_types[a.arg] = t
        if self._cls and not self._fn_stack:
            self._cls.methods[node.name] = key
        elif not self._fn_stack:
            self.mod.functions[node.name] = key
        self.p.functions[key] = fn
        # Nested defs execute later, usually on another thread: they get
        # their own node with an EMPTY lexical held-stack.
        saved_held, self._held = self._held, []
        self._fn_stack.append(fn)
        self.generic_visit(node)
        self._fn_stack.pop()
        self._held = saved_held
        if node.name in ("encode", "decode", "to_bytes", "from_bytes") \
                and self._cls:
            from .san import collect_wire_method
            collect_wire_method(self.p, self.mod, self._cls, node)
        if node.name in ("pack_state_frame", "unpack_state_frame") \
                and not self._cls_stack:
            from .san import collect_state_frame
            collect_state_frame(self.p, self.mod, node)

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    # -- lock creation + type hints on assignment ------------------------
    def _lock_ctor(self, value: ast.AST) -> tuple[str, tuple | None] | None:
        """(kind, condition-arg-spine) when `value` constructs a
        threading primitive."""
        if not isinstance(value, ast.Call):
            return None
        sp = _spine(value.func)
        if not sp:
            return None
        name = sp[-1]
        if name not in _LOCK_CTORS:
            return None
        ok = (len(sp) >= 2 and sp[-2] == "threading") or \
            (len(sp) == 1 and name in self.mod.threading_names)
        if not ok:
            return None
        arg = _spine(value.args[0]) if (name == "Condition" and
                                        value.args) else None
        return _LOCK_CTORS[name], arg

    def _note_assign(self, target: ast.AST, value: ast.AST,
                     annotation: ast.AST | None = None) -> None:
        tsp = _spine(target)
        if not tsp:
            return
        # Module-level facts for the wire/spec drift rules: frame-kind
        # constants (STATE_HELLO = 1) and struct.Struct formats.
        if self._fn is None and self._cls is None and len(tsp) == 1:
            if isinstance(value, ast.Constant) and \
                    isinstance(value.value, int) and tsp[0].isupper():
                self.mod.int_consts[tsp[0]] = (value.value,
                                               target.lineno)
            elif isinstance(value, ast.Call):
                vsp = _spine(value.func)
                if vsp and vsp[-1] == "Struct" and value.args and \
                        isinstance(value.args[0], ast.Constant) and \
                        isinstance(value.args[0].value, str):
                    self.mod.struct_fmts[tsp[0]] = \
                        (value.args[0].value, target.lineno)
        ctor = self._lock_ctor(value) if value is not None else None
        if ctor is not None:
            kind, cond_arg = ctor
            self.p.lock_creations.append(LockCreation(
                module=self.mod.label,
                cls=self._cls.name if self._cls else None,
                func=self._fn.name if self._fn else None,
                target=tsp, kind=kind, path=self.mod.path,
                line=target.lineno, cond_arg=cond_arg))
            return
        # Type hints: self.x = ClassName(...) / self.x = typed_param /
        # local = ClassName(...) / annotated targets.
        tspine = None
        if isinstance(value, ast.Call):
            tspine = _spine(value.func)
            if tspine and not tspine[-1][:1].isupper():
                tspine = None                    # only Class-looking ctors
        elif isinstance(value, ast.Name):
            # st = _global / x = typed_param: propagate the known type.
            tspine = (self._fn.param_types.get(value.id)
                      if self._fn else None) or \
                self.mod.global_types.get(value.id)
        if tspine is None and annotation is not None:
            tspine = _ann_spine(annotation)
        if tspine:
            if len(tsp) == 2 and tsp[0] == "self" and self._cls:
                self._cls.attr_types.setdefault(tsp[1], tspine)
            elif len(tsp) == 1 and self._fn:
                self._fn.local_types.setdefault(tsp[0], tspine)
            elif len(tsp) == 1 and self._fn is None and \
                    self._cls is None:
                self.mod.global_types.setdefault(tsp[0], tspine)
        if annotation is not None and len(tsp) == 2 and tsp[0] == "self" \
                and self._cls:
            elem = _ann_elem_spine(annotation)
            if elem:
                self._cls.attr_elem_types.setdefault(tsp[1], elem)
        # local = self._attr[k]  -> element type of a typed container
        if isinstance(value, ast.Subscript) and self._fn and len(tsp) == 1:
            vs = _spine(value)
            if vs and vs[0] == "self" and len(vs) == 3 and \
                    vs[2] == _SUBSCRIPT and self._cls:
                elem = self._cls.attr_elem_types.get(vs[1])
                if elem:
                    self._fn.local_types.setdefault(tsp[0], elem)
        # local = self._attr.get(k) on a typed container
        if isinstance(value, ast.Call) and self._fn and len(tsp) == 1:
            vs = _spine(value.func)
            if vs and vs[0] == "self" and len(vs) == 3 and \
                    vs[2] == "get" and self._cls:
                elem = self._cls.attr_elem_types.get(vs[1])
                if elem:
                    self._fn.local_types.setdefault(tsp[0], elem)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._note_assign(t, node.value)
            self._note_write(t, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None or True:
            self._note_assign(node.target, node.value, node.annotation)
        self._note_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._note_write(node.target, node.lineno)
        self.generic_visit(node)

    def _note_write(self, target: ast.AST, line: int) -> None:
        if self._fn is None or not isinstance(target, ast.Attribute):
            return
        sp = _spine(target)
        if sp:
            self._fn.writes.append(WriteEvent(spine=sp, line=line))

    # -- with blocks (lock holds) ----------------------------------------
    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            sp = _spine(item.context_expr)
            if sp and self._fn is not None:
                self._fn.acquires.append(AcquireEvent(
                    spine=sp, held=tuple(self._held),
                    line=node.lineno, via="with"))
                self._held.append(sp)
                pushed += 1
        for n in node.body:
            self.visit(n)
        for _ in range(pushed):
            self._held.pop()

    visit_AsyncWith = visit_With

    # -- calls -----------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        fn = self._fn
        sp = _spine(node.func)
        if fn is not None and sp:
            name = sp[-1]
            held = tuple(self._held)
            if name == "acquire" and len(sp) >= 2:
                fn.acquires.append(AcquireEvent(
                    spine=sp[:-1], held=held, line=node.lineno,
                    via="acquire"))
            elif name in ("wait", "wait_for") and len(sp) >= 2:
                fn.acquires.append(AcquireEvent(
                    spine=sp[:-1], held=held, line=node.lineno,
                    via="wait"))
            if name in ("notify", "notify_all") and len(sp) >= 2:
                fn.acquires.append(AcquireEvent(
                    spine=sp[:-1], held=held, line=node.lineno,
                    via="notify"))
            if name in BLOCKING_NAMES and not self._join_exempt(node, name):
                fn.blocking.append(SimpleEvent(
                    name=name, held=held, line=node.lineno))
            if name in COLLECTIVE_NAMES:
                fn.collectives.append(SimpleEvent(
                    name=name, held=held, line=node.lineno))
            thread_target = thread_name = None
            if name == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        thread_target = _spine(kw.value)
                    elif kw.arg == "name":
                        thread_name = self._name_literal(kw.value)
            elif name == "Timer":
                # threading.Timer(interval, function): a one-shot thread
                # root (the preempt-grace backstop).  The ownership
                # manifest's THREAD_ROOTS names it.
                if len(node.args) >= 2:
                    thread_target = _spine(node.args[1])
                for kw in node.keywords:
                    if kw.arg == "function":
                        thread_target = _spine(kw.value)
            if name == "pack_state_frame" and node.args:
                asp = _spine(node.args[0])
                if asp and len(asp) == 1 and asp[0].isupper():
                    fn.state_packs.add(asp[0])
            fn.calls.append(CallEvent(
                spine=sp, held=held, line=node.lineno,
                kwnames=tuple(kw.arg for kw in node.keywords if kw.arg),
                thread_target=thread_target, thread_name=thread_name))
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        if self._fn is not None:
            for sub in (node.left, *node.comparators):
                sp = _spine(sub)
                if sp and sp[-1].isupper() and \
                        sp[-1].startswith("STATE_") and \
                        not sp[-1].endswith("MAGIC"):
                    self._fn.state_compares.add(sp[-1])
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> None:
        if isinstance(node.value, str) and len(node.value) <= 48:
            if self._fn is not None:
                self._fn.strs.add(node.value)
            else:
                self.mod.strs.add(node.value)

    @staticmethod
    def _name_literal(node: ast.AST) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.JoinedStr):
            head = ""
            for v in node.values:
                if isinstance(v, ast.Constant):
                    head += str(v.value)
                else:
                    return head + "*"
            return head
        return None

    @staticmethod
    def _join_exempt(node: ast.Call, name: str) -> bool:
        """str.join / os.path.join — not waits (mirrors hvdlint)."""
        if name != "join" or not isinstance(node.func, ast.Attribute):
            return name == "join"        # bare join() — not a thread join
        base = node.func.value
        if isinstance(base, ast.Constant) and isinstance(base.value, str):
            return True
        sp = _spine(node.func)
        if sp and set(sp[:-1]) & {"path", "sep", "pathsep", "linesep",
                                  "os", "posixpath", "ntpath"}:
            return True
        return False


# ---------------------------------------------------------------------------
# Resolution + analysis
# ---------------------------------------------------------------------------
class Analysis:
    """Resolved lock identities, lock-order edges, and findings."""

    def __init__(self, program: Program) -> None:
        self.program = program
        self.locks: dict[str, LockInfo] = {}
        self.edges: dict[tuple[str, str], Edge] = {}
        self.findings: list[Finding] = []
        # funckey -> {lockkey: confident}
        self.acquires_closure: dict[str, dict[str, bool]] = {}
        # funckey -> {prim-label: (confident, (path, line))}
        self.blocking_closure: dict[str, dict] = {}
        # thread roots: funckey -> thread name
        self.thread_roots: dict[str, str] = {}
        self.thread_reach: dict[str, set] = {}   # funckey -> {thread names}
        self._attr_index: dict[str, list[str]] = {}
        self._method_index: dict[str, list[str]] = {}
        self._cond_waits: dict[str, list] = {}
        self._cond_notifies: dict[str, list] = {}
        self._call_targets: dict[int, list] = {}  # id(CallEvent) -> targets

    # -- lock identity resolution ----------------------------------------
    def _register_locks(self) -> None:
        for c in self.program.lock_creations:
            parts = [c.module] if c.module else []
            if c.cls:
                parts.append(c.cls)
            if c.func and c.target[0] != "self":
                parts.append(c.func)
            tail = c.target[1:] if c.target[0] in ("self", "cls") \
                else c.target
            parts.extend(tail)
            key = ".".join(parts)
            info = LockInfo(key=key, path=c.path, line=c.line,
                            kind=c.kind, canonical=key,
                            cond_arg=c.cond_arg)
            self.locks[key] = info
            self._attr_index.setdefault(tail[-1], []).append(key)
        # Condition(existing_lock): alias to the wrapped lock.
        for c in self.program.lock_creations:
            if c.kind != "condition" or not c.cond_arg:
                continue
            parts = [c.module] if c.module else []
            if c.cls:
                parts.append(c.cls)
            if c.func and c.target[0] != "self":
                parts.append(c.func)
            tail = c.target[1:] if c.target[0] in ("self", "cls") \
                else c.target
            key = ".".join(parts + list(tail))
            wrapped = self.resolve_lock(c.cond_arg, c.module, c.cls,
                                        c.func)
            if wrapped and wrapped != key:
                self.locks[key].canonical = wrapped

    def resolve_lock(self, spine: tuple, module: str, cls: str | None,
                     func: str | None) -> str | None:
        """Creation-site identity for a lock expression spine, or None."""
        if not spine:
            return None
        attr = spine[-1]
        cand = self._attr_index.get(attr)
        if not cand:
            return None
        if spine[0] in ("self", "cls") and len(spine) == 2 and cls:
            # own class, then lexical bases, then module, then unique.
            seen = set()
            stack = [(module, cls)]
            while stack:
                m, cn = stack.pop()
                if (m, cn) in seen:
                    continue
                seen.add((m, cn))
                key = ".".join(filter(None, [m, cn, attr]))
                if key in self.locks:
                    return key
                craw = self.program.modules.get(m)
                craw = craw.classes.get(cn) if craw else None
                if craw:
                    for b in craw.bases:
                        bres = self._resolve_class_spine(b, m)
                        if bres:
                            stack.append(bres)
        elif len(spine) == 1:
            if func:
                for ctx_cls in (cls, None):
                    key = ".".join(filter(None, [module, ctx_cls, func,
                                                 attr]))
                    if key in self.locks:
                        return key
            key = ".".join(filter(None, [module, attr]))
            if key in self.locks:
                return key
            # bare name in a method may still be the module global
            in_module = [k for k in cand
                         if k.rsplit(".", 1)[0] == module]
            if len(in_module) == 1:
                return in_module[0]
            return None
        # final-attr uniqueness fallbacks (module first, then package)
        in_module = [k for k in cand if k.startswith(module + ".")
                     or k == f"{module}.{attr}"]
        if len(in_module) == 1:
            return in_module[0]
        if len(cand) == 1:
            return cand[0]
        return None

    def canonical(self, key: str) -> str:
        info = self.locks.get(key)
        return info.canonical if info else key

    # -- class/symbol resolution -----------------------------------------
    def _resolve_class_spine(self, spine: tuple, module: str,
                             _depth: int = 0) -> tuple | None:
        """(module, classname) for a type spine in `module`'s scope."""
        if not spine or _depth > 6:
            return None
        mod = self.program.modules.get(module)
        if mod is None:
            return None
        name = spine[-1]
        if len(spine) == 1:
            if name in mod.classes:
                return (module, name)
            alias = mod.aliases.get(name)
            if alias and alias[0] == "sym":
                return self._find_class(alias[1], alias[2], _depth + 1)
            return None
        # a.b.C through a module alias
        alias = mod.aliases.get(spine[0])
        target = self._alias_module(alias)
        if target is not None and target != "~threading":
            for part in spine[1:-1]:
                target = f"{target}.{part}" if target else part
            return self._find_class(target, name, _depth + 1)
        return None

    def _find_class(self, module: str, name: str,
                    _depth: int = 0) -> tuple | None:
        if _depth > 6:
            return None
        mod = self.program.modules.get(module)
        if mod is None:
            return None
        if name in mod.classes:
            return (module, name)
        alias = mod.aliases.get(name)
        if alias and alias[0] == "sym":
            return self._find_class(alias[1], alias[2], _depth + 1)
        if alias and alias[0] == "mod":
            return None
        return None

    def _find_function(self, module: str, name: str,
                       _depth: int = 0) -> str | None:
        if _depth > 6:
            return None
        mod = self.program.modules.get(module)
        if mod is None:
            return None
        if name in mod.functions:
            return mod.functions[name]
        alias = mod.aliases.get(name)
        if alias and alias[0] == "sym":
            return self._find_function(alias[1], alias[2], _depth + 1)
        return None

    def _class_method(self, module: str, cls: str, meth: str,
                      _depth: int = 0) -> str | None:
        if _depth > 8:
            return None
        mod = self.program.modules.get(module)
        craw = mod.classes.get(cls) if mod else None
        if craw is None:
            return None
        if meth in craw.methods:
            return craw.methods[meth]
        for b in craw.bases:
            bres = self._resolve_class_spine(b, module)
            if bres:
                hit = self._class_method(bres[0], bres[1], meth,
                                         _depth + 1)
                if hit:
                    return hit
        return None

    def _ctor(self, module: str, cls: str) -> str | None:
        return self._class_method(module, cls, "__init__")

    def _receiver_type(self, fn: FuncRaw, spine: tuple) -> tuple | None:
        """(module, classname) of the receiver `spine` (everything but
        the final method name), or None."""
        recv = spine[:-1]
        if not recv:
            return None
        craw = None
        if fn.cls:
            mod = self.program.modules.get(fn.module)
            craw = mod.classes.get(fn.cls) if mod else None
        if recv[0] in ("self", "cls") and craw is not None:
            t: tuple | None = (fn.module, fn.cls)
            i = 1
            while i < len(recv) and t is not None:
                attr = recv[i]
                m, cn = t
                mod2 = self.program.modules.get(m)
                c2 = mod2.classes.get(cn) if mod2 else None
                if c2 is None:
                    return None
                if i + 1 < len(recv) and recv[i + 1] == _SUBSCRIPT:
                    tsp = c2.attr_elem_types.get(attr)
                    i += 2
                else:
                    tsp = c2.attr_types.get(attr)
                    i += 1
                t = self._resolve_class_spine(tsp, m) if tsp else None
            return t
        # local / param / module-global: walk attr chain through the
        # classes' attr_types (dataclass annotations cover _global).
        mod = self.program.modules.get(fn.module)
        tsp = fn.local_types.get(recv[0]) or \
            fn.param_types.get(recv[0]) or \
            (mod.global_types.get(recv[0]) if mod else None)
        if not tsp:
            return None
        t = self._resolve_class_spine(tsp, fn.module)
        i = 1
        while i < len(recv) and t is not None:
            attr = recv[i]
            m, cn = t
            mod2 = self.program.modules.get(m)
            c2 = mod2.classes.get(cn) if mod2 else None
            if c2 is None:
                return None
            if i + 1 < len(recv) and recv[i + 1] == _SUBSCRIPT:
                nsp = c2.attr_elem_types.get(attr)
                i += 2
            else:
                nsp = c2.attr_types.get(attr)
                i += 1
            t = self._resolve_class_spine(nsp, m) if nsp else None
        return t

    def resolve_call(self, fn: FuncRaw, ev: CallEvent) -> list:
        """[(funckey, confident)] targets of one call event."""
        cached = self._call_targets.get(id(ev))
        if cached is not None:
            return cached
        out = self._resolve_call_uncached(fn, ev)
        self._call_targets[id(ev)] = out
        return out

    def _resolve_call_uncached(self, fn: FuncRaw, ev: CallEvent) -> list:
        sp = ev.spine
        name = sp[-1]
        mod = self.program.modules.get(fn.module)
        # 1. bare name: module function / imported symbol / class ctor
        if len(sp) == 1:
            if mod and name in mod.functions:
                return [(mod.functions[name], True)]
            if mod and name in mod.classes:
                ctor = self._ctor(fn.module, name)
                return [(ctor, True)] if ctor else []
            alias = mod.aliases.get(name) if mod else None
            if alias and alias[0] == "sym":
                f = self._find_function(alias[1], alias[2])
                if f:
                    return [(f, True)]
                c = self._find_class(alias[1], alias[2])
                if c:
                    ctor = self._ctor(*c)
                    return [(ctor, True)] if ctor else []
            # nested function defined in this same function
            nested = f"{fn.key}.{name}"
            if nested in self.program.functions:
                return [(nested, True)]
            return []
        # 2. typed receiver (self / annotated / constructed)
        t = self._receiver_type(fn, sp)
        if t is not None:
            hit = self._class_method(t[0], t[1], name)
            return [(hit, True)] if hit else []
        # 2b. ClassName.method (static-ish)
        if len(sp) == 2:
            c = None
            if mod and sp[0] in mod.classes:
                c = (fn.module, sp[0])
            else:
                alias = mod.aliases.get(sp[0]) if mod else None
                if alias and alias[0] == "sym":
                    c = self._find_class(alias[1], alias[2])
            if c is not None:
                hit = self._class_method(c[0], c[1], name)
                return [(hit, True)] if hit else []
        # 3. module alias chain: pkg.sub.func / pkg.func — including
        # modules imported as symbols (`from .parallel import multihost`)
        alias = mod.aliases.get(sp[0]) if mod else None
        target = self._alias_module(alias)
        if target is not None:
            if target == "~threading":
                return []
            for part in sp[1:-1]:
                nxt = f"{target}.{part}" if target else part
                if nxt in self.program.modules:
                    target = nxt
                else:
                    c = self._find_class(target, part)
                    if c:
                        hit = self._class_method(c[0], c[1], name)
                        return [(hit, True)] if hit else []
                    return []
            f = self._find_function(target, name)
            if f:
                return [(f, True)]
            c = self._find_class(target, name)
            if c:
                ctor = self._ctor(*c)
                return [(ctor, True)] if ctor else []
            return []
        # 4. bounded method-name index fallback (low confidence)
        if name in _INDEX_DENY:
            return []
        cands = self._method_index.get(name, [])
        if 1 <= len(cands) <= _INDEX_FALLBACK_LIMIT:
            return [(k, False) for k in cands]
        return []

    def _alias_module(self, alias) -> str | None:
        """Module label an import alias denotes, for both spellings:
        `from . import x` and `from .pkg import submodule`."""
        if not alias:
            return None
        if alias[0] == "mod":
            return alias[1]
        base, nm = alias[1], alias[2]
        cand = f"{base}.{nm}" if base else nm
        return cand if (cand in self.program.modules
                        or cand == "~threading") else None

    # -- fixpoints --------------------------------------------------------
    def _build_indexes(self) -> None:
        for mod in self.program.modules.values():
            for craw in mod.classes.values():
                for mname, fkey in craw.methods.items():
                    if mname.startswith("__"):
                        continue
                    self._method_index.setdefault(mname, []).append(fkey)

    def _resolve_all_calls(self) -> None:
        for fn in self.program.functions.values():
            for ev in fn.calls:
                self.resolve_call(fn, ev)

    def _fix_acquires(self) -> None:
        acq = {k: {} for k in self.program.functions}
        for fn in self.program.functions.values():
            for ev in fn.acquires:
                if ev.via == "notify":
                    continue
                key = self.resolve_lock(ev.spine, fn.module, fn.cls,
                                        fn.name)
                if key:
                    acq[fn.key][self.canonical(key)] = True
        changed = True
        while changed:
            changed = False
            for fn in self.program.functions.values():
                mine = acq[fn.key]
                for ev in fn.calls:
                    for g, confg in self._call_targets.get(id(ev), []):
                        for b, confb in acq.get(g, {}).items():
                            conf = confg and confb
                            if mine.get(b) is None or \
                                    (conf and not mine[b]):
                                mine[b] = conf
                                changed = True
        self.acquires_closure = acq

    def _fix_blocking(self) -> None:
        blk: dict[str, dict] = {k: {} for k in self.program.functions}
        for fn in self.program.functions.values():
            for ev in fn.blocking:
                blk[fn.key].setdefault(
                    ev.name, (True, (fn.path, ev.line)))
            for ev in fn.collectives:
                blk[fn.key].setdefault(
                    f"collective {ev.name}", (True, (fn.path, ev.line)))
        changed = True
        while changed:
            changed = False
            for fn in self.program.functions.values():
                mine = blk[fn.key]
                for ev in fn.calls:
                    for g, confg in self._call_targets.get(id(ev), []):
                        for label, (confb, site) in blk.get(g, {}).items():
                            conf = confg and confb
                            cur = mine.get(label)
                            if cur is None or (conf and not cur[0]):
                                mine[label] = (conf, site)
                                changed = True
        self.blocking_closure = blk

    def _fix_threads(self) -> None:
        for fn in self.program.functions.values():
            for ev in fn.calls:
                if ev.spine[-1] not in ("Thread", "Timer") or \
                        ev.thread_target is None:
                    continue
                pseudo = CallEvent(spine=ev.thread_target, held=(),
                                   line=ev.line)
                for tkey, _conf in self._resolve_call_uncached(fn, pseudo):
                    self.thread_roots[tkey] = ev.thread_name or \
                        f"thread@{fn.path}:{ev.line}"
        # Manifest-declared roots (ownership.THREAD_ROOTS): Thread
        # subclasses (run() overrides) and Timer callbacks static
        # target resolution can miss get their stable names here.
        from .ownership import THREAD_ROOTS
        for tname, (funckey, _why) in THREAD_ROOTS.items():
            if funckey in self.program.functions:
                self.thread_roots[funckey] = tname
        reach: dict[str, set] = {k: set() for k in self.program.functions}
        for root, tname in self.thread_roots.items():
            stack = [root]
            seen = set()
            while stack:
                k = stack.pop()
                if k in seen or k not in reach:
                    continue
                seen.add(k)
                reach[k].add(tname)
                fn = self.program.functions.get(k)
                if fn is None:
                    continue
                for ev in fn.calls:
                    for g, _c in self._call_targets.get(id(ev), []):
                        stack.append(g)
        self.thread_reach = reach

    # -- edges ------------------------------------------------------------
    def _add_edge(self, a: str, b: str, confident: bool, path: str,
                  line: int, label: str) -> None:
        if a == b:
            return
        e = self.edges.get((a, b))
        if e is None:
            e = Edge(src=a, dst=b, confident=confident)
            self.edges[(a, b)] = e
        elif confident and not e.confident:
            e.confident = True
        if len(e.sites) < 8:
            e.sites.append((path, line, label))

    def _build_edges(self) -> None:
        for fn in self.program.functions.values():
            for ev in fn.acquires:
                if ev.via == "notify" or not ev.held:
                    continue
                b = self.resolve_lock(ev.spine, fn.module, fn.cls,
                                      fn.name)
                if not b:
                    continue
                b = self.canonical(b)
                for hs in ev.held:
                    a = self.resolve_lock(hs, fn.module, fn.cls, fn.name)
                    if a:
                        self._add_edge(self.canonical(a), b, True,
                                       fn.path, ev.line,
                                       f"{fn.key} ({ev.via})")
            for ev in fn.calls:
                if not ev.held:
                    continue
                held_keys = [self.canonical(k) for k in
                             (self.resolve_lock(hs, fn.module, fn.cls,
                                                fn.name)
                              for hs in ev.held) if k]
                if not held_keys:
                    continue
                for g, confg in self._call_targets.get(id(ev), []):
                    for b, confb in self.acquires_closure.get(g,
                                                              {}).items():
                        for a in held_keys:
                            self._add_edge(a, b, confg and confb,
                                           fn.path, ev.line,
                                           f"{fn.key} -> {g}")

    # -- findings ---------------------------------------------------------
    def _suppressed(self, path: str, line: int, rule: Rule) -> bool:
        sup = self.program.suppressions.get(path)
        return bool(sup and sup.active(line, rule))

    def _emit(self, rule_key: str, severity: str, path: str, line: int,
              message: str, sites: tuple = ()) -> None:
        rule = RULES[rule_key]
        if self._suppressed(path, line, rule):
            return
        for p, ln in sites:
            if self._suppressed(p, ln, rule):
                return
        self.findings.append(Finding(rule=rule, severity=severity,
                                     path=path, line=line,
                                     message=message, sites=sites))

    def _find_cycles(self) -> None:
        """HVD501: cycles in the lock-order graph (Tarjan SCCs; one
        finding per cyclic SCC, anchored at its first edge site)."""
        for confident_only in (True, False):
            adj: dict[str, list[str]] = {}
            for (a, b), e in self.edges.items():
                if confident_only and not e.confident:
                    continue
                adj.setdefault(a, []).append(b)
            for scc in _tarjan(adj):
                in_scc = set(scc)
                cyc_edges = [e for (a, b), e in self.edges.items()
                             if a in in_scc and b in in_scc
                             and (e.confident or not confident_only)]
                if len(scc) == 1:
                    continue
                if confident_only:
                    severity = "error"
                elif all(e.confident for e in cyc_edges):
                    continue       # already reported in the error pass
                else:
                    severity = "warning"
                cycle = " -> ".join(sorted(in_scc)) + \
                    f" -> {sorted(in_scc)[0]}"
                prov = "; ".join(
                    f"{e.src}->{e.dst} at {e.sites[0][0]}:{e.sites[0][1]}"
                    f" ({e.sites[0][2]})" for e in cyc_edges[:6])
                first = cyc_edges[0].sites[0]
                self._emit(
                    "lock-order-inversion", severity, first[0], first[1],
                    f"lock-order inversion cycle: {cycle}.  Two threads "
                    f"taking these locks in opposite orders deadlock the "
                    f"world; impose one global order or drop a lock "
                    f"before taking the next.  Edges: {prov}",
                    sites=tuple((e.sites[0][0], e.sites[0][1])
                                for e in cyc_edges))

    def _find_held_blocking(self) -> None:
        """HVD502: lock held across a blocking/collective call, direct
        or through any call chain."""
        from .ownership import blocking_allowed_under
        reported: set = set()
        for fn in self.program.functions.values():
            for ev in fn.blocking + fn.collectives:
                if not ev.held:
                    continue
                held = self._held_keys(fn, ev.held)
                label = getattr(ev, "name", "?")
                if label in ("wait", "wait_for"):
                    # Condition.wait on the held condition's own lock is
                    # the sanctioned idiom — it RELEASES that lock.
                    held = self._drop_cond_self_wait(fn, ev, held)
                for a in held:
                    if blocking_allowed_under(a):
                        continue
                    key = (fn.key, a, label)
                    if key in reported:
                        continue
                    reported.add(key)
                    what = "collective" if ev in fn.collectives \
                        else "blocking call"
                    self._emit(
                        "lock-held-across-blocking", "error", fn.path,
                        ev.line,
                        f"{what} '{label}' while holding lock {a} "
                        f"(in {fn.key}); a peer or callback thread "
                        f"needing {a} deadlocks for the full wait — "
                        f"release the lock first or bound and justify "
                        f"the hold")
            for ev in fn.calls:
                if not ev.held:
                    continue
                held = self._held_keys(fn, ev.held)
                if not held:
                    continue
                for g, confg in self._call_targets.get(id(ev), []):
                    for label, (confb, site) in \
                            self.blocking_closure.get(g, {}).items():
                        conf = confg and confb
                        for a in held:
                            if blocking_allowed_under(a):
                                continue
                            key = (fn.key, a, g.rsplit(".", 1)[-1],
                                   label)
                            if key in reported:
                                continue
                            reported.add(key)
                            self._emit(
                                "lock-held-across-blocking",
                                "error" if conf else "warning",
                                fn.path, ev.line,
                                f"call to {g} while holding lock {a} "
                                f"(in {fn.key}) reaches '{label}' at "
                                f"{site[0]}:{site[1]}; the lock is held "
                                f"across that wait — release it first, "
                                f"or justify the external bound with a "
                                f"suppression")

    def _held_keys(self, fn: FuncRaw, held) -> list[str]:
        out = []
        for hs in held:
            k = self.resolve_lock(hs, fn.module, fn.cls, fn.name)
            if k:
                out.append(self.canonical(k))
        return out

    def _drop_cond_self_wait(self, fn: FuncRaw, ev, held: list[str]):
        # ev.line corresponds to a recorded acquire with via="wait";
        # find its receiver's canonical lock and drop it from held.
        for acq in fn.acquires:
            if acq.line == ev.line and acq.via == "wait":
                k = self.resolve_lock(acq.spine, fn.module, fn.cls,
                                      fn.name)
                if k:
                    c = self.canonical(k)
                    return [h for h in held if h != c]
        return held

    def _find_orphan_conditions(self) -> None:
        """HVD503: Condition with wait sites but no notify anywhere."""
        waits: dict[str, list] = {}
        notifies: set[str] = set()
        for fn in self.program.functions.values():
            for ev in fn.acquires:
                if ev.via not in ("wait", "notify"):
                    continue
                k = self.resolve_lock(ev.spine, fn.module, fn.cls,
                                      fn.name)
                if not k or self.locks[k].kind != "condition":
                    continue
                if ev.via == "wait":
                    waits.setdefault(k, []).append((fn, ev.line))
                else:
                    notifies.add(k)
        for k, sites in waits.items():
            if k in notifies:
                continue
            fn, line = sites[0]
            self._emit(
                "orphan-condition-wait", "error", fn.path, line,
                f"wait on condition {k} but no code path ever calls "
                f"notify/notify_all on it: the predicate is written by "
                f"no other thread, so the wait can only end by timeout "
                f"(or never) — add the notify at the state change, or "
                f"replace the condition with a timeout poll and justify")

    def analyze(self) -> "Analysis":
        self._register_locks()
        self._build_indexes()
        self._resolve_all_calls()
        self._fix_acquires()
        self._fix_blocking()
        self._fix_threads()
        self._build_edges()
        self._find_cycles()
        self._find_held_blocking()
        self._find_orphan_conditions()
        from .ownership import check_ownership
        check_ownership(self)
        from .san import check_state_frame_drift, check_wire_drift
        check_wire_drift(self)
        check_state_frame_drift(self)
        from ..hvdmc.conformance import check_spec_conformance
        check_spec_conformance(self)
        self.findings.sort(key=lambda f: (f.path, f.line, f.rule.id))
        return self

    # -- serialization -----------------------------------------------------
    def graph_json(self) -> dict:
        return {
            "locks": {k: {"site": v.site, "kind": v.kind,
                          "canonical": v.canonical}
                      for k, v in self.locks.items()},
            "edges": [{"src": a, "dst": b, "confident": e.confident,
                       "sites": [f"{p}:{ln}" for p, ln, _ in e.sites]}
                      for (a, b), e in sorted(self.edges.items())],
            "threads": dict(sorted(self.thread_roots.items())),
        }

    def site_to_lock(self) -> dict[str, str]:
        """creation-site "path:line" -> canonical lock key (the map the
        runtime witness diff uses)."""
        return {v.site: v.canonical for v in self.locks.values()}

    def edge_keys(self) -> set[tuple[str, str]]:
        return set(self.edges.keys())


def _tarjan(adj: dict[str, list[str]]) -> list[list[str]]:
    """Iterative Tarjan SCC over the adjacency dict (includes
    single-node SCCs; callers filter)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]
    nodes = set(adj)
    for vs in adj.values():
        nodes.update(vs)

    def strongconnect(root: str) -> None:
        work = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack.add(v)
            recurse = False
            succs = adj.get(v, [])
            for i in range(pi, len(succs)):
                w = succs[i]
                if w not in index:
                    work[-1] = (v, i + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                sccs.append(scc)
            work.pop()
            if work:
                u = work[-1][0]
                low[u] = min(low[u], low[v])

    for n in sorted(nodes):
        if n not in index:
            strongconnect(n)
    return sccs


def analyze_paths(paths) -> Analysis:
    program = Program()
    program.collect_paths(paths)
    return Analysis(program).analyze()
