"""hvdsan driver + runtime lock-order witness.

Static half (``analyze``/``main``): run the whole-program lock-graph
analysis (:mod:`.lockgraph`), the ownership manifest check
(:mod:`.ownership`) and the wire-schema drift check (HVD505, below)
over a tree, and render text/JSON/SARIF reports.  CLI::

    python -m horovod_tpu.analysis.hvdsan [paths...]
        [--format text|json|sarif] [--graph] [--witness dump.json ...]

Runtime half (the **witness**): under ``HOROVOD_SAN=1``
(:func:`maybe_enable`, called at ``horovod_tpu`` import before any
package lock exists) ``threading.Lock/RLock/Condition`` constructed
from package code are wrapped in lightweight recording proxies.  Each
wrapper knows its creation site (``horovod_tpu/...py:line`` — the same
key the static analysis assigns), every acquisition while other
package locks are held records ordered edges ``held-site →
new-site`` per thread (first observation also lands in the flight
recorder's ring), and :func:`dump_witness` (registered atexit) writes
the observed lock-order graph as rank-stamped JSON
(``HOROVOD_SAN_FILE``).

The CI contract (tests/test_multiprocess.py san battery): every edge
the 2/4-rank worlds *observe* must exist in the static graph —
otherwise the analyzer is unsound and the build fails; static cycles
never observed demote to warnings (``apply_witness``).
"""
from __future__ import annotations

import ast
import atexit
import json
import os
import sys
import threading
import time

from ..rules import RULES  # noqa: F401  (suppression parsing shares it)

# NOTE: .lockgraph (and through it ..lint) is imported lazily inside
# the functions that need it: this module loads at `horovod_tpu` import
# time to install the witness, and must not drag the static-analysis
# machinery (or pre-import analysis.lint under `python -m`) with it.

# ---------------------------------------------------------------------------
# HVD505 — wire-schema drift (common/message.py <-> common/wire.py)
# ---------------------------------------------------------------------------
# Fallback primitive vocabulary when the analyzed set doesn't include
# common/wire.py (single-fixture runs).
_DEFAULT_WIRE_PRIMS = frozenset({
    "uvarint", "svarint", "f64", "string", "blob", "bool_",
    "uvarint_list", "svarint_list", "string_list",
})
_ENC_METHODS = ("encode", "to_bytes")
_DEC_METHODS = ("decode", "from_bytes")

# Optional-field prefixes that MUST sit behind a negotiated feature-bit
# gate (`if features & FEATURE_X:`) on both codec sides — the
# compile-time half of the versioned wire handshake: an optional field
# encoded unconditionally breaks every peer that negotiated the bit
# away.  Mirrors common/wire.py OPTIONAL_FIELD_FEATURES (tests assert
# the two tables agree).
_OPTIONAL_WIRE_PREFIXES = ("fp_", "tm_", "trace_", "sp_")


def collect_wire_method(program, mod, cls, node) -> None:
    """Extract the ordered primitive-call sequence of one encode/decode
    method (called from the lockgraph collector's single AST walk)."""
    side = "enc" if node.name in _ENC_METHODS else "dec"
    tokens = _wire_tokens(node, side)
    # A wire codec writes/reads a field *sequence*; a lone primitive hit
    # (e.g. a compress kernel calling some to_bytes helper) is not one.
    if len(tokens) < 2:
        return
    program.wire_codecs.append({
        "module": mod.label, "cls": cls.name, "path": mod.path,
        "method": node.name, "line": node.lineno, "side": side,
        "tokens": tokens, "gated": _feature_gated_spans(node),
    })


def _feature_gated_spans(node) -> tuple:
    """Line spans of ``if`` bodies whose test consults the negotiated
    ``features`` word — primitive calls inside them are feature-gated
    (the HVD505 optional-field check)."""
    spans = []
    for sub in ast.walk(node):
        if not isinstance(sub, ast.If):
            continue
        gated = any(
            isinstance(t, (ast.Name, ast.Attribute)) and
            "feature" in (t.id if isinstance(t, ast.Name)
                          else t.attr).lower()
            for t in ast.walk(sub.test))
        if gated:
            spans.append((sub.lineno, sub.end_lineno or sub.lineno))
    return tuple(spans)


def note_wire_class(program, mod, cls_node) -> None:
    """Record Encoder/Decoder method vocabularies from a wire module."""
    names = {n.name for n in cls_node.body
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
             and not n.name.startswith("_")}
    program.wire_prims[cls_node.name] = names


def _wire_tokens(node, side: str) -> list:
    """[(prim|"nested", fieldname|None), ...] in wire order."""
    from .lockgraph import _spine
    # Loop-variable -> iterated self-attr (for r in self.requests).
    loopmap: dict[str, str] = {}
    for sub in ast.walk(node):
        if isinstance(sub, ast.For) and isinstance(sub.target, ast.Name):
            isp = _spine(sub.iter)
            if isp and isp[0] == "self" and len(isp) == 2:
                loopmap[sub.target.id] = isp[1]
    # Enclosing single-Name assign target per contained call.
    assign_of: dict[int, str] = {}
    kwarg_of: dict[int, str] = {}
    kwmap: dict[str, str] = {}      # local name -> ctor kwarg name
    for sub in ast.walk(node):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                isinstance(sub.targets[0], ast.Name):
            tname = sub.targets[0].id
            for c in ast.walk(sub.value):
                if isinstance(c, ast.Call):
                    assign_of[id(c)] = tname
        if isinstance(sub, ast.Call):
            fsp = _spine(sub.func)
            is_ctor = bool(fsp) and (fsp[-1] == "cls" or
                                     fsp[-1][:1].isupper())
            if is_ctor and sub.keywords:
                for kw in sub.keywords:
                    if kw.arg is None:
                        continue
                    if isinstance(kw.value, ast.Name):
                        kwmap[kw.value.id] = kw.arg
                    for c in ast.walk(kw.value):
                        if isinstance(c, ast.Call):
                            kwarg_of.setdefault(id(c), kw.arg)
    out = []
    for call in sorted(
            (c for c in ast.walk(node) if isinstance(c, ast.Call)
             and isinstance(c.func, ast.Attribute)),
            key=lambda c: (c.func.end_lineno or 0,
                           c.func.end_col_offset or 0)):
        # The receiver may itself be a chained Call
        # (enc.uvarint(a).string(b)); only the method name matters.
        name = call.func.attr
        recv = _spine(call.func.value)
        if name in ("encode", "decode"):
            # nested message: r.encode(enc) / Request.decode(dec)
            if side == "enc" and name == "encode" and recv:
                field = loopmap.get(recv[0])
                out.append(("nested", field, call.lineno))
            elif side == "dec" and name == "decode":
                field = kwarg_of.get(id(call))
                out.append(("nested", field, call.lineno))
            continue
        if name not in _DEFAULT_WIRE_PRIMS:
            continue
        field = None
        if side == "enc":
            for a in call.args:
                for s in ast.walk(a):
                    ssp = _spine(s) if isinstance(
                        s, (ast.Attribute, ast.Name)) else None
                    if ssp and ssp[0] == "self" and len(ssp) == 2:
                        field = ssp[1]
                        break
                if field:
                    break
            # len(self.x) prefixes are counts, not the field itself.
            if call.args and isinstance(call.args[0], ast.Call):
                inner = _spine(call.args[0].func)
                if inner and inner[-1] == "len":
                    field = None
        else:
            field = kwarg_of.get(id(call))
            if field is None:
                local = assign_of.get(id(call))
                if local is not None:
                    field = kwmap.get(local, local)
        out.append((name, field, call.lineno))
    return out


def collect_state_frame(program, mod, node) -> None:
    """Extract one side of the STATE_MAGIC frame codec
    (``pack_state_frame``/``unpack_state_frame`` module functions) for
    the statesync half of HVD505: header struct identity, header field
    order, and the magic constant each side keys on."""
    from .lockgraph import _spine
    side = "pack" if node.name.startswith("pack") else "unpack"
    hdr = None
    fields: list = []
    magics: set = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "MAGIC" in sub.id:
            magics.add(sub.id)
        if not isinstance(sub, ast.Call):
            continue
        sp = _spine(sub.func)
        if not sp or len(sp) < 2:
            continue
        if side == "pack" and sp[-1] == "pack":
            hdr = sp[-2]
            for a in sub.args:
                # len(...) and other computed args are positionally
                # uncomparable: record None so only named fields diff.
                if isinstance(a, ast.Name):
                    fields.append(a.id)
                elif isinstance(a, ast.Attribute):
                    fields.append(a.attr)
                else:
                    fields.append(None)
        elif side == "unpack" and sp[-1] in ("unpack", "unpack_from"):
            hdr = sp[-2]
    if side == "unpack":
        # Header field order = the tuple-assign targets of unpack_from.
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign) or \
                    not isinstance(sub.value, ast.Call):
                continue
            vsp = _spine(sub.value.func)
            if vsp and vsp[-1] in ("unpack", "unpack_from"):
                tgt = sub.targets[0]
                if isinstance(tgt, ast.Tuple):
                    fields = [t.id if isinstance(t, ast.Name) else None
                              for t in tgt.elts]
                elif isinstance(tgt, ast.Name):
                    fields = [tgt.id]
    program.state_frames.append({
        "module": mod.label, "path": mod.path, "line": node.lineno,
        "side": side, "hdr": hdr, "fields": tuple(fields),
        "magics": frozenset(magics)})


def check_state_frame_drift(analysis: Analysis) -> None:
    """HVD505 over the statesync STATE_MAGIC frame codec: the pack and
    unpack halves must agree on the header struct format, the header
    field order, and the magic prefix — and the frame-kind constants
    (``STATE_*``) must carry unique wire values (two verbs sharing a
    value dispatch each other's frames)."""
    program = analysis.program
    by_mod: dict = {}
    for rec in program.state_frames:
        by_mod.setdefault(rec["module"], {})[rec["side"]] = rec
    for modlabel, sides in sorted(by_mod.items()):
        mod = program.modules.get(modlabel)
        pack, unpack = sides.get("pack"), sides.get("unpack")
        if pack is None or unpack is None:
            rec = pack or unpack
            other = "unpack_state_frame" if unpack is None \
                else "pack_state_frame"
            analysis._emit(
                "wire-schema-drift", "error", rec["path"], rec["line"],
                f"{rec['side']}_state_frame has no matching {other} in "
                f"the same module: a one-sided frame codec cannot "
                f"round-trip")
            continue
        fmts = mod.struct_fmts if mod else {}
        pf = fmts.get(pack["hdr"], (None, 0))[0]
        uf = fmts.get(unpack["hdr"], (None, 0))[0]
        if pf is not None and uf is not None and pf != uf:
            analysis._emit(
                "wire-schema-drift", "error", unpack["path"],
                unpack["line"],
                f"state-frame header drift: pack_state_frame packs "
                f"{pack['hdr']}({pf!r}) but unpack_state_frame reads "
                f"{unpack['hdr']}({uf!r}) — every frame decodes "
                f"garbage on the peer")
        if pack["magics"] and unpack["magics"] and \
                not (pack["magics"] & unpack["magics"]):
            analysis._emit(
                "wire-schema-drift", "error", unpack["path"],
                unpack["line"],
                f"state-frame magic drift: pack prefixes with "
                f"{sorted(pack['magics'])} but unpack checks "
                f"{sorted(unpack['magics'])}")
        n = min(len(pack["fields"]), len(unpack["fields"]))
        for i in range(n):
            a, b = pack["fields"][i], unpack["fields"][i]
            if a and b and a != b and \
                    {a, b} & (set(pack["fields"])
                              & set(unpack["fields"])):
                analysis._emit(
                    "wire-schema-drift", "error", unpack["path"],
                    unpack["line"],
                    f"state-frame header field-order drift at "
                    f"position #{i + 1}: pack writes '{a}' where "
                    f"unpack assigns '{b}' — same width, swapped "
                    f"fields decode silently wrong")
                break
    # Frame-kind verbs must have unique wire values per module.
    for modlabel, mod in sorted(program.modules.items()):
        verbs = {k: v for k, v in mod.int_consts.items()
                 if k.startswith("STATE_")}
        byval: dict = {}
        for k, (val, line) in sorted(verbs.items()):
            prior = byval.get(val)
            if prior is not None:
                analysis._emit(
                    "wire-schema-drift", "error", mod.path, line,
                    f"frame kinds {prior} and {k} share wire value "
                    f"{val}: one verb's frames dispatch as the "
                    f"other's")
            else:
                byval[val] = k


def check_wire_drift(analysis: Analysis) -> None:
    """HVD505: encode/decode primitive sequences must agree per class,
    and only use primitives both wire codec classes define."""
    program = analysis.program
    by_cls: dict = {}
    for rec in program.wire_codecs:
        by_cls.setdefault((rec["module"], rec["cls"]), {})[rec["side"]] \
            = rec
    enc_prims = program.wire_prims.get("Encoder")
    dec_prims = program.wire_prims.get("Decoder")
    known = (enc_prims & dec_prims) if (enc_prims and dec_prims) \
        else _DEFAULT_WIRE_PRIMS
    for (modlabel, cls), sides in sorted(by_cls.items()):
        enc, dec = sides.get("enc"), sides.get("dec")
        if enc is None or dec is None:
            rec = enc or dec
            other = "decode/from_bytes" if dec is None \
                else "encode/to_bytes"
            analysis._emit(
                "wire-schema-drift", "error", rec["path"], rec["line"],
                f"{cls}.{rec['method']} has no matching {other} in the "
                f"same class: a one-sided wire schema cannot round-trip "
                f"— add the counterpart or drop the codec method")
            continue
        et, dt = enc["tokens"], dec["tokens"]
        for rec, toks in ((enc, et), (dec, dt)):
            for prim, _f, line in toks:
                if prim != "nested" and prim not in known:
                    analysis._emit(
                        "wire-schema-drift", "error", rec["path"], line,
                        f"{cls}.{rec['method']} uses wire primitive "
                        f"'{prim}' not defined by both Encoder and "
                        f"Decoder in common/wire.py — the peer cannot "
                        f"decode what this side writes")
            # Optional-field feature-bit gate (the compile-time half of
            # the versioned HELLO handshake): every
            # fp_*/tm_*/trace_*/sp_* field must encode/decode inside
            # an `if features & ...:`
            # arm, or a peer that negotiated the bit away desyncs.
            for prim, field, line in toks:
                if not field or \
                        not field.startswith(_OPTIONAL_WIRE_PREFIXES):
                    continue
                if not any(s <= line <= e for s, e in rec["gated"]):
                    analysis._emit(
                        "wire-schema-drift", "error", rec["path"], line,
                        f"{cls}.{rec['method']} carries optional wire "
                        f"field '{field}' outside a feature-bit gate "
                        f"(`if features & FEATURE_...:`) — a peer that "
                        f"negotiated the bit away cannot skip it; gate "
                        f"the field on its OPTIONAL_FIELD_FEATURES bit "
                        f"(common/wire.py)")
        n = min(len(et), len(dt))
        for i in range(n):
            ep, ef, eline = et[i]
            dp, df, dline = dt[i]
            if ep != dp:
                analysis._emit(
                    "wire-schema-drift", "error", dec["path"], dline,
                    f"{cls} wire drift at field #{i + 1}: "
                    f"{enc['method']} writes '{ep}'"
                    f"{f' ({ef})' if ef else ''} but {dec['method']} "
                    f"reads '{dp}'{f' ({df})' if df else ''} — every "
                    f"frame after this field decodes garbage on the "
                    f"peer")
                break
            if ef and df and ef != df:
                analysis._emit(
                    "wire-schema-drift", "error", dec["path"], dline,
                    f"{cls} wire field-order drift at position "
                    f"#{i + 1}: {enc['method']} writes field '{ef}' "
                    f"where {dec['method']} assigns '{df}' — same "
                    f"primitive, swapped fields decode silently wrong")
                break
        else:
            if len(et) != len(dt):
                longer, shorter = (enc, dec) if len(et) > len(dt) \
                    else (dec, enc)
                lt = et if len(et) > len(dt) else dt
                prim, f, line = lt[n]
                analysis._emit(
                    "wire-schema-drift", "error", longer["path"], line,
                    f"{cls} wire drift: {longer['method']} has "
                    f"{abs(len(et) - len(dt))} trailing field(s) "
                    f"starting with '{prim}'{f' ({f})' if f else ''} "
                    f"that {shorter['method']} never "
                    f"{'reads' if longer is enc else 'writes'} — "
                    f"fp_*/tm_*/trace_*/sp_*-style field growth must "
                    f"land "
                    f"on both sides in the same change")


# ---------------------------------------------------------------------------
# Runtime witness
# ---------------------------------------------------------------------------
_orig_lock = threading.Lock
_orig_rlock = threading.RLock
_orig_condition = threading.Condition
_enabled = False
_witness: "Witness | None" = None


class Witness:
    """Process-wide observed lock-order graph."""

    def __init__(self) -> None:
        self.edges: dict = {}        # (src, dst) -> [count, set(threads)]
        self.locks: dict = {}        # site -> kind
        self._tls = threading.local()
        self._reg = _orig_lock()

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def note_acquire(self, proxy) -> None:
        stack = self._stack()
        if stack:
            tname = threading.current_thread().name
            for held in stack:
                if held.site != proxy.site:
                    self._note_edge(held.site, proxy.site, tname)
        stack.append(proxy)

    def note_release(self, proxy, all_levels: bool = False) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is proxy:
                del stack[i]
                if not all_levels:
                    return

    def _note_edge(self, src: str, dst: str, thread: str) -> None:
        with self._reg:
            e = self.edges.get((src, dst))
            fresh = e is None
            if fresh:
                e = self.edges[(src, dst)] = [0, set()]
            e[0] += 1
            e[1].add(thread)
        if fresh:
            self._flight_record(src, dst, thread)

    @staticmethod
    def _flight_record(src: str, dst: str, thread: str) -> None:
        """First observation of an edge lands in the flight-recorder
        ring (direct global read — recorder() would take a lock)."""
        try:
            from ...telemetry import flight
            rec = flight._recorder
            if rec is not None and rec.enabled:
                rec.record("lock-order", f"{src} -> {dst}",
                           detail=f"thread={thread}")
        except Exception:  # noqa: BLE001 - witness must never break init
            pass

    def snapshot(self) -> dict:
        with self._reg:
            edges = [{"src": s, "dst": d, "count": c,
                      "threads": sorted(ts)}
                     for (s, d), (c, ts) in sorted(self.edges.items())]
        return {"rank": int(os.environ.get("HOROVOD_RANK", "0") or 0),
                "pid": os.getpid(),
                "monotonic": time.monotonic(),
                "locks": dict(sorted(self.locks.items())),
                "edges": edges}

    def reset(self) -> None:
        with self._reg:
            self.edges.clear()
            self.locks.clear()


class _SanLock:
    """Recording proxy over a real Lock/RLock."""

    def __init__(self, inner, site: str, witness: Witness) -> None:
        self._inner = inner
        self.site = site
        self._w = witness

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._w.note_acquire(self)
        return ok

    def release(self) -> None:
        self._w.note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<SanLock {self.site} over {self._inner!r}>"

    # Condition integration: delegate the RLock save/restore protocol so
    # Condition.wait releases every recursion level (and our per-thread
    # stack tracks it).
    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):
        self._w.note_release(self, all_levels=True)
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return inner._release_save()
        inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        self._w.note_acquire(self)


def _creation_site() -> str | None:
    """Creation site of the package frame constructing a lock, or None
    for stdlib/user code (those get raw primitives, zero overhead)."""
    try:
        frame = sys._getframe(2)
    except ValueError:
        return None
    fname = (frame.f_code.co_filename or "").replace(os.sep, "/")
    idx = fname.find("horovod_tpu/")
    if idx < 0:
        return None
    rel = fname[idx:]
    if rel.endswith("analysis/hvdsan/san.py"):
        return None
    return f"{rel}:{frame.f_lineno}"


def _san_lock_factory():
    site = _creation_site()
    inner = _orig_lock()
    if site is None or _witness is None:
        return inner
    _witness.locks.setdefault(site, "lock")
    return _SanLock(inner, site, _witness)


def _san_rlock_factory():
    site = _creation_site()
    inner = _orig_rlock()
    if site is None or _witness is None:
        return inner
    _witness.locks.setdefault(site, "rlock")
    return _SanLock(inner, site, _witness)


def _san_condition_factory(lock=None):
    site = _creation_site()
    if lock is None and site is not None and _witness is not None:
        _witness.locks.setdefault(site, "condition")
        lock = _SanLock(_orig_rlock(), site, _witness)
    return _orig_condition(lock) if lock is not None \
        else _orig_condition()


def enabled() -> bool:
    return _enabled


def witness() -> "Witness | None":
    return _witness


def enable() -> Witness:
    """Install the recording wrappers (idempotent).  Must run before
    the package modules that create locks are imported —
    ``horovod_tpu/__init__`` calls :func:`maybe_enable` first thing."""
    global _enabled, _witness
    if _enabled:
        return _witness
    _witness = Witness()
    threading.Lock = _san_lock_factory
    threading.RLock = _san_rlock_factory
    threading.Condition = _san_condition_factory
    _enabled = True
    atexit.register(dump_witness)
    return _witness


def disable() -> None:
    """Restore the original factories (tests); existing wrappers keep
    working, new locks are raw again."""
    global _enabled
    threading.Lock = _orig_lock
    threading.RLock = _orig_rlock
    threading.Condition = _orig_condition
    _enabled = False


def maybe_enable() -> bool:
    if os.environ.get("HOROVOD_SAN", "").strip().lower() in (
            "1", "true", "on", "yes"):
        enable()
        return True
    return False


def _rank_path(path: str, rank: int) -> str:
    if "{rank}" in path:
        return path.format(rank=rank)
    if rank == 0:
        return path
    root, dot, ext = path.rpartition(".")
    return f"{root}.r{rank}.{ext}" if dot else f"{path}.r{rank}"


def dump_witness(path: str | None = None) -> str | None:
    """Write the observed lock-order graph as rank-stamped JSON;
    returns the path (None when the witness is off or unwritable)."""
    w = _witness
    if w is None:
        return None
    if not w.locks and not w.edges:
        return None        # nothing observed (witness reset/unused)
    payload = w.snapshot()
    path = path or os.environ.get("HOROVOD_SAN_FILE",
                                  "hvdsan_witness.json")
    path = _rank_path(path, payload["rank"])
    try:
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)
    except OSError:
        return None
    return path


# ---------------------------------------------------------------------------
# Witness <-> static diff
# ---------------------------------------------------------------------------
def witness_diff(analysis: Analysis, payloads) -> list[str]:
    """Soundness check: every observed edge must exist in the static
    graph.  Returns human-readable problems (empty = sound)."""
    site_map = analysis.site_to_lock()
    static_edges = analysis.edge_keys()
    problems: list[str] = []
    for payload in payloads:
        rank = payload.get("rank", "?")
        for e in payload.get("edges", []):
            src, dst = e["src"], e["dst"]
            ks, kd = site_map.get(src), site_map.get(dst)
            if ks is None or kd is None:
                missing = src if ks is None else dst
                problems.append(
                    f"rank {rank}: observed lock at {missing} has no "
                    f"static identity — the analyzer missed a "
                    f"creation site")
                continue
            if ks == kd:
                continue
            if (ks, kd) not in static_edges:
                problems.append(
                    f"rank {rank}: observed order {ks} -> {kd} "
                    f"({src} -> {dst}, threads "
                    f"{','.join(e.get('threads', []))}) is missing "
                    f"from the static graph — the analyzer is unsound "
                    f"on this path")
    return sorted(set(problems))


def apply_witness(analysis: Analysis, payloads) -> None:
    """Demote HVD501 cycle findings whose edges were never observed at
    runtime to warnings (the fixture documenting why lives with the
    battery; ISSUE 8 tentpole contract)."""
    observed: set = set()
    site_map = analysis.site_to_lock()
    for payload in payloads:
        for e in payload.get("edges", []):
            ks, kd = site_map.get(e["src"]), site_map.get(e["dst"])
            if ks and kd:
                observed.add((ks, kd))
    for f in analysis.findings:
        if f.rule.id != "HVD501" or f.severity != "error":
            continue
        edge_pairs = {
            (e.src, e.dst) for e in analysis.edges.values()
            if (e.sites[0][0], e.sites[0][1]) in set(f.sites)}
        if edge_pairs and not (edge_pairs & observed):
            f.severity = "warning"
            f.message += (" [demoted: no edge of this cycle was "
                          "observed by the runtime witness]")


# ---------------------------------------------------------------------------
# Report driver / CLI
# ---------------------------------------------------------------------------
def analyze(paths) -> "Analysis":
    from . import lockgraph
    return lockgraph.analyze_paths(paths)


def sarif_payload(records) -> dict:
    """SARIF 2.1.0 from hvdlint Violations and/or hvdsan Findings."""
    rules_seen: dict[str, dict] = {}
    results = []
    for r in records:
        rule = r.rule
        rules_seen.setdefault(rule.id, {
            "id": rule.id,
            "name": rule.slug,
            "shortDescription": {"text": rule.summary}})
        level = "warning" if getattr(r, "severity", "error") \
            == "warning" else "error"
        results.append({
            "ruleId": rule.id,
            "level": level,
            "message": {"text": r.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": r.path},
                    "region": {"startLine": r.line,
                               "startColumn": getattr(r, "col", 1)},
                }}],
        })
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "hvdlint",
                "informationUri":
                    "https://example.invalid/horovod_tpu/docs/analysis",
                "rules": list(rules_seen.values())}},
            "results": results,
        }],
    }


def report_text(analysis: Analysis, graph: bool = False) -> str:
    lines: list[str] = []
    errors = [f for f in analysis.findings if f.severity == "error"]
    warnings = [f for f in analysis.findings if f.severity == "warning"]
    lines.append(
        f"hvdsan: {len(analysis.locks)} lock(s), "
        f"{len(analysis.edges)} order edge(s), "
        f"{len(analysis.thread_roots)} thread root(s)")
    if graph:
        for key, info in sorted(analysis.locks.items()):
            alias = "" if info.canonical == key \
                else f" (aliases {info.canonical})"
            lines.append(f"  lock {key} [{info.kind}] @ {info.site}"
                         f"{alias}")
        for (a, b), e in sorted(analysis.edges.items()):
            conf = "" if e.confident else " (index-resolved)"
            p, ln, via = e.sites[0]
            lines.append(f"  edge {a} -> {b}{conf} @ {p}:{ln} [{via}]")
        for root, name in sorted(analysis.thread_roots.items()):
            lines.append(f"  thread {name}: {root}")
    from .ownership import LOCK_HOLD_ALLOWED
    for key, why in sorted(LOCK_HOLD_ALLOWED.items()):
        if key in analysis.locks:
            lines.append(f"  allowed-hold {key} -- {why}")
    for f in analysis.findings:
        lines.append(f.text())
    lines.append(f"hvdsan: {len(errors)} error(s), "
                 f"{len(warnings)} warning(s)")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis.hvdsan",
        description="Whole-program concurrency verification: static "
                    "lock-order/deadlock analysis with a runtime "
                    "witness (see docs/analysis.md).")
    parser.add_argument("paths", nargs="*", default=["horovod_tpu"])
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--graph", action="store_true",
                        help="include the full lock/edge/thread tables")
    parser.add_argument("--witness", nargs="*", default=[],
                        help="runtime witness dumps to diff against "
                             "the static graph")
    args = parser.parse_args(argv)

    t0 = time.monotonic()
    analysis = analyze(args.paths)
    payloads = []
    for p in args.witness:
        with open(p) as f:
            payloads.append(json.load(f))
    unsound = witness_diff(analysis, payloads) if payloads else []
    if payloads:
        apply_witness(analysis, payloads)
    wall_ms = (time.monotonic() - t0) * 1e3

    errors = [f for f in analysis.findings if f.severity == "error"]
    if args.format == "json":
        print(json.dumps({
            "findings": [f.json() for f in analysis.findings],
            "graph": analysis.graph_json(),
            "unsound": unsound,
            "wall_ms": round(wall_ms, 3),
        }, indent=1))
    elif args.format == "sarif":
        print(json.dumps(sarif_payload(analysis.findings), indent=1))
    else:
        print(report_text(analysis, graph=args.graph))
        for p in unsound:
            print(f"hvdsan: UNSOUND: {p}")
        print(f"hvdsan: wall {wall_ms:.1f} ms", file=sys.stderr)
    return 1 if (errors or unsound) else 0
