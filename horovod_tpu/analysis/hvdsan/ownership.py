"""Declarative thread-ownership manifest for shared runtime state.

Before this module, hvdlint's HVD401 carried a hard-coded list of
"owner" module basenames; nothing named the *thread* that owns each
piece of shared state, so a write racing the owning thread from, say,
the heartbeat monitor looked identical to a legitimate wiring write at
init.  The manifest below is the single source of truth for both:

- **hvdlint HVD401** reads each domain's ``writer_modules`` (replacing
  the old hard-coded set): writes to a domain's attributes outside its
  writer modules are flagged per-file, exactly as before but
  declaratively.
- **hvdsan HVD504** (``cross-thread-write``) adds the interprocedural
  half: a write to a domain's attributes from a function reachable from
  a *named thread root* other than the domain's ``owner_thread`` is a
  cross-thread write racing the owner — flagged even inside a writer
  module.

``LOCK_HOLD_ALLOWED`` is the manifest's second leg: locks that are
*documented* to be held across blocking calls, each with the external
ordering guarantee that makes the hold safe.  hvdsan's HVD502 consults
it so the justification lives here, reviewable in one place, instead of
scattered across dozens of inline suppressions.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class StateDomain:
    name: str
    # Thread that owns mutation of this state at steady state (thread
    # names as passed to threading.Thread(name=...); "main" = user/init
    # threads, which the per-module allowlist governs instead).
    owner_thread: str
    # Attribute names that mark the state anywhere on a write target's
    # spine (matching hvdlint HVD401 semantics: the final assigned field
    # is excluded — `x.controller = c` wires up, `x.controller.f = v`
    # mutates internals).
    attrs: frozenset
    # Module path suffixes allowed to write (init wiring + the owners).
    writer_modules: frozenset
    why: str = ""


MANIFEST: tuple[StateDomain, ...] = (
    StateDomain(
        name="controller",
        owner_thread="hvd-background",
        attrs=frozenset({"controller", "_controller"}),
        writer_modules=frozenset({"core.py", "common/controller.py",
                                  "common/parameter_manager.py"}),
        why="the background loop drives the negotiation protocol; all "
            "controller state mutates on its cycle"),
    StateDomain(
        name="tensor-queue",
        owner_thread="hvd-background",
        attrs=frozenset({"tensor_queue", "_tensor_queue"}),
        writer_modules=frozenset({"core.py", "common/tensor_queue.py",
                                  "common/controller.py"}),
        why="single-consumer table: the background thread pops; user "
            "threads only enqueue through add_to_tensor_queue"),
    StateDomain(
        name="global-state",
        owner_thread="main",
        attrs=frozenset({"_global"}),
        writer_modules=frozenset({"core.py"}),
        why="process-wide runtime wiring; mutated only under "
            "core._init_lock on init/shutdown"),
    StateDomain(
        name="timeline",
        owner_thread="hvd-timeline",
        attrs=frozenset({"timeline", "_timeline"}),
        writer_modules=frozenset({"core.py", "common/timeline.py"}),
        why="the writer thread owns the file; recording state mutates "
            "under the timeline's own lock"),
    StateDomain(
        name="telemetry",
        owner_thread="main",
        attrs=frozenset({"telemetry", "_registry"}),
        writer_modules=frozenset({"core.py", "telemetry/__init__.py",
                                  "telemetry/registry.py"}),
        why="registry construction happens at init; metric updates go "
            "through per-metric locks, never by field assignment"),
    StateDomain(
        name="flight",
        owner_thread="main",
        attrs=frozenset({"flight", "_recorder"}),
        writer_modules=frozenset({"core.py", "telemetry/flight.py"}),
        why="the recorder ring is GIL-atomic append-only; the recorder "
            "*reference* swaps only at configure time"),
)


def owner_module_suffixes() -> frozenset:
    """Union of every domain's writer modules — hvdlint HVD401's
    replacement for its old hard-coded basename list."""
    out: set = set()
    for d in MANIFEST:
        out |= d.writer_modules
    return frozenset(out)


def domain_for_write(spine) -> StateDomain | None:
    """Domain owning a write-target spine, or None.  HVD401 semantics:
    domain attrs anywhere on the spine EXCEPT the final assigned field,
    plus root names (``_global.x = ...``)."""
    if len(spine) < 2:
        return None
    marks = set(spine[:-1])
    for d in MANIFEST:
        if marks & d.attrs:
            return d
    return None


def module_allowed(path: str, domain: StateDomain) -> bool:
    norm = path.replace("\\", "/")
    return any(norm.endswith(sfx) for sfx in domain.writer_modules)


# ---------------------------------------------------------------------------
# Documented lock-hold allowances (HVD502 manifest suppressions)
# ---------------------------------------------------------------------------
# canonical lock key -> the external ordering guarantee that bounds the
# hold.  Each entry is a *reviewed* exception: hvdsan reports nothing
# for these locks being held across blocking calls, and the report mode
# lists them so the justification stays visible.
LOCK_HOLD_ALLOWED: dict[str, str] = {
    "core._init_lock":
        "one-shot init guard taken only by user threads; the formation "
        "waits under it are themselves timeout-bounded (rendezvous/"
        "connect timeouts), the background loop never takes it, and "
        "shutdown's potentially-wedging teardown (channel-close joins, "
        "dump file I/O) runs OUTSIDE the lock since the HVD502 pass "
        "that motivated this manifest",
    "parallel.multihost._lock":
        "orders init/shutdown of the JAX world on user threads only; "
        "the init-time barrier under it carries its own timeout "
        "(the HVD301 suppression in multihost.py documents the same "
        "guarantee)",
    "native._lock":
        "one-shot native-library build/load guard on the first caller "
        "thread; the compile it covers is finite and no hot path "
        "takes the lock",
    "resilience.context._lock":
        "configure/shutdown-time guard for the process ResilienceState "
        "swap; heartbeat start/stop joins under it are bounded by the "
        "monitor poll interval",
    "resilience.chaos._lock":
        "configure-time guard for the chaos-engine swap; never taken "
        "on the dispatch path",
    "elastic.driver.ElasticDriver._lock":
        "the round condition's own lock: waits on _round_cond release "
        "it (condition idiom), and discovery-thread RPC fan-out under "
        "it is bounded by the per-client RPC timeout",
    "elastic.rpc.RpcClient._lock":
        "BY DESIGN held across one send+recv pair: it serializes whole "
        "request/response exchanges on the shared persistent socket so "
        "frames from concurrent callers never interleave; no other "
        "lock ever nests inside it, and a broken connection raises out",
    "elastic.worker.WorkerNotificationManager._lock":
        "one-shot notification-service registration guard; the "
        "register_worker RPC under it happens once at worker start, "
        "bounded by the RPC connect timeout, before any listener can "
        "contend",
    "runner.controlplane.ControlPlane._lock":
        "the election critical section BY DESIGN: promotion/demotion "
        "re-reads the durable WAL and appends the leader record under "
        "it so role flips are serialized against the write fence; the "
        "file I/O is local and bounded (no network inside the lock — "
        "the urlopen the index fallback attributes here is the tail "
        "thread's, which never takes this lock)",
    "runner.network.RendezvousServer._httpd.kv_lock":
        "KV commit ordering: the WAL enqueue (non-blocking put on the "
        "group-commit lane) and the in-memory apply happen under one "
        "hold so log order equals apply order; the fsync wait happens "
        "on the commit event AFTER release, and the long-poll "
        "Condition wait on kv_cond releases the lock by construction "
        "(condition idiom)",
}


def blocking_allowed_under(lock_key: str) -> bool:
    return lock_key in LOCK_HOLD_ALLOWED


# ---------------------------------------------------------------------------
# Named thread roots the static Thread(target=) scan cannot see
# ---------------------------------------------------------------------------
# thread name -> (function key of the thread body, why it exists).
# Two shapes land here: Thread SUBCLASSES (run() overrides — no
# target= keyword to resolve) and threading.Timer callbacks whose
# receiver type the index fallback cannot bind.  HVD504's
# cross-thread-write reachability seeds from these exactly like the
# detected Thread(target=, name=) roots, so writes reachable from the
# statesync watcher, the autoscale controller, or the preempt backstop
# timer are checked against the ownership manifest.
THREAD_ROOTS: dict[str, tuple[str, str]] = {
    "hvd-statesync-watch": (
        "statesync.service.StateSyncService._watch_loop",
        "KV watcher polling join/ready records between boundaries"),
    "hvd-autoscale": (
        "statesync.autoscale.AutoscaleController.run",
        "rank-0 Thread subclass driving the elastic target size"),
    "hvd-preempt-backstop": (
        "statesync.service.StateSyncService._grace_expired",
        "SIGTERM-grace Timer: stamps bye| and exits 143 when no step "
        "boundary arrives inside the grace window"),
    # hvdlife harvest (ISSUE 13): Thread SUBCLASSES whose run() the
    # static Thread(target=) scan cannot see — registered here so
    # hvdsan and hvdlife share ONE root manifest (the two passes'
    # thread universes are asserted equal in tests/test_hvdlife.py).
    "hvd-statesync-donor-*": (
        "statesync.stream.DonorServer.run",
        "one incumbent's donor half of a join event: serves the frozen "
        "snapshot over the dedicated sync mesh until BYE or the round "
        "deadline; reaped by StateSyncService._reap_donors at the next "
        "boundary/close"),
    # Rendezvous control plane (ISSUE 15): replica-id-suffixed names
    # the static Thread(target=, name=) scan cannot bind (f-strings).
    "hvd-rdzv-wal-*": (
        "runner.controlplane.WalWriter._run",
        "group-commit fsync lane of the rendezvous WAL: drains queued "
        "records, one fsync per batch, sets commit events; poisoned + "
        "joined by WalWriter.close (reachable from "
        "RendezvousServer.stop)"),
    "hvd-rdzv-tail-*": (
        "runner.controlplane.Replicator._run",
        "standby log-tail replicator: long-polls the primary's "
        "/.ctl/wal and mirrors records; stopped + joined by "
        "Replicator.close"),
    "hvd-rdzv-lease-*": (
        "runner.controlplane.ControlPlane._lease_loop",
        "lease monitor: renews the leader lease (primary) or watches "
        "for lapse and runs the election (standby); stopped + joined "
        "by ControlPlane.close"),
    # fleetsim harness (ISSUE 16): vid-suffixed virtual-rank bodies the
    # static Thread(target=, name=) scan cannot bind (f-string names).
    "hvd-fleet-vrank-*": (
        "fleetsim.vrank.VirtualRank._run",
        "one virtual rank's protocol loop: real heartbeat monitor + "
        "chaos matching + loopback boundary exchange per step; joined "
        "by FleetSim.run against the episode deadline (abort wakes "
        "stragglers via LoopbackFabric.abort)"),
    "hvd-fleet-ctlwatch": (
        "fleetsim.harness._CtlRoleProber._run",
        "episode-long sampler of every rendezvous replica's /.ctl/role "
        "(the console's failover timeline); stopped + joined by "
        "_CtlRoleProber.close from FleetSim.close"),
    # Fleet controller (ISSUE 20): Thread subclasses whose run() the
    # static Thread(target=) scan cannot see.
    "hvd-fleet-controller": (
        "fleet.controller.FleetController.run",
        "rank-0 arbitration loop: polls both worlds' load gauges, "
        "feeds the rebalancing policy, journals + directs migrations; "
        "stopped + joined by FleetController.stop"),
    "hvd-fleet-publisher": (
        "fleet.deploy.WeightPublisher.run",
        "trainer-side snapshot committer: digests, shards and commits "
        "published param images to the coordinator KV off the step "
        "critical path; stopped + joined by WeightPublisher.close"),
    "hvd-fleet-puller": (
        "fleet.deploy.WeightPuller.run",
        "serving-side snapshot fetcher: polls the published head, "
        "digest-verifies and stages new versions for the plan-boundary "
        "swap; stopped + joined by WeightPuller.close (reachable from "
        "ReplicaExecutor.close)"),
    "hvd-chaos-cont": (
        "resilience.chaos._sigcont",
        "coordpause resume Timer: delivers SIGCONT to the paused "
        "rendezvous primary after the configured pause; fire-and-"
        "forget by design (the process under test may outlive the "
        "engine)"),
}


# ---------------------------------------------------------------------------
# HVD504 check (called from lockgraph.Analysis.analyze)
# ---------------------------------------------------------------------------
def check_ownership(analysis) -> None:
    """Cross-thread writes: a write to a manifest domain's state from a
    function reachable from a named thread root other than the domain's
    owner thread (module allowlist exempts the owners themselves)."""
    reported = set()
    for fn in analysis.program.functions.values():
        threads = analysis.thread_reach.get(fn.key, set())
        if not threads:
            continue        # only user/main threads reach it: HVD401's job
        for ev in fn.writes:
            domain = domain_for_write(ev.spine)
            if domain is None:
                continue
            if module_allowed(fn.path, domain):
                continue
            foreign = sorted(
                t for t in threads
                if t != domain.owner_thread)
            if not foreign:
                continue
            key = (fn.key, ev.line, domain.name)
            if key in reported:
                continue
            reported.add(key)
            analysis._emit(
                "cross-thread-write", "error", fn.path, ev.line,
                f"write to {domain.name} state "
                f"'{'.'.join(ev.spine)}' from {fn.key}, reachable from "
                f"thread(s) {', '.join(foreign)} — owner thread is "
                f"'{domain.owner_thread}' ({domain.why}); route the "
                f"change through the owner (controller protocol / "
                f"owning module API) or extend the manifest with the "
                f"guarantee")
