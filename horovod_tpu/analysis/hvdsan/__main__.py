"""``python -m horovod_tpu.analysis.hvdsan`` — standalone report mode."""
import sys

from .san import main

if __name__ == "__main__":
    sys.exit(main())
