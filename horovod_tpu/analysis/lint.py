"""hvdlint — static analyzer for the symmetric-collective contract.

CLI::

    python -m horovod_tpu.analysis.lint [paths...]
                                        [--format text|json|sarif]
                                        [--select RULES] [--ignore RULES]
                                        [--changed-only] [--san]
                                        [--flow] [--life] [--shard]
                                        [--knobs]

``--changed-only`` lints only files git reports as modified/untracked
(sub-second gate as the rule count grows; cross-file rules see only the
changed set).  ``--san`` additionally runs the hvdsan whole-program
concurrency analysis (HVD501-505, analysis/hvdsan/) over the SAME parse
of each file — one AST per file serves both rule families.  ``--flow``
does the same for the hvdflow interprocedural rank-divergence dataflow
analysis (HVD601-604, analysis/hvdflow/), ``--life`` for the hvdlife
whole-program resource-lifecycle analysis (HVD701-705,
analysis/hvdlife/), ``--shard`` for the hvdshard sharding-spec
analysis (HVD801-804, analysis/hvdshard/ — HVD803 rides the hvdflow
spec-annotated streams, so --shard builds the flow program too).
``--knobs`` prints the
generated typed-knob registry table (docs/configuration.md) and exits.
``--sarif`` emits SARIF 2.1.0 so findings annotate PRs.

Walks a Python tree and flags call patterns that break the invariant the
whole coordination protocol rests on — every rank submits the same
collectives in the same order (SURVEY §5.2):

- ``HVD101 rank-gated-collective``: a collective/barrier call under an
  ``if``/``while``/ternary/boolean-guard whose condition depends on
  ``rank``/``local_rank``/``cross_rank``/``is_coordinator``/... — only a
  subset of ranks submits it and the peers hang (or, with
  ``HOROVOD_FINGERPRINT`` on, get a structured error at runtime).
- ``HVD102 rank-gated-early-return``: a collective reachable after a
  rank-dependent early ``return``/``raise`` in the same function.
- ``HVD201/HVD202`` barrier-tag discipline for ``kv_barrier``:
  duplicated tag literals across call sites, and tags that are not
  string literals (so cannot be proven rank-invariant).
- ``HVD301 collective-under-lock``: a collective invoked while holding a
  lock — if the background loop or a completion callback takes the same
  lock, the world deadlocks.
- ``HVD401 shared-state-write``: writes to controller/tensor-queue/
  global-state fields outside their owning modules (single-writer
  discipline for state the background thread owns).
- ``HVD1001 thread-spawn-in-backend``: ``threading.Thread`` constructed
  inside a ``backend/`` module — data-plane hot paths must ride the
  transport's persistent per-peer sender lanes, not per-op threads (the
  2(N-1)-spawns-per-ring regression the pipelined plane removed).
- ``HVD1002 blocking-io-in-hot-path``: blocking I/O
  (``open``/``print``/``sendall``/``sendmsg``) inside a dispatch/backend
  hot-path function (op methods, ring helpers, the dispatch loops), or
  anywhere inside a ``telemetry/`` module — per-op file/terminal I/O
  perturbs the very latencies the observability layer measures (the
  timeline's own writer batches+flushes off-thread for this reason).
- ``HVD1003 unbounded-blocking-wait``: ``recv``/``join``/``wait``/
  ``urlopen`` without a timeout/deadline argument (keyword, or a
  positional whose name carries ``timeout``/``deadline``/``poll``) in
  ``backend/``, ``common/tcp_transport.py`` or ``runner/network.py`` —
  the exact waits a dead or wedged peer turns into a whole-job
  deadlock; the resilience/ subsystem bounds them (docs/resilience.md),
  and every surviving unbounded wait must justify its bound with a
  suppression.  ``str.join``/``os.path.join`` are lexically exempt.
- ``HVD1004 per-segment-codec-loop``: a compress/ codec call
  (``quantize``/``dequantize``/``from_bytes``/``to_bytes`` and the
  ``*_rows`` jax twins) inside a loop or comprehension in a ``backend/``
  module — the per-segment Python-level dequant→reduce→requant chain
  allocates on every leg; route codec math through the single-pass fused
  kernels (``compress/fused.py`` ``FusedKernels.decode_add``/``encode``)
  so it executes inside the collective pass.  The kept reference A/B
  baselines carry justified suppressions.

- ``HVD1005 unbalanced-span``: a Timeline span-open call
  (``activity_start``/``activity_start_all``/``_act_start``) in a
  ``backend/`` module with no finally-guarded close on the path — an
  exception mid-op leaves the span open, every later span on that
  tensor's lane nests wrongly, and the merged cross-rank trace
  (``telemetry/trace.py``) misattributes the time.  Wrap the op body in
  ``try/finally`` with the end call in the ``finally`` block (the
  forwarding helper ``_act_start`` itself is exempt: its callers own
  the balance).

Heuristics are deliberately lexical (no type inference): a flagged line
that is provably safe carries ``# hvdlint: disable=<rule> -- <why>``;
the justification is mandatory (``HVD901``).
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from dataclasses import dataclass, field

from .rules import RULES, Rule, Suppressions, Violation, parse_suppressions

# Names whose value differs per rank: any condition containing one makes
# the guarded code rank-asymmetric.
RANK_SOURCES = frozenset({
    "rank", "local_rank", "cross_rank", "node_rank", "request_rank",
    "process_index", "is_coordinator", "local_joined", "joined_ranks",
})

# Terminal callable names that submit a collective/barrier every rank
# must participate in (eager API, SPMD wrappers, control-plane barriers).
COLLECTIVE_NAMES = frozenset({
    "allreduce", "grouped_allreduce", "allgather", "grouped_allgather",
    "broadcast", "alltoall", "reducescatter", "grouped_reducescatter",
    "adasum",
    "enqueue_allreduce", "enqueue_grouped_allreduce", "enqueue_allgather",
    "enqueue_broadcast", "enqueue_alltoall", "enqueue_reducescatter",
    "enqueue_barrier", "enqueue_join",
    "barrier", "kv_barrier", "sync_global_devices",
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle",
})

BARRIER_NAME = "kv_barrier"

# `with <expr>:` where the terminal name contains one of these is treated
# as holding a lock (threading.Lock/RLock conventions in this tree).
LOCK_HINTS = ("lock", "mutex")

# Attribute spines that mark thread-owned shared state — the union of
# every manifest domain's attrs ("_global" covers both the bare name
# and the `core._global` spelling).  The manifest import lives below
# with the owner-module list it also feeds.
OWNED_STATE_ROOTS = frozenset({"_global"})

# Modules allowed to write owned state — declared per domain in the
# hvdsan thread-ownership manifest (analysis/hvdsan/ownership.py), which
# replaced this rule's old hard-coded basename list; entries are path
# suffixes ("common/controller.py") so same-named files in other
# packages stay outside the allowlist.
from .hvdsan.ownership import MANIFEST as OWNERSHIP_MANIFEST  # noqa: E402
from .hvdsan.ownership import owner_module_suffixes  # noqa: E402

DEFAULT_OWNER_BASENAMES = owner_module_suffixes()
OWNED_STATE_ATTRS = frozenset().union(
    *(d.attrs for d in OWNERSHIP_MANIFEST))


def _is_owner_path(path: str, owners) -> bool:
    """True when `path` matches an owner entry (path suffix, or bare
    basename for --owner-files compatibility)."""
    norm = os.path.normpath(path).replace(os.sep, "/")
    base = os.path.basename(norm)
    for sfx in owners:
        if base == sfx or norm.endswith("/" + sfx) or norm == sfx:
            return True
    return False

# Directory whose modules are data-plane hot paths: thread construction
# there is the per-ring-step spawn regression HVD1001 guards against.
# (The persistent channel workers live in runner/network.py — outside
# this directory by design, which IS the allowlist.)
THREAD_HOT_DIRS = frozenset({"backend"})

# HVD1002: blocking-I/O call names that stall a dispatch thread (file
# open, terminal write, raw socket sends that bypass the persistent
# lanes).  Flagged inside hot-path FUNCTIONS (below) anywhere in the
# tree, and inside ANY function of a telemetry/ module — telemetry ships
# in-process with the data plane, so its threads must prove their I/O is
# off the hot loop (one justified suppression: the exporter's shutdown
# dump).
BLOCKING_IO_NAMES = frozenset({"open", "print", "sendall", "sendmsg"})
# Dispatch/backend hot-path function names (leading underscores are
# stripped before matching): the per-response execution surface — op
# methods, ring/exchange helpers, and the dispatch loops that drive them.
HOT_IO_FUNCS = frozenset({
    "allreduce", "grouped_allreduce", "allgather", "allgatherv",
    "broadcast", "alltoall", "alltoallv", "reducescatter",
    "reduce_scatter", "adasum", "execute", "execute_operation",
    "quantized_allreduce", "cast_allreduce", "allreduce_locked",
    "allreduce_quantized", "full_sum", "sendrecv", "recv_accum",
    "recv_into", "recv_scratch", "pack_fusion_buffer",
    "unpack_fusion_buffer", "execute_response", "perform_operation",
    "dispatch_cycle", "background_loop", "run_cycle",
})
TELEMETRY_DIRS = frozenset({"telemetry"})

# HVD1003: blocking primitives that must carry a timeout/deadline (or a
# justified suppression) inside the transport/backend modules — the
# layers where an unbounded wait on a dead/wedged peer deadlocks the
# whole job (resilience/ converts them into RanksFailedError instead).
WAIT_NAMES = frozenset({"recv", "recv_into", "join", "wait", "urlopen"})
WAIT_DIRS = frozenset({"backend"})
WAIT_BASENAMES = frozenset({"tcp_transport.py", "network.py"})
_BOUND_HINTS = ("timeout", "deadline", "poll")

# HVD1004: compress/ codec entry points whose appearance inside a loop in
# a backend/ module marks a per-segment Python-level dequant/requant
# chain — the allocation-churn shape the fused single-pass kernels
# (compress/fused.py) replace.
CODEC_CALL_NAMES = frozenset({
    "quantize", "dequantize", "from_bytes", "to_bytes",
    "quantize_rows", "dequantize_rows",
})
CODEC_HOT_DIRS = frozenset({"backend"})

# HVD1006: queue discipline in serving/ modules — the serving hot path
# must never buffer unboundedly (overload becomes unbounded latency) or
# block unboundedly on a queue handoff (the serve loop wedges like an
# unbounded transport wait).  Queue constructors need a maxsize,
# SimpleQueue has none to give, and blocking put/get need a
# timeout/deadline or block=False.
SERVING_DIRS = frozenset({"serving"})
QUEUE_CTOR_NAMES = frozenset({"Queue", "LifoQueue", "PriorityQueue"})
QUEUE_BLOCKING_NAMES = frozenset({"put", "get"})

# HVD1007: streamed-state reads in statesync/ modules — a function that
# consumes a streamed state image (unflatten into arrays, apply a frame
# payload) must have a digest/stamp verification call in the same scope
# (or be the consumption primitive itself, whose callers own the
# check).  pull_round counts as verifying: it digest-verifies before
# returning.
STATE_CONSUME_NAMES = frozenset({
    "unflatten_state", "apply_chunk", "consume_payload",
})
STATE_VERIFY_NAMES = frozenset({
    "verify_round", "verify_stamp", "state_digest", "pull_round",
})
STATESYNC_DIRS = frozenset({"statesync"})

# HVD1005: Timeline span-open calls in backend/ modules must be paired
# with a finally-guarded close — an exception on the op path otherwise
# leaves the span open and every later span on the lane nests wrongly
# (the merged cross-rank trace then lies about where time went).  A
# call inside a function whose OWN (underscore-stripped) name is a
# span-open primitive is exempt: that is the forwarding helper
# (CollectiveBackend._act_start), whose callers own the balance.
SPAN_START_NAMES = frozenset({
    "activity_start", "activity_start_all", "act_start",
})
SPAN_END_NAMES = frozenset({
    "activity_end", "activity_end_all", "act_end",
})
SPAN_HOT_DIRS = frozenset({"backend"})


@dataclass
class LintConfig:
    select: set[str] = field(default_factory=set)    # empty = all
    ignore: set[str] = field(default_factory=set)
    owner_basenames: set[str] = field(
        default_factory=lambda: set(DEFAULT_OWNER_BASENAMES))

    def wants(self, rule: Rule) -> bool:
        keys = {rule.id, rule.slug}
        if self.select and not (keys & self.select):
            return False
        return not (keys & self.ignore)


def _terminal_name(node: ast.AST) -> str | None:
    """foo -> 'foo'; a.b.foo(...) -> 'foo'."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_rank_dependent(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in RANK_SOURCES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in RANK_SOURCES:
            return True
    return False


def _body_exits(stmts: list[ast.stmt]) -> bool:
    return any(isinstance(s, (ast.Return, ast.Raise, ast.Continue,
                              ast.Break)) for s in stmts)


def _string_literal(node: ast.AST | None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and all(
            isinstance(v, ast.Constant) for v in node.values):
        return "".join(str(v.value) for v in node.values)
    return None


@dataclass
class _BarrierSite:
    path: str
    line: int
    col: int
    tag: str


def statement_spans(tree: ast.AST) -> list[tuple[int, int]]:
    """Physical-line span of every statement: suppressions anchor to
    the whole statement, so a comment on any line of a multi-line call
    covers the violation reported at the call's first line, and a
    suppression on a decorated ``def`` line covers its decorators.
    Function/class spans stop at the header (body statements have their
    own spans) so a suppression inside a body never silences the
    def-line or decorator-line findings of the enclosing scope."""
    spans: list[tuple[int, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        start = node.lineno
        end = node.end_lineno or start
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.decorator_list:
                start = min(start,
                            min(d.lineno for d in node.decorator_list))
            if node.body:
                end = max(start, node.body[0].lineno - 1)
        spans.append((start, end))
    return spans


def span_suppressed(spans: list[tuple[int, int]], sup, line: int,
                    rule) -> bool:
    """Suppression check against the smallest statement span containing
    ``line`` (innermost statement wins, so a suppression on an outer
    compound statement never blankets its body)."""
    best: tuple[int, int] | None = None
    for s, e in spans:
        if s <= line <= e and (best is None or
                               (e - s) < (best[1] - best[0])):
            best = (s, e)
    return best is not None and sup.active_span(best[0], best[1], rule)


class _Analyzer(ast.NodeVisitor):
    def __init__(self, path: str, cfg: LintConfig, sup: Suppressions,
                 out: list[Violation],
                 barrier_sites: dict[str, _BarrierSite],
                 spans: list[tuple[int, int]] | None = None) -> None:
        self.path = path
        self.cfg = cfg
        self.sup = sup
        self.spans = spans or []
        self.out = out
        self.barrier_sites = barrier_sites
        self._in_hot_dir = bool(
            THREAD_HOT_DIRS
            & set(os.path.normpath(path).split(os.sep)[:-1]))
        self._in_telemetry_dir = bool(
            TELEMETRY_DIRS
            & set(os.path.normpath(path).split(os.sep)[:-1]))
        self._in_wait_scope = bool(
            WAIT_DIRS & set(os.path.normpath(path).split(os.sep)[:-1])
        ) or os.path.basename(path) in WAIT_BASENAMES
        self._in_codec_dir = bool(
            CODEC_HOT_DIRS
            & set(os.path.normpath(path).split(os.sep)[:-1]))
        self._in_span_dir = bool(
            SPAN_HOT_DIRS
            & set(os.path.normpath(path).split(os.sep)[:-1]))
        self._in_serving_dir = bool(
            SERVING_DIRS
            & set(os.path.normpath(path).split(os.sep)[:-1]))
        self._in_statesync_dir = bool(
            STATESYNC_DIRS
            & set(os.path.normpath(path).split(os.sep)[:-1]))
        # Depth of enclosing try-blocks whose finally contains a span
        # close, plus the linenos of span-open statements IMMEDIATELY
        # followed by such a try — the tree's idiom
        # (`_act_start(...)` then `try: ... finally: _act_end(...)`),
        # precomputed in visit_Module (HVD1005).
        self._span_guard_depth = 0
        self._span_guarded_lines: set[int] = set()
        self._func_stack: list[str] = []
        self._loop_depth = 0
        self._rank_gate_depth = 0
        self._gate_lines: list[int] = []     # lineno of each active gate
        self._lock_lines: list[int] = []     # lineno of each held lock
        # Per-function: (gate line, end line) of rank-dependent early exits.
        self._func_exits: list[list[tuple[int, int]]] = []
        self._flagged_101: set[int] = set()

    # --- reporting ---------------------------------------------------------
    def _report(self, rule_key: str, node: ast.AST, message: str) -> None:
        rule = RULES[rule_key]
        if not self.cfg.wants(rule):
            return
        line = getattr(node, "lineno", 1)
        if self.sup.active(line, rule) or \
                span_suppressed(self.spans, self.sup, line, rule):
            return
        self.out.append(Violation(self.path, line,
                                  getattr(node, "col_offset", 0) + 1,
                                  rule, message))

    # --- scope helpers -----------------------------------------------------
    def _visit_gated(self, nodes: list, gate_line: int) -> None:
        self._rank_gate_depth += 1
        self._gate_lines.append(gate_line)
        for n in nodes:
            self.visit(n)
        self._gate_lines.pop()
        self._rank_gate_depth -= 1

    # --- functions ---------------------------------------------------------
    def _visit_function(self, node) -> None:
        self._check_state_frame_reads(node)
        self._func_exits.append([])
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()
        self._func_exits.pop()

    # --- HVD1007: unverified streamed-state reads in statesync/ -------------
    def _check_state_frame_reads(self, node) -> None:
        if not self._in_statesync_dir:
            return
        if node.name.lstrip("_") in STATE_CONSUME_NAMES:
            return   # the consumption primitive itself: callers verify
        consumes: list[ast.Call] = []
        verified = False
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = _terminal_name(sub)
            if name in STATE_CONSUME_NAMES:
                consumes.append(sub)
            elif name in STATE_VERIFY_NAMES:
                verified = True
        if verified:
            return
        for call in consumes:
            self._report(
                "unverified-state-frame", call,
                f"'{_terminal_name(call)}' consumes streamed state in "
                f"'{node.name}' with no digest/stamp verification call "
                f"in scope: a torn or stale snapshot applied unverified "
                f"silently diverges the joiner — verify_round/"
                f"state_digest the image against its stamp first, or "
                f"justify with a suppression")

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    # --- rank-dependent control flow ---------------------------------------
    def visit_If(self, node: ast.If) -> None:
        dep = _is_rank_dependent(node.test)
        self.visit(node.test)
        if dep:
            self._visit_gated(node.body, node.lineno)
            self._visit_gated(node.orelse, node.lineno)
            if self._func_exits and \
                    _body_exits(node.body) != _body_exits(node.orelse):
                self._func_exits[-1].append(
                    (node.lineno, node.end_lineno or node.lineno))
        else:
            for n in node.body:
                self.visit(n)
            for n in node.orelse:
                self.visit(n)

    def visit_While(self, node: ast.While) -> None:
        dep = _is_rank_dependent(node.test)
        self.visit(node.test)
        bodies = node.body + node.orelse
        self._loop_depth += 1
        if dep:
            self._visit_gated(bodies, node.lineno)
        else:
            for n in bodies:
                self.visit(n)
        self._loop_depth -= 1

    # --- loops (HVD1004 scope: loop bodies + comprehensions) ---------------
    def _visit_loop(self, node) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_ListComp = _visit_loop
    visit_SetComp = _visit_loop
    visit_DictComp = _visit_loop
    visit_GeneratorExp = _visit_loop

    def visit_IfExp(self, node: ast.IfExp) -> None:
        dep = _is_rank_dependent(node.test)
        self.visit(node.test)
        if dep:
            self._visit_gated([node.body, node.orelse], node.lineno)
        else:
            self.visit(node.body)
            self.visit(node.orelse)

    def visit_BoolOp(self, node: ast.BoolOp) -> None:
        # `rank == 0 and do_collective()`: operands after a rank-dependent
        # operand are conditionally evaluated.
        gated = False
        for value in node.values:
            if gated:
                self._visit_gated([value], node.lineno)
            else:
                self.visit(value)
            gated = gated or _is_rank_dependent(value)

    def visit_Assert(self, node: ast.Assert) -> None:
        # `assert rank == 0` raises on every other rank: code after it is
        # as asymmetric as code after a rank-gated raise.
        if self._func_exits and _is_rank_dependent(node.test):
            self._func_exits[-1].append(
                (node.lineno, node.end_lineno or node.lineno))
        self.generic_visit(node)

    # --- try/finally (HVD1005 span balance) ---------------------------------
    @staticmethod
    def _finally_closes_span(try_node) -> bool:
        return any(
            isinstance(sub, ast.Call)
            and (_terminal_name(sub) or "").lstrip("_") in SPAN_END_NAMES
            for stmt in try_node.finalbody for sub in ast.walk(stmt))

    @staticmethod
    def _is_span_start_stmt(stmt: ast.stmt) -> bool:
        return (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and (_terminal_name(stmt.value) or "").lstrip("_")
                in SPAN_START_NAMES)

    @classmethod
    def _span_start_stmt_lines(cls, stmt: ast.stmt) -> list[int]:
        """Linenos of span-open calls this statement contributes to the
        followed-by-a-guarded-try idiom: a bare start statement, or an
        `if cond: start(...)` whose body holds only start calls (the
        conditional-span idiom, e.g. fused-only MEMCPY spans)."""
        if cls._is_span_start_stmt(stmt):
            return [stmt.lineno]
        if isinstance(stmt, ast.If):
            lines: list[int] = []
            for sub in stmt.body + stmt.orelse:
                if cls._is_span_start_stmt(sub):
                    lines.append(sub.lineno)
                elif not isinstance(sub, ast.Pass):
                    return []
            return lines
        return []

    def visit_Module(self, node: ast.Module) -> None:
        if self._in_span_dir:
            for sub in ast.walk(node):
                for fname in ("body", "orelse", "finalbody"):
                    stmts = getattr(sub, fname, None)
                    if not isinstance(stmts, list):
                        continue
                    for s, nxt in zip(stmts, stmts[1:]):
                        if isinstance(nxt, ast.Try) \
                                and self._finally_closes_span(nxt):
                            self._span_guarded_lines.update(
                                self._span_start_stmt_lines(s))
        self.generic_visit(node)

    def visit_Try(self, node) -> None:
        guarded = self._finally_closes_span(node)
        if guarded:
            self._span_guard_depth += 1
        for n in node.body + node.handlers + node.orelse:
            self.visit(n)
        if guarded:
            self._span_guard_depth -= 1
        for n in node.finalbody:
            self.visit(n)

    visit_TryStar = visit_Try

    # --- locks -------------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        lockish = False
        for item in node.items:
            self.visit(item.context_expr)
            name = _terminal_name(item.context_expr)
            if name and any(h in name.lower() for h in LOCK_HINTS):
                lockish = True
        if lockish:
            self._lock_lines.append(node.lineno)
        for n in node.body:
            self.visit(n)
        if lockish:
            self._lock_lines.pop()

    visit_AsyncWith = visit_With

    # --- calls -------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        name = _terminal_name(node)
        if name in COLLECTIVE_NAMES:
            self._check_collective(node, name)
        if name == BARRIER_NAME:
            self._check_barrier_tag(node)
        if name == "Thread" and self._in_hot_dir:
            self._report(
                "thread-spawn-in-backend", node,
                "threading.Thread constructed in a backend/ hot path; "
                "per-op spawns scale with ring steps — route sends "
                "through the mesh's persistent sender lanes "
                "(PeerMesh.send_async) instead")
        if name in BLOCKING_IO_NAMES:
            self._check_blocking_io(node, name)
        if name in WAIT_NAMES and self._in_wait_scope:
            self._check_unbounded_wait(node, name)
        if self._in_serving_dir:
            self._check_serving_queue(node, name)
        if name and name.lstrip("_") in SPAN_START_NAMES \
                and self._in_span_dir \
                and self._span_guard_depth == 0 \
                and node.lineno not in self._span_guarded_lines \
                and not (self._func_stack and
                         self._func_stack[-1].lstrip("_")
                         in SPAN_START_NAMES):
            self._report(
                "unbalanced-span", node,
                f"span-open call '{name}' has no finally-guarded "
                f"activity_end on this path: an exception before the "
                f"end call leaves the span open and corrupts the "
                f"tensor's trace lane — wrap the body in try/finally "
                f"with the matching end call in the finally block")
        if name in CODEC_CALL_NAMES and self._in_codec_dir \
                and self._loop_depth > 0:
            self._report(
                "per-segment-codec-loop", node,
                f"codec call '{name}' inside a loop in a backend/ "
                f"module: per-segment Python-level dequant/requant "
                f"chains allocate on every leg — execute the codec "
                f"math inside the collective pass via the fused "
                f"single-pass kernels (compress/fused.py "
                f"FusedKernels.decode_add/decode_into/encode), or "
                f"justify the reference chain with a suppression")
        self.generic_visit(node)

    # --- HVD1003: unbounded blocking waits ---------------------------------
    @staticmethod
    def _wait_is_exempt(node: ast.Call, name: str) -> bool:
        """str.join / os.path.join etc. are not waits: exempt a `join`
        whose receiver is a string literal or an attribute spine through
        `path`/`sep` (lexical, like every other rule here)."""
        if name != "join" or not isinstance(node.func, ast.Attribute):
            return False
        base = node.func.value
        if isinstance(base, ast.Constant) and isinstance(base.value, str):
            return True
        spine = set()
        while isinstance(base, ast.Attribute):
            spine.add(base.attr)
            base = base.value
        if isinstance(base, ast.Name):
            spine.add(base.id)
        return bool(spine & {"path", "sep", "pathsep", "linesep",
                             "os", "posixpath", "ntpath"})

    @staticmethod
    def _call_is_bounded(node: ast.Call) -> bool:
        for kw in node.keywords:
            if kw.arg and any(h in kw.arg.lower() for h in _BOUND_HINTS):
                return True
        for arg in node.args:
            for sub in ast.walk(arg):
                ident = sub.id if isinstance(sub, ast.Name) else (
                    sub.attr if isinstance(sub, ast.Attribute) else None)
                if ident and any(h in ident.lower()
                                 for h in _BOUND_HINTS):
                    return True
        return False

    def _check_unbounded_wait(self, node: ast.Call, name: str) -> None:
        if self._wait_is_exempt(node, name):
            return
        if self._call_is_bounded(node):
            return
        self._report(
            "unbounded-blocking-wait", node,
            f"blocking call '{name}' has no timeout/deadline argument; "
            f"in a transport/backend module an unbounded wait turns a "
            f"dead or wedged peer into a whole-job deadlock — pass a "
            f"timeout, derive a deadline from the ResilienceContext "
            f"(resilience/), or justify the bound with a suppression")

    # --- HVD1006: queue discipline in serving/ ------------------------------
    @staticmethod
    def _receiver_is_queueish(base: ast.AST) -> bool:
        """Lexical receiver filter for put/get: dict.get / config
        knob .get() are everywhere, so the blocking-call half of the
        rule bites only on receivers that read as queues ('q',
        '*queue*', '*_q')."""
        ident = base.attr if isinstance(base, ast.Attribute) else (
            base.id if isinstance(base, ast.Name) else None)
        if ident is None or ident.isupper():
            return False   # ALL-CAPS receiver = a config-knob constant
        ident = ident.lower()
        return ident == "q" or "queue" in ident or ident.endswith("_q")

    def _check_serving_queue(self, node: ast.Call, name: str | None) -> None:
        if name in QUEUE_CTOR_NAMES:
            bounded = bool(node.args) or any(
                kw.arg and "maxsize" in kw.arg.lower()
                for kw in node.keywords)
            if not bounded:
                self._report(
                    "unbounded-queue-in-serving", node,
                    f"'{name}()' without maxsize in a serving/ module: "
                    f"an unbounded ingress queue converts overload into "
                    f"unbounded latency — bound it and shed at the door "
                    f"(serving/queue.py RequestQueue)")
        elif name == "SimpleQueue":
            self._report(
                "unbounded-queue-in-serving", node,
                "SimpleQueue in a serving/ module has no capacity bound "
                "at all — use a bounded queue and shed at the door")
        elif name in QUEUE_BLOCKING_NAMES \
                and isinstance(node.func, ast.Attribute) \
                and self._receiver_is_queueish(node.func.value):
            nonblocking = any(
                kw.arg == "block" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False for kw in node.keywords)
            if not nonblocking and not self._call_is_bounded(node):
                self._report(
                    "unbounded-queue-in-serving", node,
                    f"blocking '{name}' without a timeout/deadline in a "
                    f"serving/ module: the serve loop wedges like an "
                    f"unbounded transport wait (HVD1003) — pass a "
                    f"timeout derived from the request deadline, or "
                    f"block=False and shed")

    def _check_blocking_io(self, node: ast.Call, name: str) -> None:
        hot_fn = next((fn for fn in self._func_stack
                       if fn.lstrip("_") in HOT_IO_FUNCS), None)
        if hot_fn is not None:
            self._report(
                "blocking-io-in-hot-path", node,
                f"blocking I/O call '{name}' inside hot-path function "
                f"'{hot_fn}': file/terminal I/O on the dispatch path "
                f"perturbs the latencies being measured — emit through "
                f"the timeline's async writer or a telemetry metric "
                f"instead")
        elif self._in_telemetry_dir and self._func_stack:
            self._report(
                "blocking-io-in-hot-path", node,
                f"blocking I/O call '{name}' in a telemetry/ module "
                f"(ships in-process with the data plane): justify that "
                f"it runs off the hot loop with a suppression, or route "
                f"it through the exporter thread")

    def _check_collective(self, node: ast.Call, name: str) -> None:
        if self._rank_gate_depth > 0:
            self._report(
                "rank-gated-collective", node,
                f"collective '{name}' is only submitted by ranks "
                f"satisfying the rank-dependent condition at line "
                f"{self._gate_lines[-1]}; the other ranks will wait "
                f"forever (every rank must submit the same collectives "
                f"in the same order)")
            self._flagged_101.add(node.lineno)
        elif self._func_exits:
            for gate_line, gate_end in self._func_exits[-1]:
                if node.lineno > gate_end and \
                        node.lineno not in self._flagged_101:
                    self._report(
                        "rank-gated-early-return", node,
                        f"collective '{name}' is unreachable for ranks "
                        f"taking the rank-dependent early exit at line "
                        f"{gate_line}")
                    break
        if self._lock_lines:
            self._report(
                "collective-under-lock", node,
                f"collective '{name}' invoked while holding the lock "
                f"acquired at line {self._lock_lines[-1]}; if the "
                f"background loop or a completion callback takes the "
                f"same lock, the world deadlocks")

    def _check_barrier_tag(self, node: ast.Call) -> None:
        tag_node: ast.AST | None = None
        if node.args:
            tag_node = node.args[0]
        for kw in node.keywords:
            if kw.arg == "tag":
                tag_node = kw.value
        if tag_node is None:
            return
        tag = _string_literal(tag_node)
        if tag is None:
            self._report(
                "dynamic-barrier-tag", node,
                "kv_barrier tag is not a string literal; it cannot be "
                "proven identical on every rank (a rank-dependent tag "
                "permanently misaligns the barrier sequence)")
            return
        prior = self.barrier_sites.get(tag)
        if prior is not None and (prior.path, prior.line) != \
                (self.path, node.lineno):
            self._report(
                "duplicate-barrier-tag", node,
                f"kv_barrier tag {tag!r} is already used at "
                f"{prior.path}:{prior.line}; a timeout naming this tag "
                f"could not be attributed to a call site")
        else:
            self.barrier_sites[tag] = _BarrierSite(
                self.path, node.lineno, node.col_offset + 1, tag)

    # --- shared-state writes -----------------------------------------------
    def _owned_state_target(self, target: ast.AST) -> str | None:
        if not isinstance(target, ast.Attribute):
            return None
        spine: list[str] = []
        node: ast.AST = target
        while isinstance(node, ast.Attribute):
            spine.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            spine.append(node.id)
            if node.id in OWNED_STATE_ROOTS:
                return ".".join(reversed(spine))
        # owner attrs anywhere on the spine EXCEPT the final field being
        # assigned (writing `x.controller = c` wires the object up;
        # writing `x.controller.field = v` mutates its internals).
        if set(spine[1:]) & OWNED_STATE_ATTRS:
            return ".".join(reversed(spine))
        return None

    def _check_state_write(self, node, targets: list[ast.AST]) -> None:
        if _is_owner_path(self.path, self.cfg.owner_basenames):
            return
        for target in targets:
            chain = self._owned_state_target(target)
            if chain is not None:
                self._report(
                    "shared-state-write", node,
                    f"write to background-thread-owned state "
                    f"'{chain}' outside its owning module; route the "
                    f"change through the controller protocol (e.g. a "
                    f"broadcast ResponseList field) instead")

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_state_write(node, node.targets)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_state_write(node, [node.target])
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._check_state_write(node, [node.target])
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------
def lint_source(source: str, path: str, cfg: LintConfig | None = None,
                barrier_sites: dict[str, _BarrierSite] | None = None,
                tree: ast.AST | None = None) -> list[Violation]:
    """Lint one file's source.  ``tree`` reuses an existing parse —
    the driver parses each file exactly once and hands the same AST to
    every rule family (including hvdsan under ``--san``)."""
    cfg = cfg or LintConfig()
    sup = parse_suppressions(source)
    out: list[Violation] = []
    if tree is None:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            out.append(Violation(path, exc.lineno or 1, exc.offset or 1,
                                 RULES["syntax-error"],
                                 f"syntax error: {exc.msg}"))
            return out
    analyzer = _Analyzer(path, cfg, sup,
                         out, barrier_sites if barrier_sites is not None
                         else {}, spans=statement_spans(tree))
    analyzer.visit(tree)
    bare_rule = RULES["bare-suppression"]
    if cfg.wants(bare_rule):
        for line, text in sup.bare:
            if not sup.active(line, bare_rule):
                out.append(Violation(
                    path, line, 1, bare_rule,
                    f"suppression without a '-- <justification>': "
                    f"{text!r}"))
    return out


def iter_python_files(paths: list[str]):
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def changed_py_files(paths: list[str], diff_base: str | None = None
                     ) -> tuple[list[str] | None, str | None]:
    """Python files git reports modified/staged/untracked under
    ``paths`` (--changed-only), **following renames** (a renamed file
    is linted at its new path).  Returns ``(files, warning)``:
    ``(None, reason)`` when git is unavailable, errors out, or the
    requested ``--diff-base`` ref is missing — callers fall back to
    the full walk and surface the structured warning instead of
    crashing (CI must degrade to over-checking, never under-)."""
    import subprocess

    def _git(argv):
        return subprocess.run(["git"] + argv, capture_output=True,
                              text=True, timeout=30)

    out: set[str] = set()
    try:
        proc = _git(["status", "--porcelain", "--find-renames", "--"]
                    + list(paths))
    except (OSError, subprocess.TimeoutExpired) as exc:
        return None, f"git unavailable ({exc.__class__.__name__}); " \
                     f"fell back to a full-tree scan"
    if proc.returncode != 0:
        return None, (f"git status failed "
                      f"({proc.stderr.strip() or proc.returncode}); "
                      f"fell back to a full-tree scan")
    for line in proc.stdout.splitlines():
        if len(line) < 4:
            continue
        name = line[3:].strip().strip('"')
        if " -> " in name:       # rename: lint the NEW path
            name = name.split(" -> ", 1)[1].strip().strip('"')
        if name.endswith(".py") and os.path.isfile(name):
            out.add(name)
    if diff_base:
        try:
            proc = _git(["diff", "--name-status", "-M", diff_base,
                         "--"] + list(paths))
        except (OSError, subprocess.TimeoutExpired) as exc:
            return None, (f"git diff vs {diff_base!r} unavailable "
                          f"({exc.__class__.__name__}); fell back to "
                          f"a full-tree scan")
        if proc.returncode != 0:
            return None, (f"diff base {diff_base!r} missing or "
                          f"unusable "
                          f"({proc.stderr.strip() or proc.returncode});"
                          f" fell back to a full-tree scan")
        for line in proc.stdout.splitlines():
            parts = line.split("\t")
            if len(parts) < 2:
                continue
            # Rxx old new / Cxx old new: last column is the new path.
            name = parts[-1].strip().strip('"')
            if name.endswith(".py") and os.path.isfile(name):
                out.add(name)
    return sorted(out), None


def lint_paths_timed(paths: list[str], cfg: LintConfig | None = None,
                     san: bool = False, changed_only: bool = False,
                     diff_base: str | None = None, flow: bool = False,
                     life: bool = False, shard: bool = False
                     ) -> tuple[list[Violation], list, dict]:
    """One parse + one rule walk per file; hvdsan (``san=True``),
    hvdflow (``flow=True``), hvdlife (``life=True``) and hvdshard
    (``shard=True``) ride the SAME trees.  ``shard`` implies building
    the flow program: HVD803 is located by the hvdflow pass over its
    spec-annotated streams.  Returns (violations,
    san+flow+life+shard findings, stats)."""
    import time as _time
    cfg = cfg or LintConfig()
    out: list[Violation] = []
    warnings: list[str] = []
    barrier_sites: dict[str, _BarrierSite] = {}
    program = None
    flowprog = None
    lifeprog = None
    shardprog = None
    if san or flow or life or shard:
        from .hvdsan.lockgraph import Program
        program = Program()
    if flow or shard:
        from .hvdflow.flow import FlowProgram
        flowprog = FlowProgram()
    if life:
        from .hvdlife.life import LifeProgram
        lifeprog = LifeProgram()
    if shard:
        from .hvdshard.shard import ShardProgram
        shardprog = ShardProgram()
    files = list(iter_python_files(paths))
    if changed_only:
        changed, warning = changed_py_files(paths,
                                            diff_base=diff_base)
        if changed is not None:
            keep = {os.path.normpath(c) for c in changed}
            files = [f for f in files if os.path.normpath(f) in keep]
        else:
            warnings.append(f"--changed-only: {warning}")
    t0 = _time.monotonic()
    nfiles = 0
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as exc:
            print(f"hvdlint: cannot read {path}: {exc}", file=sys.stderr)
            continue
        nfiles += 1
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            out.append(Violation(path, exc.lineno or 1, exc.offset or 1,
                                 RULES["syntax-error"],
                                 f"syntax error: {exc.msg}"))
            continue
        out.extend(lint_source(source, path, cfg, barrier_sites,
                               tree=tree))
        if program is not None:
            program.collect_source(path, source, tree)
        if flowprog is not None:
            flowprog.collect_source(path, source, tree)
        if lifeprog is not None:
            lifeprog.collect_source(path, source, tree)
        if shardprog is not None:
            shardprog.collect_source(path, source, tree)
    findings: list = []
    if san and program is not None:
        from .hvdsan.lockgraph import Analysis
        analysis = Analysis(program).analyze()
        findings = [f for f in analysis.findings if cfg.wants(f.rule)]
    if flowprog is not None:
        from .hvdflow.flow import analyze_flow
        findings.extend(analyze_flow(program, flowprog, cfg))
    if lifeprog is not None:
        from .hvdlife.life import analyze_life
        findings.extend(analyze_life(program, lifeprog, cfg))
    if shardprog is not None:
        from .hvdshard.shard import analyze_shard
        findings.extend(analyze_shard(program, shardprog, cfg))
    # The flow pass emits both families; keep only what was asked for
    # (--shard without --flow must not surface HVD6xx, and vice versa).
    if flowprog is not None and not flow:
        from .hvdflow.flow import FLOW_RULE_IDS
        findings = [f for f in findings
                    if f.rule.id not in FLOW_RULE_IDS]
    if flowprog is not None and not shard:
        from .hvdshard.shard import SHARD_RULE_IDS
        findings = [f for f in findings
                    if f.rule.id not in SHARD_RULE_IDS]
    stats = {"files": nfiles,
             "wall_ms": round((_time.monotonic() - t0) * 1e3, 3),
             "warnings": warnings}
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule.id))
    return out, findings, stats


def lint_paths(paths: list[str],
               cfg: LintConfig | None = None) -> list[Violation]:
    return lint_paths_timed(paths, cfg)[0]


def _parse_rule_set(raw: str | None) -> set[str]:
    if not raw:
        return set()
    names = {r.strip() for r in raw.split(",") if r.strip()}
    unknown = {n for n in names if n not in RULES and n != "all"}
    if unknown:
        raise SystemExit(f"hvdlint: unknown rule(s): {sorted(unknown)} "
                         f"(known: {sorted(set(r.slug for r in RULES.values()))})")
    return names


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis.lint",
        description="Static analyzer for the symmetric-collective "
                    "contract (see docs/analysis.md).")
    parser.add_argument("paths", nargs="*", default=["horovod_tpu"],
                        help="files or directories to lint "
                             "(default: horovod_tpu)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--select", help="comma-separated rule ids/slugs "
                                         "to enable (default: all)")
    parser.add_argument("--ignore", help="comma-separated rule ids/slugs "
                                         "to disable")
    parser.add_argument("--owner-files",
                        help="extra basenames/path suffixes allowed to "
                             "write manifest-owned shared state (HVD401)")
    parser.add_argument("--changed-only", action="store_true",
                        help="lint only files git reports as changed, "
                             "following renames (fast CI gate; "
                             "cross-file rules see only the changed "
                             "set; falls back to the full tree with a "
                             "structured warning when git or the diff "
                             "base is unavailable)")
    parser.add_argument("--diff-base", metavar="REF",
                        help="with --changed-only, also include files "
                             "changed since REF (git diff -M REF)")
    parser.add_argument("--san", action="store_true",
                        help="also run the hvdsan whole-program "
                             "concurrency analysis (HVD501-505) over "
                             "the same parse of each file")
    parser.add_argument("--flow", action="store_true",
                        help="also run the hvdflow interprocedural "
                             "rank-divergence dataflow analysis "
                             "(HVD601-604) over the same parse of "
                             "each file")
    parser.add_argument("--life", action="store_true",
                        help="also run the hvdlife whole-program "
                             "resource-lifecycle analysis "
                             "(HVD701-705) over the same parse of "
                             "each file")
    parser.add_argument("--shard", action="store_true",
                        help="also run the hvdshard sharding-spec "
                             "analysis (HVD801-804) over the same "
                             "parse of each file (builds the hvdflow "
                             "program too: HVD803 rides its "
                             "spec-annotated streams)")
    parser.add_argument("--knobs", action="store_true",
                        help="print the generated typed-knob registry "
                             "table (the docs/configuration.md "
                             "content) and exit")
    args = parser.parse_args(argv)

    if args.knobs:
        from ..common.config import configuration_markdown
        print(configuration_markdown(), end="")
        return 0

    cfg = LintConfig(select=_parse_rule_set(args.select),
                     ignore=_parse_rule_set(args.ignore))
    if args.owner_files:
        cfg.owner_basenames |= {b.strip()
                                for b in args.owner_files.split(",")
                                if b.strip()}
    violations, findings, stats = lint_paths_timed(
        args.paths, cfg, san=args.san, changed_only=args.changed_only,
        diff_base=args.diff_base, flow=args.flow, life=args.life,
        shard=args.shard)
    from .hvdflow.flow import FLOW_RULE_IDS
    from .hvdlife.life import LIFE_RULE_IDS
    from .hvdshard.shard import SHARD_RULE_IDS
    san_findings = [f for f in findings
                    if f.rule.id not in FLOW_RULE_IDS
                    and f.rule.id not in LIFE_RULE_IDS
                    and f.rule.id not in SHARD_RULE_IDS]
    flow_findings = [f for f in findings if f.rule.id in FLOW_RULE_IDS]
    life_findings = [f for f in findings if f.rule.id in LIFE_RULE_IDS]
    shard_findings = [f for f in findings
                      if f.rule.id in SHARD_RULE_IDS]
    errors = [f for f in findings if f.severity == "error"]
    for w in stats["warnings"]:
        print(f"hvdlint: warning: {w}", file=sys.stderr)
    if args.format == "json":
        print(json.dumps({
            "violations": [v.json() for v in violations],
            "san": [f.json() for f in san_findings],
            "flow": [f.json() for f in flow_findings],
            "life": [f.json() for f in life_findings],
            "shard": [f.json() for f in shard_findings],
            "files": stats["files"],
            "wall_ms": stats["wall_ms"],
            "warnings": stats["warnings"],
        }, indent=2))
    elif args.format == "sarif":
        from .hvdsan.san import sarif_payload
        print(json.dumps(sarif_payload(list(violations) + findings),
                         indent=2))
    else:
        for v in violations:
            print(v.text())
        for f in findings:
            print(f.text())
        print(f"hvdlint: {len(violations)} violation(s)"
              + (f", {len(errors)} san/flow/life/shard error(s), "
                 f"{len(findings) - len(errors)} warning(s)"
                 if (args.san or args.flow or args.life or args.shard)
                 else "")
              + f" in {', '.join(args.paths)} "
              f"({stats['files']} file(s), {stats['wall_ms']:.1f} ms)",
              file=sys.stderr)
    return 1 if (violations or errors) else 0


if __name__ == "__main__":
    sys.exit(main())
