"""Correctness tooling for the symmetric-collective contract.

Horovod-class deadlock freedom rests on one invariant: every rank submits
the same collectives in the same order (SURVEY §5.2; reference paper
arxiv 1802.05799 turns *parameter* mismatches into structured errors, but
ships no tooling for *call-pattern* mismatches).  This package closes that
gap from both ends:

- :mod:`horovod_tpu.analysis.lint` — **hvdlint**, an AST-based static
  analyzer (CLI: ``python -m horovod_tpu.analysis.lint``) that proves the
  contract at review time: rank-gated collectives, asymmetric early
  returns, duplicated/dynamic ``kv_barrier`` tags, collectives under
  locks the background loop takes, shared-state writes outside the
  owning thread.
- :mod:`horovod_tpu.analysis.fingerprint` — runtime collective
  fingerprinting: each rank folds every submitted op into a rolling
  hash; the coordinator compares fingerprints on the existing
  Request/Response control plane and turns cross-rank divergence into a
  structured ``Response.ERROR`` naming the first divergent op
  (``HOROVOD_FINGERPRINT={off,cycle,strict}``).
- :mod:`horovod_tpu.analysis.hvdsan` — **hvdsan**, whole-program
  concurrency verification (CLI:
  ``python -m horovod_tpu.analysis.hvdsan`` or ``lint --san``): an
  interprocedural lock-acquisition graph checked for lock-order
  inversion cycles, locks held across blocking/collective calls and
  orphan condition waits (HVD501-503); a declarative thread-ownership
  manifest (HVD504, also feeding hvdlint's HVD401); a wire-schema
  drift check (HVD505); and a ``HOROVOD_SAN=1`` runtime witness whose
  observed lock-order graph CI diffs against the static one.
- :mod:`horovod_tpu.analysis.hvdflow` — **hvdflow**, interprocedural
  rank-divergence dataflow (CLI:
  ``python -m horovod_tpu.analysis.hvdflow`` or ``lint --flow``):
  per-function collective-effect summaries composed through the hvdsan
  call graph plus a rank-taint fixpoint, flagging divergent collective
  streams under rank-tainted branches (HVD601) and loops (HVD602),
  serve-path waits with no deadline on any interprocedural path
  (HVD603), and raw ``HOROVOD_*`` environment reads missing from the
  typed knob registry (HVD604, ``lint --knobs`` /
  docs/configuration.md) — the compile-time half of fingerprinting.

See docs/analysis.md for the rule catalogue and fingerprint modes.
"""
from .fingerprint import (FingerprintMode, FingerprintTracker,  # noqa: F401
                          OpRecord)
from .rules import RULES, Rule, Violation  # noqa: F401
