"""HVD506 — spec <-> code conformance (the hvdmc half of what HVD505
does for ``common/wire.py``).

The protocol specs co-located with the implementation
(``statesync/specs.py``, ``resilience/specs.py``) claim a message
vocabulary and a set of handler transitions bound to concrete
functions.  This pass diffs both against the collected program facts
(the same single AST walk hvdsan rides):

**spec -> code** (the checker must verify a protocol that exists):

- every frame verb's constant is defined in its declaring module;
- every transition's bound function exists;
- a ``recv:V`` transition's bound function really compares on ``V``'s
  constant, a ``send:V`` one really packs it;
- KV-record and boundary-flag verbs appear as string literals in the
  bound (or anchor-module) code;
- every ``requires_calls`` name is called from some bound function.

**code -> spec** (the checker must know every protocol branch):

- every ``STATE_*`` constant defined in a verb-declaring module is
  claimed by some spec verb;
- every frame-constant comparison or ``pack_state_frame(CONST, ...)``
  in an anchor module is claimed by a spec transition bound to that
  function.

A spec only activates when one of its ``anchor_modules`` is in the
analyzed set, so single-fixture lint runs never see tree-wide drift.
"""
from __future__ import annotations

__all__ = ["all_specs", "check_spec_conformance", "check_tree"]


def all_specs():
    """The registered protocol specs (order is report order)."""
    from ...fleet.specs import fleet_spec
    from ...resilience.specs import shrink_spec
    from ...runner.specs import failover_spec
    from ...statesync.specs import grow_spec, preempt_spec, stream_spec

    return (grow_spec(), stream_spec(), preempt_spec(), shrink_spec(),
            failover_spec(), fleet_spec())


def _module_of(program, funckey: str):
    """Longest module label that prefixes a hvdsan function key."""
    parts = funckey.split(".")
    for i in range(len(parts) - 1, 0, -1):
        label = ".".join(parts[:i])
        if label in program.modules:
            return label
    return None


def check_spec_conformance(analysis, specs=None) -> None:
    """Emit HVD506 findings on `analysis` (a lockgraph.Analysis)."""
    program = analysis.program
    specs = all_specs() if specs is None else specs
    active = [sp for sp in specs
              if any(m in program.modules for m in sp.anchor_modules)]
    if not active:
        return
    # code -> spec: every STATE_* constant in a verb-declaring module is
    # claimed by SOME active spec's vocabulary.
    claimed_by_module: dict = {}
    for sp in active:
        for v in sp.verbs:
            if v.kind == "frame" and v.const and v.defined_in:
                claimed_by_module.setdefault(v.defined_in,
                                             set()).add(v.const)
    for suffix, claimed in sorted(claimed_by_module.items()):
        mod = next((m for m in program.modules.values()
                    if m.path.endswith(suffix)), None)
        if mod is None:
            continue
        defined = {k for k in mod.int_consts if k.startswith("STATE_")}
        for extra in sorted(defined - claimed):
            val, line = mod.int_consts[extra]
            analysis._emit(
                "spec-conformance", "error", mod.path, line,
                f"frame verb constant {extra} is not in any protocol "
                f"spec's vocabulary: the model checker never explores "
                f"frames of this kind — add the verb (and its "
                f"transitions) to the spec, or remove the constant")
        for missing in sorted(claimed - defined):
            analysis._emit(
                "spec-conformance", "error", mod.path, 1,
                f"spec verb constant {missing} is not defined in "
                f"{suffix}: the spec describes a frame kind the wire "
                f"cannot carry")
    for sp in active:
        _check_spec(analysis, sp)
    _check_unspecced_handlers(analysis, active)


def _anchor_path(program, spec):
    for m in spec.anchor_modules:
        mod = program.modules.get(m)
        if mod is not None:
            return mod.path
    return spec.anchor_modules[0] if spec.anchor_modules else "<spec>"


def _check_spec(analysis, spec) -> None:
    program = analysis.program
    apath = _anchor_path(program, spec)
    for problem in spec.validate():
        analysis._emit("spec-conformance", "error", apath, 1,
                       f"spec {spec.name} is malformed: {problem}")
    verbs = {v.name: v for v in spec.verbs}
    for t in spec.transitions:
        bound = []
        for key in t.binds:
            mod = _module_of(program, key)
            if mod is None:
                continue             # binding module not analyzed: skip
            fn = program.functions.get(key)
            if fn is None:
                analysis._emit(
                    "spec-conformance", "error", apath, 1,
                    f"spec {spec.name} transition {t.tid} binds "
                    f"{key}, which no longer exists — rebind the "
                    f"transition or restore the handler")
            else:
                bound.append(fn)
        if not bound:
            continue
        called = set()
        for fn in bound:
            called |= {ev.spine[-1] for ev in fn.calls}
        for req in t.requires_calls:
            if req not in called:
                analysis._emit(
                    "spec-conformance", "error", bound[0].path,
                    bound[0].line,
                    f"spec {spec.name} transition {t.tid} requires a "
                    f"call to '{req}' in {', '.join(f.key for f in bound)} "
                    f"but none was found — the protocol action the "
                    f"spec models is gone")
        head, _, vname = t.event.partition(":")
        verb = verbs.get(vname)
        if verb is None:
            continue
        if verb.kind == "frame" and head in ("recv", "send"):
            facts = set()
            for fn in bound:
                facts |= fn.state_compares if head == "recv" \
                    else fn.state_packs
            if verb.const not in facts:
                what = "compares on" if head == "recv" else "packs"
                analysis._emit(
                    "spec-conformance", "error", bound[0].path,
                    bound[0].line,
                    f"spec {spec.name} transition {t.tid} says "
                    f"{bound[0].key} {what} {verb.const}, but the "
                    f"code does not — handler drift")
        elif verb.kind in ("kv", "flag") and head in ("kv", "send",
                                                      "recv"):
            strs = set()
            for fn in bound:
                strs |= fn.strs
            for m in spec.anchor_modules:
                mod = program.modules.get(m)
                if mod is not None:
                    strs |= mod.strs
                    for f2 in program.functions.values():
                        if f2.module == m:
                            strs |= f2.strs
            if not any(verb.const in s or s.startswith(verb.const)
                       for s in strs):
                analysis._emit(
                    "spec-conformance", "error", apath, 1,
                    f"spec {spec.name} verb {verb.name} "
                    f"({verb.kind} key {verb.const!r}) appears "
                    f"nowhere in the bound code — the record the "
                    f"spec models is never written or read")


def _check_unspecced_handlers(analysis, active) -> None:
    """code -> spec: frame-constant handler branches and pack sites in
    anchor modules must be claimed by a transition bound there."""
    program = analysis.program
    claims: dict = {}            # (funckey, const, dir) -> True
    anchor_mods = set()
    for sp in active:
        anchor_mods |= set(sp.anchor_modules)
        verbs = {v.name: v for v in sp.verbs}
        for t in sp.transitions:
            head, _, vname = t.event.partition(":")
            verb = verbs.get(vname)
            if verb is None or verb.kind != "frame":
                continue
            for key in t.binds:
                claims[(key, verb.const,
                        "recv" if head == "recv" else "send")] = True
    for fn in program.functions.values():
        if fn.module not in anchor_mods:
            continue
        for const in sorted(fn.state_compares):
            if not claims.get((fn.key, const, "recv")):
                analysis._emit(
                    "spec-conformance", "error", fn.path, fn.line,
                    f"{fn.key} dispatches on frame verb {const} but no "
                    f"spec transition binds that handler — the model "
                    f"checker never explores this branch; add the "
                    f"transition to the protocol spec")
        for const in sorted(fn.state_packs):
            if not claims.get((fn.key, const, "send")):
                analysis._emit(
                    "spec-conformance", "error", fn.path, fn.line,
                    f"{fn.key} sends frame verb {const} but no spec "
                    f"transition claims that send — the model checker "
                    f"never explores this message; add the transition "
                    f"to the protocol spec")


def check_tree(paths=None):
    """Standalone conformance over a tree (the ``mc --check-tree``
    gate): returns the HVD506 findings without running the rest of the
    hvdsan analysis."""
    from ..hvdsan.lockgraph import Analysis, Program

    program = Program()
    program.collect_paths(list(paths or ["horovod_tpu"]))
    analysis = Analysis(program)
    check_spec_conformance(analysis)
    analysis.findings.sort(key=lambda f: (f.path, f.line, f.rule.id))
    return analysis.findings
