"""hvdmc explicit-state exploration kernel.

A :class:`Model` is an executable protocol semantics whose transition
labels are spec transition ids (:mod:`.spec`): :func:`explore` BFS-walks
the global state space to a fixpoint, checking safety invariants at
every state, flagging **stuck** states (no successors, not terminal),
and — for models that define a resolution predicate — flagging states
from which the protocol can no longer reach *any* resolution (the
"join neither completes nor aborts" livelock class, AG EF resolved).

Counterexamples are reconstructed from BFS parent pointers, so every
reported trace is a shortest path and the rendering is deterministic
(the golden-fixture contract: no wall times, no absolute paths — rank
interleavings and spec-bound code sites only).
"""
from __future__ import annotations

import collections
from dataclasses import dataclass, field

__all__ = ["ExploreResult", "Model", "PropertyViolation", "explore",
           "render_trace"]


class Model:
    """Interface the machines implement.  States must be hashable and
    successor enumeration deterministic."""

    name = "model"
    spec = None                  # ProtocolSpec (or a tuple of them)

    def initial(self):
        raise NotImplementedError

    def successors(self, state):
        """[(actor, tids, next_state)] — ``actor`` is a rank index or a
        symbolic actor ("joiner", "world", "net"); ``tids`` a tuple of
        spec transition ids fired atomically by this step."""
        raise NotImplementedError

    def invariants(self, state):
        """Names of safety properties VIOLATED in `state` (empty=OK)."""
        return ()

    def is_terminal(self, state) -> bool:
        """Accepting quiescent state (a successor-less non-terminal
        state is reported as stuck)."""
        return False

    def resolved(self, state) -> bool | None:
        """Protocol-resolution predicate for the AG EF check, or None
        to skip it (models without a completion obligation)."""
        return None

    def describe(self, state) -> str:
        return repr(state)

    def actor_label(self, actor) -> str:
        if isinstance(actor, int):
            return f"rank {actor}"
        return str(actor)


@dataclass
class PropertyViolation:
    prop: str                    # property name, e.g. "torn-commit"
    kind: str                    # "safety" | "stuck" | "unresolvable"
    state: object
    path: list                   # [(actor, tids, state_after)], from init
    detail: str = ""


@dataclass
class ExploreResult:
    model_name: str
    states: int = 0
    transitions: int = 0
    fixpoint: bool = False
    fired: set = field(default_factory=set)      # spec tids exercised
    violations: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.fixpoint and not self.violations


def _path_to(state, parents):
    path = []
    cur = state
    while True:
        prev = parents[cur]
        if prev is None:
            break
        prev_state, actor, tids = prev
        path.append((actor, tids, cur))
        cur = prev_state
    path.reverse()
    return path


def explore(model: Model, max_states: int = 400_000,
            max_violations: int = 4) -> ExploreResult:
    """BFS the model to a fixpoint (or the state cap), collecting the
    first counterexample per violated property."""
    res = ExploreResult(model_name=model.name)
    init = model.initial()
    parents: dict = {init: None}
    order: list = [init]
    edges: dict = collections.defaultdict(list)   # state -> [succ states]
    frontier = collections.deque([init])
    seen_props: set = set()
    capped = False
    while frontier:
        state = frontier.popleft()
        res.states += 1
        for prop in model.invariants(state):
            if prop not in seen_props and \
                    len(res.violations) < max_violations:
                seen_props.add(prop)
                res.violations.append(PropertyViolation(
                    prop=prop, kind="safety", state=state,
                    path=_path_to(state, parents),
                    detail=model.describe(state)))
        succs = model.successors(state)
        if not succs:
            if not model.is_terminal(state) and \
                    "stuck" not in seen_props and \
                    len(res.violations) < max_violations:
                seen_props.add("stuck")
                res.violations.append(PropertyViolation(
                    prop="no-stuck-state", kind="stuck", state=state,
                    path=_path_to(state, parents),
                    detail=model.describe(state)))
            continue
        for actor, tids, nxt in succs:
            res.transitions += 1
            res.fired.update(tids)
            edges[state].append(nxt)
            if nxt not in parents:
                if len(parents) >= max_states:
                    capped = True
                    continue
                parents[nxt] = (state, actor, tids)
                order.append(nxt)
                frontier.append(nxt)
    res.fixpoint = not capped
    # AG EF resolved: every reachable state must retain a path to some
    # resolved state (models opting in via resolved()).
    if res.fixpoint and model.resolved(init) is not None and \
            len(res.violations) < max_violations:
        resolved = {s for s in parents if model.resolved(s)}
        rev = collections.defaultdict(list)
        for s, outs in edges.items():
            for d in outs:
                rev[d].append(s)
        can = set(resolved)
        stack = list(resolved)
        while stack:
            for p in rev.get(stack.pop(), ()):
                if p not in can:
                    can.add(p)
                    stack.append(p)
        for s in order:                      # BFS order -> shortest first
            if s not in can:
                res.violations.append(PropertyViolation(
                    prop="resolution-reachable", kind="unresolvable",
                    state=s, path=_path_to(s, parents),
                    detail=model.describe(s)))
                break
    return res


def _binds_of(spec, tid: str) -> tuple:
    specs = spec if isinstance(spec, (list, tuple)) else (spec,)
    for sp in specs:
        if sp is None:
            continue
        t = sp.transition(tid)
        if t is not None:
            return t.binds
    return ()


def render_trace(model: Model, violation: PropertyViolation) -> str:
    """Deterministic rank-interleaved counterexample rendering: one line
    per fired step, annotated with the code sites the spec binds the
    transition to."""
    lines = [f"hvdmc counterexample [{violation.prop}] "
             f"({violation.kind}) in {model.name}"]
    for i, (actor, tids, state) in enumerate(violation.path, start=1):
        binds: list = []
        for tid in tids:
            for b in _binds_of(model.spec, tid):
                if b not in binds:
                    binds.append(b)
        anno = f"  [{'; '.join(binds)}]" if binds else ""
        lines.append(f"  {i:3d}. {model.actor_label(actor):<10} "
                     f"{' + '.join(tids)}{anno}")
        lines.append(f"       => {model.describe(state)}")
    lines.append(f"  violated: {violation.prop} at: {violation.detail}")
    return "\n".join(lines)
