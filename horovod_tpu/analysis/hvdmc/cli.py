"""``python -m horovod_tpu.analysis.mc`` — the hvdmc CLI.

Default action explores every protocol model at head with fault
injection to a fixpoint and reports state counts + violations (with
rank-interleaved counterexample traces).  ``--mutate`` drops a named
guard to prove the checker bites; ``--check-tree`` runs the HVD506
spec<->code conformance gate; ``--witness`` replays flight-recorder
dumps through the trace witness.  ``--format json|sarif`` shares the
report shapes with the hvdlint/hvdsan emitters.
"""
from __future__ import annotations

import argparse
import json
import sys
import time

from .machines import (MUTATIONS, FailoverModel, FleetModel, GrowModel,
                       PreemptModel, ShrinkModel, ToyTornModel)
from .model import explore, render_trace

__all__ = ["main"]

PROTOCOLS = {
    "grow": GrowModel,
    "preempt": PreemptModel,
    "shrink": ShrinkModel,
    "failover": FailoverModel,
    "fleet": FleetModel,
    "toy": ToyTornModel,
}


def _explore_protocols(names, ranks, mutations, faults, max_states):
    out = []
    for name in names:
        model = PROTOCOLS[name](ranks, mutations=mutations,
                                faults=faults)
        out.append((model, explore(model, max_states=max_states)))
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis.mc",
        description="Explicit-state model checking of the elastic "
                    "membership, statesync, and recovery protocols "
                    "(see docs/analysis.md).")
    parser.add_argument("--protocol", default="all",
                        choices=("all",) + tuple(PROTOCOLS),
                        help="which protocol model to explore")
    parser.add_argument("--ranks", type=int, default=3,
                        help="incumbent world size (default 3)")
    parser.add_argument("--mutate", action="append", default=[],
                        choices=list(MUTATIONS),
                        help="drop a named spec guard (seeded-mutation "
                             "demonstration; repeatable)")
    parser.add_argument("--no-faults", action="store_true",
                        help="explore without fault injection")
    parser.add_argument("--max-states", type=int, default=400_000)
    parser.add_argument("--check-tree", nargs="?", const="horovod_tpu",
                        metavar="PATH",
                        help="run the HVD506 spec<->code conformance "
                             "gate over a tree (default horovod_tpu) "
                             "instead of exploring")
    parser.add_argument("--witness", nargs="*", default=None,
                        metavar="DUMP",
                        help="flight-recorder dumps to replay through "
                             "the trace witness")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    args = parser.parse_args(argv)

    t0 = time.monotonic()
    payload: dict = {}
    rc = 0
    findings = []
    if args.check_tree:
        from .conformance import check_tree
        findings = check_tree([args.check_tree])
        payload["conformance"] = [f.json() for f in findings]
        rc |= 1 if findings else 0
    results = []
    if not args.check_tree or args.protocol != "all" or args.mutate:
        # "all" = the real protocols; the deliberately broken toy model
        # (golden-counterexample fixture) only runs when named.
        names = ("grow", "preempt", "shrink", "failover", "fleet") \
            if args.protocol == "all" else (args.protocol,)
        if args.check_tree and args.protocol == "all" \
                and not args.mutate:
            names = ()
        results = _explore_protocols(
            names, args.ranks, tuple(args.mutate),
            not args.no_faults, args.max_states)
        payload["protocols"] = {
            m.name: {
                "states": r.states,
                "transitions": r.transitions,
                "fixpoint": r.fixpoint,
                "fired": sorted(r.fired),
                "violations": [
                    {"property": v.prop, "kind": v.kind,
                     "trace": render_trace(m, v).splitlines()}
                    for v in r.violations],
            } for m, r in results}
        rc |= 1 if any(r.violations or not r.fixpoint
                       for _m, r in results) else 0
    report = None
    if args.witness is not None:
        from .witness import check, load_dumps
        report = check(load_dumps(args.witness))
        payload["witness"] = {"problems": report.problems,
                              "warnings": report.warnings,
                              "observed": report.observed}
        rc |= 1 if report.problems else 0
    payload["wall_ms"] = round((time.monotonic() - t0) * 1e3, 3)

    if args.format == "json":
        print(json.dumps(payload, indent=1))
    elif args.format == "sarif":
        from ..hvdsan.san import sarif_payload
        print(json.dumps(sarif_payload(findings), indent=1))
    else:
        for f in findings:
            print(f.text())
        for m, r in results:
            mut = f" mutations={sorted(m.__dict__.get('mutations', ()))}" \
                if getattr(m, "mutations", None) else ""
            print(f"hvdmc: {m.name}: {r.states} state(s), "
                  f"{r.transitions} transition(s), "
                  f"{'fixpoint' if r.fixpoint else 'STATE CAP HIT'}, "
                  f"{len(r.violations)} violation(s){mut}")
            for v in r.violations:
                print(render_trace(m, v))
        if report is not None:
            for p in report.problems:
                print(f"hvdmc: witness: UNSOUND: {p}")
            for w in report.warnings:
                print(f"hvdmc: witness: warning: {w}")
            print(f"hvdmc: witness: {sum(report.observed.values())} "
                  f"protocol event(s) replayed "
                  f"({len(report.observed)} kind(s))")
        if args.check_tree:
            print(f"hvdmc: conformance: {len(findings)} finding(s) "
                  f"in {args.check_tree}", file=sys.stderr)
        print(f"hvdmc: wall {payload['wall_ms']:.1f} ms",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
