"""hvdmc protocol-spec DSL — declarative state machines for the
distributed membership/recovery protocols.

A :class:`ProtocolSpec` names, for one protocol:

- the **roles** (``incumbent``/``joiner``/``donor``/``survivor``/...),
  each with its own finite state set;
- the **message verbs** the protocol puts on a wire or a KV scope
  (:class:`Verb`): frame verbs carry the code constant they correspond
  to (``STATE_HELLO`` in ``common/tcp_transport.py``), KV verbs carry
  the record-key prefix (``join:``), flag verbs name fields of the
  step-boundary allgather exchange;
- the **transitions** (:class:`Transition`): ``(role, src state, event,
  dst state)`` plus the *guard* that must hold (named so a seeded
  mutation can drop it), the code the transition **binds** to
  (``statesync.service::StateSyncService._transition_grow`` — function
  keys in the hvdsan call-graph naming scheme), the terminal call names
  the bound code must contain (``requires_calls``), and the
  flight-recorder event kind the transition emits (``observe``) so the
  runtime trace witness can replay observed event logs against the
  model.

Three consumers share one spec:

1. the **conformance pass** (HVD506, :mod:`.conformance`) diffs verbs
   and handler transitions against the implementation ASTs — drift in
   either direction is a lint error;
2. the **model checker** (:mod:`.machines` + :mod:`.model`) explores an
   executable N-rank model whose transition labels are spec transition
   ids, so counterexample traces annotate with the bound code sites;
3. the **trace witness** (:mod:`.witness`) maps observed flight-event
   kinds back to transitions via ``observe`` and fails CI when an
   observed protocol event has no transition in the model.

The DSL is declarative on purpose: specs never import the runtime, so
``python -m horovod_tpu.analysis.mc`` runs on a checkout with no JAX.
"""
from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["ProtocolSpec", "Transition", "Verb"]


@dataclass(frozen=True)
class Verb:
    """One message verb of a protocol.

    ``kind`` is where the verb lives: ``frame`` = a STATE_MAGIC wire
    frame kind (``const`` names the code constant, ``defined_in`` the
    module path suffix that must define it), ``kv`` = a rendezvous-KV
    record (``const`` is the key prefix the code writes/waits on),
    ``flag`` = a field of the step-boundary allgather exchange.
    """
    name: str
    kind: str = "frame"          # frame | kv | flag
    const: str = ""              # code constant name / kv key prefix
    defined_in: str = ""         # path suffix defining the constant
    doc: str = ""


@dataclass(frozen=True)
class Transition:
    """One edge of a role's protocol state machine."""
    tid: str                     # unique id, e.g. "inc.boundary-grow"
    role: str
    src: str
    dst: str
    event: str                   # "send:V" | "recv:V" | "kv:V" |
    #                              "boundary" | "internal:X" | "fault:X"
    guard: str = ""              # named guard (mutations drop by name)
    binds: tuple = ()            # hvdsan function keys the edge maps to
    requires_calls: tuple = ()   # terminal call names the binding needs
    observe: str = ""            # flight-event kind this edge emits
    doc: str = ""


@dataclass(frozen=True)
class ProtocolSpec:
    name: str
    doc: str
    roles: tuple
    states: dict                 # role -> tuple of state names
    verbs: tuple = ()
    transitions: tuple = ()
    # Module labels (hvdsan naming) whose presence in an analyzed set
    # activates the conformance pass for this spec — single-fixture lint
    # runs never see tree-wide drift errors.
    anchor_modules: tuple = ()
    properties: dict = field(default_factory=dict)   # name -> prose

    # -- validation ------------------------------------------------------
    def validate(self) -> list[str]:
        """Structural self-check; returns problem strings (empty = OK)."""
        problems = []
        seen: set = set()
        verb_names = {v.name for v in self.verbs}
        for t in self.transitions:
            if t.tid in seen:
                problems.append(f"duplicate transition id {t.tid!r}")
            seen.add(t.tid)
            if t.role not in self.roles:
                problems.append(f"{t.tid}: unknown role {t.role!r}")
                continue
            states = set(self.states.get(t.role, ()))
            for s in (t.src, t.dst):
                if s not in states:
                    problems.append(
                        f"{t.tid}: state {s!r} not declared for role "
                        f"{t.role!r}")
            head, _, rest = t.event.partition(":")
            if head in ("send", "recv", "kv") and rest not in verb_names:
                problems.append(
                    f"{t.tid}: event verb {rest!r} not in the spec "
                    f"vocabulary")
            elif head not in ("send", "recv", "kv", "boundary",
                              "internal", "fault"):
                problems.append(f"{t.tid}: malformed event {t.event!r}")
        return problems

    # -- lookups ---------------------------------------------------------
    def transition(self, tid: str) -> Transition | None:
        for t in self.transitions:
            if t.tid == tid:
                return t
        return None

    def transitions_for(self, role: str) -> tuple:
        return tuple(t for t in self.transitions if t.role == role)

    def guards(self) -> frozenset:
        return frozenset(t.guard for t in self.transitions if t.guard)

    def observed_map(self) -> dict:
        """flight-event kind -> tuple of transition ids emitting it."""
        out: dict = {}
        for t in self.transitions:
            if t.observe:
                out.setdefault(t.observe, []).append(t.tid)
        return {k: tuple(v) for k, v in out.items()}

    def role_adjacency(self, role: str) -> dict:
        """state -> set of states one transition away (witness replay
        uses the reflexive-transitive closure for per-rank ordering)."""
        adj: dict = {s: set() for s in self.states.get(role, ())}
        for t in self.transitions_for(role):
            adj.setdefault(t.src, set()).add(t.dst)
        return adj

    def role_reachability(self, role: str) -> dict:
        """state -> every state reachable through >= 0 transitions."""
        adj = self.role_adjacency(role)
        reach: dict = {}
        for s in adj:
            seen = {s}
            stack = [s]
            while stack:
                for n in adj.get(stack.pop(), ()):
                    if n not in seen:
                        seen.add(n)
                        stack.append(n)
            reach[s] = seen
        return reach
