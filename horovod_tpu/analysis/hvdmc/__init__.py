"""hvdmc — explicit-state model checking of the elastic membership,
statesync, and recovery protocols (ISSUE 11; docs/analysis.md).

Four pieces close the loop between the distributed state machines and
their implementation:

- a declarative **protocol-spec DSL** (:mod:`.spec`) with specs
  co-located next to the code they bind to (``statesync/specs.py``,
  ``resilience/specs.py``);
- a **spec<->code conformance pass** (:mod:`.conformance`, rule
  HVD506) diffing message vocabularies and handler transitions against
  the implementation ASTs, riding the same single-parse driver as
  hvdsan (``lint --san``) and gated in CI via
  ``python -m horovod_tpu.analysis.mc --check-tree``;
- an **explicit-state model checker** (:mod:`.model` +
  :mod:`.machines`): BFS over N-rank global states with fault
  transitions injected at every step (crash, SIGTERM mid-grace,
  boundary-flag drop, chunk corruption, donor/joiner death
  mid-stream), verifying no stuck state, no torn snapshot commit,
  boundary agreement, and join-completes-or-aborts-cleanly, printing
  counterexamples as rank-interleaved traces annotated with the code
  sites the specs bind to;
- a **trace witness** (:mod:`.witness`): mp batteries and
  flight-recorder dumps replay their observed membership events
  against the model — an observed transition absent from the model
  fails CI (unsound spec), model transitions never observed demote to
  warnings.
"""
from .conformance import all_specs, check_tree  # noqa: F401
from .machines import (MUTATIONS, FleetModel, GrowModel,  # noqa: F401
                       PreemptModel, ShrinkModel, ToyTornModel)
from .model import explore, render_trace  # noqa: F401
from .spec import ProtocolSpec, Transition, Verb  # noqa: F401
from .witness import check as witness_check  # noqa: F401
from .witness import load_dumps  # noqa: F401
