"""hvdmc trace witness — replay observed event logs against the model.

The hvdsan runtime witness closed the lock-graph soundness loop from
the runtime side; this is the same mold for the *protocol* models: the
statesync mp batteries and any flight-recorder dump carry the
membership events each rank actually emitted (``grow``, ``departed``,
``sigterm-grace``, ``donate``, ``join-*``, ``shrink*``,
``torn-reject``), and :func:`check` replays them against the specs and
the explored models:

- an observed **protocol** event kind that no spec transition claims is
  an **unsound spec** — the implementation runs a transition the model
  never explores — and fails CI (``problems``);
- an observed kind whose claimed transitions were never **fired** by
  the explored model is equally unsound (the spec names it, the
  semantics never reach it);
- two consecutive events of one rank that map into the same spec role
  must be **orderable** there (the second transition's source state
  reachable from the first's target) — a cheap per-rank replay;
- spec transitions with observable kinds that no dump ever exercised
  demote to **warnings** (coverage gaps, the hvdsan demotion contract).

Generic data-plane flight kinds (enqueue/dispatch/done/...) are not
protocol events and are ignored; a NEW membership-flavored kind must be
claimed by a spec before the batteries will pass.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field

__all__ = ["GENERIC_KINDS", "WitnessReport", "check", "load_dumps",
           "protocol_kinds"]

# Flight-event kinds of the generic data plane / observability layers —
# never protocol transitions (telemetry/flight.py taxonomy).
GENERIC_KINDS = frozenset({
    "enqueue", "dispatch", "done", "error", "ranks-failed",
    "fingerprint-divergence", "sigterm", "lock-order", "mark-failed",
    "deadline-convert", "autoscale",
})


@dataclass
class WitnessReport:
    problems: list = field(default_factory=list)   # unsound: fail CI
    warnings: list = field(default_factory=list)   # coverage gaps
    observed: dict = field(default_factory=dict)   # kind -> count

    @property
    def ok(self) -> bool:
        return not self.problems


def protocol_kinds(specs=None) -> dict:
    """flight-event kind -> [(spec, transition)] across the specs."""
    from .conformance import all_specs

    out: dict = {}
    for sp in (all_specs() if specs is None else specs):
        for t in sp.transitions:
            if t.observe:
                out.setdefault(t.observe, []).append((sp, t))
    return out


def load_dumps(paths) -> list:
    payloads = []
    for p in paths:
        with open(p) as f:
            payloads.append(json.load(f))
    return payloads


def _fired_tids(specs) -> set:
    """Union of transition ids the head models actually fire."""
    from .machines import FleetModel, GrowModel, PreemptModel, ShrinkModel
    from .model import explore

    fired: set = set()
    for m in (GrowModel(3), PreemptModel(3), ShrinkModel(3),
              FleetModel(2)):
        fired |= explore(m).fired
    return fired


def check(payloads, specs=None, fired: set | None = None
          ) -> WitnessReport:
    """Replay flight dumps (``{"rank":..,"events":[{"kind":..},..]}``)
    against the specs + explored models."""
    from .conformance import all_specs

    specs = all_specs() if specs is None else specs
    kinds = protocol_kinds(specs)
    if fired is None:
        fired = _fired_tids(specs)
    report = WitnessReport()
    reach_cache: dict = {}
    for payload in payloads:
        rank = payload.get("rank", "?")
        prev_by_role: dict = {}
        for ev in payload.get("events", []):
            kind = ev.get("kind", "")
            if kind in GENERIC_KINDS:
                continue
            claimed = kinds.get(kind)
            if claimed is None:
                report.problems.append(
                    f"rank {rank}: observed protocol event "
                    f"{kind!r} ({ev.get('name', '')}) has no "
                    f"transition in any spec — the implementation "
                    f"runs a transition the model never explores "
                    f"(unsound spec)")
                continue
            report.observed[kind] = report.observed.get(kind, 0) + 1
            if not any(t.tid in fired for _sp, t in claimed):
                report.problems.append(
                    f"rank {rank}: observed event {kind!r} maps to "
                    f"transition(s) "
                    f"{[t.tid for _sp, t in claimed]} that the "
                    f"explored model never fires — the spec names a "
                    f"transition its semantics cannot reach")
            for sp, t in claimed[:1]:
                key = (sp.name, t.role)
                prev = prev_by_role.get(key)
                prev_by_role[key] = t
                if prev is None:
                    continue
                reach = reach_cache.get(key)
                if reach is None:
                    reach = reach_cache[key] = \
                        sp.role_reachability(t.role)
                if t.src not in reach.get(prev.dst, {prev.dst}):
                    report.problems.append(
                        f"rank {rank}: observed {prev.observe!r} then "
                        f"{kind!r}, but {t.tid} is not reachable "
                        f"after {prev.tid} in {sp.name} role "
                        f"{t.role} — the observed order contradicts "
                        f"the spec")
    for kind, claimed in sorted(kinds.items()):
        if kind not in report.observed:
            report.warnings.append(
                f"spec transition(s) {[t.tid for _sp, t in claimed]} "
                f"(kind {kind!r}) never observed in any replayed "
                f"dump — model state demoted to a coverage warning")
    report.problems.sort()
    return report
