"""Executable protocol semantics for the hvdmc model checker.

Each machine is a :class:`~.model.Model` whose transition labels are
spec transition ids (``statesync/specs.py``, ``resilience/specs.py``),
so counterexample traces annotate with the code sites the specs bind
to, and the runtime trace witness can ask "was this observed transition
ever fired by the model?".

Abstraction choices (documented, deliberate):

- training state never appears — only the **step/boundary counter**
  (saturating at a small cap so the space closes) and the **snapshot
  stamps** donors cut at;
- the byte stream is abstracted to per-donor stamp + pull/verify
  phases; chunk CRCs appear as the ``chunk-crc`` guard against the
  injected ``chunk-corrupt`` fault;
- fault injection is adversarial **against the protocol**, not the
  transport: the boundary flag exchange may drop one rank's receipt
  (``flag-drop`` — the torn-snapshot hazard the stamp-equality guard
  contains), chunks may corrupt, donor threads and the joiner may die
  mid-stream, SIGTERM may land mid-grace and the in-flight step may
  wedge past the grace window.

Seeded **mutations** (``--mutate``) drop a named guard so CI can prove
the checker bites:

- ``drop-torn-reject`` — the joiner commits a round even when donor
  stamps disagree (kills the ``stamps-unanimous`` guard);
- ``early-ready-ack`` — the joiner posts ``ready`` before the bulk
  image digest-verifies (kills the ``ready-after-verify`` guard);
- ``accept-stale-lease`` — a rendezvous primary resumed after a lease
  lapse keeps serving without re-reading the log (kills the
  ``epoch-fence`` guard): the checker answers with a two-leaders +
  lost-committed-write counterexample (FailoverModel);
- ``swap-before-verify`` — a serving replica stages a pulled weight
  snapshot without digest-verifying it (kills the
  ``verify-before-stage`` guard): the shard-corrupt fault then drives
  a corrupt image through the boundary swap (FleetModel).
"""
from __future__ import annotations

from .model import Model

__all__ = ["FailoverModel", "FleetModel", "GrowModel", "MUTATIONS",
           "PreemptModel", "ShrinkModel", "ToyTornModel", "toy_spec"]

MUTATIONS = ("drop-torn-reject", "early-ready-ack",
             "accept-stale-lease", "swap-before-verify")

_SEQ_CAP = 4


def _repl(t: tuple, i: int, v) -> tuple:
    return t[:i] + (v,) + t[i + 1:]


# ---------------------------------------------------------------------------
# Grow protocol: N incumbents + one joiner
# ---------------------------------------------------------------------------
# Incumbent: (ph, pj, rs, ds)  ph R=run B=bound W=rebuild F=failed;
#            pj/rs = watcher saw join/ready; ds = donor snapshot stamp
#            (boundary seq it cut at; -1 = not donating).
# Joiner: (jph, metas, verified, corrupted)
#            jph I=idle A=announced M=metas P=pulling D=pulled
#            V=verified Y=ready G=final Q=final-verified E=entered
#            X=aborted C=crashed; metas = per-donor stamps collected.
# kv: (join_posted, ready_posted, go_posted)
# faults: (flagdrop, corrupt, donordeath, joinercrash) budgets +
#         dead = frozenset of dead donor threads.
# world: (seq, final_stamp, done)
class GrowModel(Model):
    name = "statesync-grow"

    def __init__(self, ranks: int = 3, mutations=(), *,
                 faults: bool = True) -> None:
        from ...resilience.specs import shrink_spec
        from ...statesync.specs import grow_spec, stream_spec

        self.n = int(ranks)
        self.mutations = frozenset(mutations)
        unknown = self.mutations - set(MUTATIONS)
        if unknown:
            raise ValueError(f"unknown mutation(s) {sorted(unknown)}; "
                             f"known: {list(MUTATIONS)}")
        self.spec = (grow_spec(), stream_spec(), shrink_spec())
        b = 1 if faults else 0
        self._fault_budget = (b, b, b, b)

    def initial(self):
        incs = tuple(("R", False, False, -1) for _ in range(self.n))
        joiner = ("I", (), False, False)
        return (incs, joiner, (False, False, False),
                (self._fault_budget, frozenset()), (0, -1, False))

    # -- helpers ---------------------------------------------------------
    @staticmethod
    def _live(incs):
        return [i for i, (ph, *_r) in enumerate(incs) if ph != "F"]

    def actor_label(self, actor):
        if actor == "J":
            return "joiner"
        return super().actor_label(actor)

    def describe(self, state) -> str:
        incs, joiner, kv, faults, world = state
        jph, metas, verified, corrupted = joiner
        seq, fstamp, done = world
        inc_s = " ".join(
            f"r{i}:{ph}{'' if ds < 0 else f'/ds{ds}'}"
            f"{'+pj' if pj else ''}{'+rs' if rs else ''}"
            for i, (ph, pj, rs, ds) in enumerate(incs))
        kv_s = "".join(k for k, v in
                       zip(("J", "R", "G"), kv) if v) or "-"
        return (f"seq={seq} incs[{inc_s}] joiner={jph}"
                f"{f'/metas{list(metas)}' if metas else ''}"
                f"{'+ver' if verified else ''}"
                f"{'+corrupt' if corrupted else ''} kv={kv_s}"
                f"{' DONE' if done else ''}")

    # -- properties ------------------------------------------------------
    def invariants(self, state):
        incs, joiner, kv, faults, world = state
        jph, metas, verified, corrupted = joiner
        out = []
        if jph == "E" and len(set(metas)) > 1:
            out.append("torn-commit")
        if any(ph == "W" for ph, *_r in incs) and not verified:
            out.append("premature-boundary-ack")
        if kv[2] and any(ph in "RB" for ph, *_r in incs) \
                and not world[2]:
            out.append("boundary-agreement")
        return out

    def is_terminal(self, state) -> bool:
        incs, joiner, kv, faults, world = state
        if world[2]:
            return True
        return all(ph == "F" for ph, *_r in incs) and \
            joiner[0] in "XC"

    def resolved(self, state) -> bool:
        incs, joiner, kv, faults, world = state
        if world[2]:
            return True
        if joiner[0] in "XC":
            return not any(ph == "W" for ph, *_r in incs) or \
                all(ph == "F" for ph, *_r in incs)
        return False

    # -- semantics -------------------------------------------------------
    def successors(self, state):
        incs, joiner, kv, faults, world = state
        jph, metas, verified, corrupted = joiner
        join_p, ready_p, go_p = kv
        budgets, dead = faults
        flagdrop, corrupt, donordeath, jcrash = budgets
        seq, fstamp, done = world
        if self.is_terminal(state):
            return []
        out = []
        live = self._live(incs)

        def st(incs=incs, joiner=joiner, kv=kv, faults=(budgets, dead),
               world=world):
            return (incs, joiner, kv, faults, world)

        # -- incumbent local steps --------------------------------------
        for i in live:
            ph, pj, rs, ds = incs[i]
            if ph == "R":
                out.append((i, ("inc.step",),
                            st(incs=_repl(incs, i, ("B", pj, rs, ds)))))
            if ph in "RB":
                if join_p and not pj and ds < 0:
                    out.append((i, ("inc.watch-join",),
                                st(incs=_repl(incs, i,
                                              (ph, True, rs, ds)))))
                if ready_p and not rs:
                    out.append((i, ("inc.watch-ready",),
                                st(incs=_repl(incs, i,
                                              (ph, pj, True, ds)))))

        # -- the step boundary (one symmetric exchange) -----------------
        if live and all(incs[i][0] == "B" for i in live) and \
                not any(ph == "W" for ph, *_r in incs):
            seq2 = min(seq + 1, _SEQ_CAP)
            rs_any = any(incs[i][2] for i in live)
            pj_any = any(incs[i][1] for i in live)
            if rs_any:
                # grow: final boundary snapshot + GO record + rebuild.
                grown = tuple(
                    ("W", False, False, seq) if i in live else incs[i]
                    for i in range(self.n))
                out.append(("world",
                            ("inc.boundary-grow", "inc.post-go"),
                            st(incs=grown, kv=(join_p, ready_p, True),
                               world=(seq2, seq, done))))
            elif pj_any and any(incs[i][3] < 0 for i in live):
                def admit(skip=None):
                    return tuple(
                        (("R", False, incs[i][2],
                          seq if incs[i][3] < 0 and i != skip
                          else incs[i][3])
                         if i in live and i != skip else
                         (("R",) + incs[i][1:] if i in live
                          else incs[i]))
                        for i in range(self.n))
                out.append(("world", ("inc.boundary-admit",),
                            st(incs=admit(), world=(seq2, fstamp,
                                                    done))))
                if flagdrop > 0:
                    nb = (flagdrop - 1, corrupt, donordeath, jcrash)
                    for x in live:
                        if incs[x][3] >= 0:
                            continue
                        out.append((
                            "net",
                            ("net.flag-drop", "inc.boundary-admit"),
                            st(incs=admit(skip=x), faults=(nb, dead),
                               world=(seq2, fstamp, done))))
            else:
                idled = tuple(
                    ("R",) + incs[i][1:] if i in live else incs[i]
                    for i in range(self.n))
                out.append(("world", ("inc.boundary-idle",),
                            st(incs=idled,
                               world=(seq2, fstamp, done))))

        # -- joiner ------------------------------------------------------
        alive_donors = [i for i in live if i not in dead]
        if jph == "I":
            out.append(("J", ("join.announce",),
                        st(joiner=("A", metas, verified, corrupted),
                           kv=(True, ready_p, go_p))))
        elif jph == "A":
            if live and all(incs[i][3] >= 0 for i in live):
                collected = tuple(incs[i][3] for i in live)
                out.append(("J", ("join.hello", "join.meta"),
                            st(joiner=("M", collected, verified,
                                       corrupted))))
        elif jph == "M":
            torn = len(set(metas)) > 1
            if torn and "drop-torn-reject" not in self.mutations:
                out.append(("J", ("join.torn-reject",),
                            st(joiner=("X", metas, verified,
                                       corrupted))))
            else:
                out.append(("J", ("join.stamps-ok",),
                            st(joiner=("P", metas, verified,
                                       corrupted))))
        elif jph == "P":
            if corrupted:
                out.append(("J", ("join.crc-reject",),
                            st(joiner=("X", metas, verified, True))))
            elif alive_donors:
                out.append(("J", ("join.req", "join.data", "join.end"),
                            st(joiner=("D", metas, verified, False))))
            else:
                out.append(("J", ("join.bulk-abort",),
                            st(joiner=("X", metas, verified,
                                       corrupted))))
            if corrupt > 0 and not corrupted:
                nb = (flagdrop, corrupt - 1, donordeath, jcrash)
                out.append(("net", ("net.chunk-corrupt",),
                            st(joiner=("P", metas, verified, True),
                               faults=(nb, dead))))
            if donordeath > 0:
                nb = (flagdrop, corrupt, donordeath - 1, jcrash)
                for d in alive_donors:
                    out.append(("net",
                                ("net.donor-death", "join.donor-died"),
                                st(faults=(nb, dead | {d}))))
        elif jph == "D":
            out.append(("J", ("join.verify",),
                        st(joiner=("V", metas, True, corrupted))))
        elif jph == "G":
            if corrupt > 0:
                nb = (flagdrop, corrupt - 1, donordeath, jcrash)
                out.append(("net", ("net.chunk-corrupt",
                                    "join.final-abort"),
                            st(joiner=("X", metas, verified, True),
                               faults=(nb, dead))))
            out.append(("J", ("join.data", "join.end", "join.verify"),
                        st(joiner=("Q", metas, verified, corrupted))))
        elif jph == "Q":
            if live and all(incs[i][0] == "W" for i in live):
                out.append(("J", ("join.enter",),
                            st(joiner=("E", metas, verified,
                                       corrupted))))
        if jph == "V" and not ready_p:
            out.append(("J", ("join.post-ready", "join.bye"),
                        st(joiner=("Y", metas, verified, corrupted),
                           kv=(join_p, True, go_p))))
        if "early-ready-ack" in self.mutations and jph in "PD" \
                and not ready_p:
            # MUTATED: ready acked before the digest verified.
            out.append(("J", ("join.post-ready",),
                        st(kv=(join_p, True, go_p))))
        if jph in "VY" and ready_p and go_p:
            out.append(("J", ("join.see-go",),
                        st(joiner=("G", metas, verified, corrupted))))
        if jcrash > 0 and jph in "AMPDVYGQ":
            nb = (flagdrop, corrupt, donordeath, jcrash - 1)
            out.append(("net", ("net.crash-joiner",),
                        st(joiner=("C", metas, verified, corrupted),
                           faults=(nb, dead))))

        # -- abort cleanup ----------------------------------------------
        if jph in "XC":
            if any(incs[i][0] == "W" for i in live):
                failed = tuple(
                    ("F", False, False, -1)
                    if incs[i][0] == "W" else incs[i]
                    for i in range(self.n))
                out.append(("world", ("inc.formation-timeout",),
                            st(incs=failed)))
            elif any(incs[i][3] >= 0 for i in live) or join_p or \
                    ready_p:
                cleared = tuple(
                    (incs[i][0], False, False, -1) if i in live
                    else incs[i] for i in range(self.n))
                out.append(("world", ("donor.round-timeout",),
                            st(incs=cleared,
                               kv=(False, False, go_p))))

        # -- world formation --------------------------------------------
        if jph == "E" and live and \
                all(incs[i][0] == "W" for i in live):
            formed = tuple(
                ("R", False, False, -1) if i in live else incs[i]
                for i in range(self.n))
            out.append(("world", ("inc.world-formed",),
                        st(incs=formed,
                           world=(seq, fstamp, True))))
        return out


# ---------------------------------------------------------------------------
# Preemption grace: N ranks, SIGTERM lands on one of them
# ---------------------------------------------------------------------------
# Rank: (ph, pre)  ph R=run B=bound Z=wedged D=departed(0)
#                  T=exited143 F=failcaught; pre = SIGTERM received.
# kv: (bye, confirmed); faults: (sigterm, dup, wedge) budgets;
# world: (seq, gen).
class PreemptModel(Model):
    name = "statesync-preempt"

    def __init__(self, ranks: int = 3, mutations=(), *,
                 faults: bool = True) -> None:
        from ...resilience.specs import shrink_spec
        from ...statesync.specs import preempt_spec

        self.n = int(ranks)
        self.mutations = frozenset(mutations)
        self.spec = (preempt_spec(), shrink_spec())
        self._budget = (1, 1, 1) if faults else (0, 0, 0)

    def initial(self):
        return (tuple(("R", False) for _ in range(self.n)),
                (False, False), self._budget, (0, 0))

    def describe(self, state) -> str:
        ranks, kv, faults, world = state
        rs = " ".join(f"r{i}:{ph}{'!' if pre else ''}"
                      for i, (ph, pre) in enumerate(ranks))
        return (f"seq={world[0]} gen={world[1]} [{rs}]"
                f"{' bye' if kv[0] else ''}"
                f"{' confirmed' if kv[1] else ''}")

    @staticmethod
    def _victim(ranks):
        for i, (ph, pre) in enumerate(ranks):
            if pre or ph in "ZDT":
                return i
        return -1

    def invariants(self, state):
        ranks, kv, faults, world = state
        bye, confirmed = kv
        out = []
        v = self._victim(ranks)
        if v >= 0 and ranks[v][0] in "DT" and not bye:
            out.append("bye-before-exit")
        if v >= 0 and ranks[v][0] == "D" and \
                any(ph == "F" for ph, _ in ranks):
            out.append("no-failure-on-clean-path")
        if world[1] == 1 and not (bye or confirmed):
            out.append("shrink-requires-evidence")
        return out

    def resolved(self, state) -> bool:
        ranks, kv, faults, world = state
        v = self._victim(ranks)
        if v < 0:
            return True
        return world[1] == 1 and ranks[v][0] in "DT"

    def successors(self, state):
        ranks, kv, faults, world = state
        bye, confirmed = kv
        sig, dup, wedge = faults
        seq, gen = world
        out = []
        v = self._victim(ranks)
        live = [i for i, (ph, _p) in enumerate(ranks) if ph not in "DT"]

        def st(ranks=ranks, kv=kv, faults=faults, world=world):
            return (ranks, kv, faults, world)

        for i in live:
            ph, pre = ranks[i]
            if ph == "R":
                tid = "pre.finish-step" if pre else "sur.step"
                out.append((i, (tid,),
                            st(ranks=_repl(ranks, i, ("B", pre)))))
            if sig > 0 and v < 0 and ph in "RB":
                out.append((i, ("pre.sigterm",),
                            st(ranks=_repl(ranks, i, (ph, True)),
                               faults=(0, dup, wedge))))
            if pre and dup > 0 and ph in "RBZ":
                out.append((i, ("pre.sigterm-dup",),
                            st(faults=(sig, dup - 1, wedge))))
            if pre and wedge > 0 and ph == "R":
                out.append((i, ("pre.wedge",),
                            st(ranks=_repl(ranks, i, ("Z", pre)),
                               faults=(sig, dup, 0))))
            if ph == "Z":
                out.append((i, ("pre.backstop",),
                            st(ranks=_repl(ranks, i, ("T", pre)),
                               kv=(True, confirmed))))
            if ph == "B" and gen == 0 and v >= 0 and \
                    ranks[v][0] in "ZT":
                out.append((i, ("sur.deadline-fail",),
                            st(ranks=_repl(ranks, i, ("F", pre)))))
            if ph == "F" and gen == 0:
                if ranks[v][0] == "Z" and not confirmed:
                    out.append((i, ("sur.reraise-suspect",),
                                st(ranks=_repl(ranks, i, ("B", pre)))))

        if v >= 0 and ranks[v][0] == "T" and not confirmed:
            out.append((v, ("hb.confirm",), st(kv=(bye, True))))

        # boundary: every live rank bound; a wedged peer blocks it, and
        # a backstop-exited peer makes the collective FAIL (deadline
        # conversion), never complete — no boundary until the shrink.
        if live and all(ranks[i][0] == "B" for i in live) and \
                (v < 0 or ranks[v][0] == "B" or gen == 1):
            seq2 = min(seq + 1, 3)
            if any(ranks[i][1] for i in live):
                nr = tuple(
                    ("D", pre) if pre else
                    (("R", pre) if i in live else (ph2, pre))
                    for i, (ph2, pre) in enumerate(ranks))
                out.append(("world",
                            ("pre.depart", "pre.fast-donate",
                             "sur.proactive-shrink"),
                            st(ranks=nr, kv=(True, confirmed),
                               world=(seq2, 1))))
            else:
                nr = tuple(("R", pre) if i in live else ranks[i]
                           for i in range(self.n))
                out.append(("world", ("sur.boundary-idle",),
                            st(ranks=nr, world=(seq2, gen))))

        # failure-shrink convergence (backstop path)
        survivors = [i for i in live if i != v]
        if v >= 0 and gen == 0 and survivors and confirmed and \
                all(ranks[i][0] == "F" for i in survivors):
            nr = tuple(("R", pre) if i in survivors else ranks[i]
                       for i, (_ph, pre) in enumerate(ranks))
            out.append(("world", ("sur.converge-shrink",),
                        st(ranks=nr, world=(seq, 1))))
        return out


# ---------------------------------------------------------------------------
# Hard-failure shrink convergence
# ---------------------------------------------------------------------------
# Rank: (ph, v)  ph R C=crashed Z=frozen F=failcaught K=converging
#                S=shrunk X=raised; v = state version at the catch.
class ShrinkModel(Model):
    name = "resilience-shrink"

    def __init__(self, ranks: int = 3, mutations=(), *,
                 faults: bool = True) -> None:
        from ...resilience.specs import shrink_spec

        self.n = int(ranks)
        self.mutations = frozenset(mutations)
        self.spec = (shrink_spec(),)
        self._faults = faults

    def initial(self):
        return (tuple(("R", 0) for _ in range(self.n)),
                False, -1, "", False)

    def describe(self, state) -> str:
        ranks, confirmed, victim, kind, done = state
        rs = " ".join(f"r{i}:{ph}v{v}" for i, (ph, v) in
                      enumerate(ranks))
        return (f"[{rs}] victim={victim}({kind or '-'})"
                f"{' confirmed' if confirmed else ''}"
                f"{' DONE' if done else ''}")

    def invariants(self, state):
        ranks, confirmed, victim, kind, done = state
        out = []
        if kind == "freeze" and any(ph == "S" for ph, _v in ranks):
            out.append("never-shrink-live")
        if any(ph == "S" for ph, _v in ranks) and not confirmed:
            out.append("shrink-requires-confirmation")
        if done:
            vs = {v for ph, v in ranks if ph == "R"}
            if len(vs) > 1:
                out.append("resync-equal")
        return out

    def is_terminal(self, state) -> bool:
        ranks, confirmed, victim, kind, done = state
        if done:
            return True
        survivors = [i for i in range(self.n) if i != victim]
        return victim >= 0 and \
            all(ranks[i][0] == "X" for i in survivors)

    def resolved(self, state) -> bool:
        return self.is_terminal(state)

    def successors(self, state):
        ranks, confirmed, victim, kind, done = state
        if self.is_terminal(state):
            return []
        out = []

        def st(ranks=ranks, confirmed=confirmed, victim=victim,
               kind=kind, done=done):
            return (ranks, confirmed, victim, kind, done)

        if victim < 0:
            if self._faults:
                for r in range(self.n):
                    out.append((r, ("vic.crash",),
                                st(ranks=_repl(ranks, r, ("C", 0)),
                                   victim=r, kind="crash")))
                    out.append((r, ("vic.freeze",),
                                st(ranks=_repl(ranks, r, ("Z", 0)),
                                   victim=r, kind="freeze")))
            # no fault chosen: quiescent world — allowed terminal.
            if not out:
                return []
            return out
        survivors = [i for i in range(self.n) if i != victim]
        if kind == "crash" and not confirmed:
            out.append((victim, ("hb.confirm",), st(confirmed=True)))
        for i in survivors:
            ph, v = ranks[i]
            if ph == "R":
                for nv in (v, min(v + 1, 1)):
                    out.append((i, ("sur.fail",),
                                st(ranks=_repl(ranks, i, ("F", nv)))))
            elif ph == "F":
                out.append((i, ("sur.converge-poll",),
                            st(ranks=_repl(ranks, i, ("K", v)))))
            elif ph == "K" and kind == "freeze":
                out.append((i, ("sur.reraise-suspect",),
                            st(ranks=_repl(ranks, i, ("X", v)))))
        if confirmed and all(ranks[i][0] == "K" for i in survivors):
            nr = tuple(("S", v) if i in survivors else (ph, v)
                       for i, (ph, v) in enumerate(ranks))
            out.append(("world", ("sur.confirm-shrink",), st(ranks=nr)))
        if survivors and all(ranks[i][0] == "S" for i in survivors):
            vmax = max(ranks[i][1] for i in survivors)
            nr = tuple(("R", vmax) if i in survivors else ranks[i]
                       for i in range(self.n))
            out.append(("world", ("sur.resync",),
                        st(ranks=nr, done=True)))
        return out


# ---------------------------------------------------------------------------
# Rendezvous leader failover: N replicas + one client (runner/specs.py)
# ---------------------------------------------------------------------------
# Replica: (role, epoch)  role P=leading Z=paused S=tailing C=candidate
#          D=dead; epoch = the reign the replica believes is current.
# log: current epoch (the last leader record's epoch).
# writes: per acked write (epoch_at_append, log_epoch_at_append) — a
#         write is LOST when a later replay fences it (appended with an
#         epoch older than the log's reigning epoch at append time).
# client: (target replica, acked count).
# faults: (kill, pause) budgets.
class FailoverModel(Model):
    name = "rendezvous-failover"

    _WRITES = 2                    # client is done after 2 acked writes
    _EPOCH_CAP = 6

    def __init__(self, ranks: int = 3, mutations=(), *,
                 faults: bool = True) -> None:
        from ...runner.specs import failover_spec

        self.n = max(2, int(ranks))
        self.mutations = frozenset(mutations)
        unknown = self.mutations - set(MUTATIONS)
        if unknown:
            raise ValueError(f"unknown mutation(s) {sorted(unknown)}; "
                             f"known: {list(MUTATIONS)}")
        self.spec = (failover_spec(),)
        self._budget = (1, 1) if faults else (0, 0)

    def initial(self):
        replicas = (("P", 1),) + tuple(
            ("S", 1) for _ in range(self.n - 1))
        return (replicas, 1, (), (0, 0), self._budget)

    def describe(self, state) -> str:
        replicas, log_epoch, writes, client, faults = state
        rs = " ".join(f"r{i}:{role}e{ep}"
                      for i, (role, ep) in enumerate(replicas))
        ws = " ".join(f"w{i}@e{we}/log{ce}"
                      for i, (we, ce) in enumerate(writes))
        return (f"log=e{log_epoch} [{rs}] client->r{client[0]} "
                f"acked={client[1]}{f' [{ws}]' if ws else ''}")

    def _leaders(self, replicas):
        return [i for i, (role, _ep) in enumerate(replicas)
                if role == "P"]

    def invariants(self, state):
        replicas, log_epoch, writes, client, faults = state
        out = []
        if len(self._leaders(replicas)) > 1:
            out.append("two-leaders")
        if any(we < ce for we, ce in writes):
            out.append("committed-write-lost")
        return out

    def is_terminal(self, state) -> bool:
        _replicas, _log, _writes, client, _faults = state
        return client[1] >= self._WRITES

    def resolved(self, state) -> bool:
        # clients-converge: every reachable state must keep a path to
        # all-writes-acked (the AG EF half of the property set).
        return self.is_terminal(state)

    def successors(self, state):
        replicas, log_epoch, writes, client, faults = state
        if self.is_terminal(state):
            return []
        target, acked = client
        kill_left, pause_left = faults
        out = []
        leaders = self._leaders(replicas)

        def st(replicas=replicas, log_epoch=log_epoch, writes=writes,
               client=client, faults=faults):
            return (replicas, log_epoch, writes, client, faults)

        # -- replica faults + lease machinery ---------------------------
        for i, (role, ep) in enumerate(replicas):
            if role == "P":
                if pause_left > 0:
                    out.append(("net", ("pri.pause",),
                                st(replicas=_repl(replicas, i,
                                                  ("Z", ep)),
                                   faults=(kill_left, pause_left - 1))))
                if kill_left > 0:
                    out.append(("net", ("pri.die",),
                                st(replicas=_repl(replicas, i,
                                                  ("D", ep)),
                                   faults=(kill_left - 1, pause_left))))
            elif role == "Z":
                if "accept-stale-lease" in self.mutations:
                    # MUTATED: resume serving without re-reading the
                    # log — the stale reign survives a promotion.
                    out.append((i, ("pri.resume-reclaim",),
                                st(replicas=_repl(replicas, i,
                                                  ("P", ep)))))
                elif log_epoch > ep:
                    out.append((i, ("pri.resume-fenced",),
                                st(replicas=_repl(replicas, i,
                                                  ("S", log_epoch)))))
                else:
                    e2 = min(log_epoch + 1, self._EPOCH_CAP)
                    out.append((i, ("pri.resume-reclaim",),
                                st(replicas=_repl(replicas, i,
                                                  ("P", e2)),
                                   log_epoch=e2)))
            elif role == "S":
                if not leaders:
                    out.append((i, ("sb.lapse",),
                                st(replicas=_repl(replicas, i,
                                                  ("C", ep)))))
            elif role == "C":
                if ep < log_epoch:
                    out.append((i, ("sb.lose",),
                                st(replicas=_repl(replicas, i,
                                                  ("S", log_epoch)))))
                elif not leaders:
                    e2 = min(log_epoch + 1, self._EPOCH_CAP)
                    out.append((i, ("sb.promote",),
                                st(replicas=_repl(replicas, i,
                                                  ("P", e2)),
                                   log_epoch=e2)))

        # -- client ------------------------------------------------------
        t_role, t_ep = replicas[target]
        if t_role == "P":
            out.append(("client", ("cli.write", "pri.commit"),
                        st(writes=writes + ((t_ep, log_epoch),),
                           client=(target, acked + 1))))
        else:
            nxt = (target + 1) % self.n
            tids = ["cli.failover"]
            if replicas[nxt][0] == "P":
                tids.append("cli.converge")
            out.append(("client", tuple(tids),
                        st(client=(nxt, acked))))
        return out

    def actor_label(self, actor):
        if actor == "client":
            return "client"
        return super().actor_label(actor)


# ---------------------------------------------------------------------------
# Fleet handoff: migration journal + continuous weight deployment
# ---------------------------------------------------------------------------
# ctl: (js, epoch, recovering)  js -=no migration P=planned D=departing
#      C=done A=aborted; recovering = a failover landed, the successor
#      must adopt the journal before anything else.
# mover: mph T=training B=boundary(directive consumed) J=joining
#        S=serving.
# joined: the mover's arrival mark is in the KV.
# pub: head version (0 = nothing published; cap 1).
# rep: (fph, fv, fok, av, aok, seen)  fph serving/fetched/staged;
#      (fv, fok) = the in-flight image and whether it matches the
#      published digest; (av, aok) = the applied (swapped) image;
#      seen = newest version staged (the puller's head watermark).
# faults: (failover, corrupt) budgets.
class FleetModel(Model):
    name = "fleet-handoff"

    def __init__(self, ranks: int = 2, mutations=(), *,
                 faults: bool = True) -> None:
        from ...fleet.specs import fleet_spec

        self.n = int(ranks)
        self.mutations = frozenset(mutations)
        unknown = self.mutations - set(MUTATIONS)
        if unknown:
            raise ValueError(f"unknown mutation(s) {sorted(unknown)}; "
                             f"known: {list(MUTATIONS)}")
        self.spec = (fleet_spec(),)
        self._budget = (1, 1) if faults else (0, 0)

    def initial(self):
        return (("-", 0, False), "T", False, 0,
                ("serving", 0, True, 0, True, 0), self._budget)

    def describe(self, state) -> str:
        (js, epoch, rec), mph, joined, head, rep, faults = state
        fph, fv, fok, av, aok, seen = rep
        inflight = (f"/v{fv}" + ("" if fok else "!corrupt")) if fv else ""
        applied = f"v{av}" + ("" if aok else "!corrupt")
        return (f"mig={js}/e{epoch}{'/recovering' if rec else ''} "
                f"mover={mph}{'+joined' if joined else ''} "
                f"head=v{head} rep={fph}{inflight} "
                f"applied={applied}")

    def invariants(self, state):
        _ctl, _mph, _joined, _head, rep, _faults = state
        _fph, _fv, _fok, av, aok, _seen = rep
        if av > 0 and not aok:
            return ["swap-verified"]
        return []

    def is_terminal(self, state) -> bool:
        (js, _e, rec), mph, joined, _head, rep, _faults = state
        fph, _fv, _fok, av, _aok, _seen = rep
        if rec:
            return False
        migration_closed = js == "A" or (js == "C" and joined)
        return migration_closed and av >= 1 and fph == "serving"

    def resolved(self, state) -> bool:
        return self.is_terminal(state)

    def successors(self, state):
        (js, epoch, rec), mph, joined, head, rep, faults = state
        fph, fv, fok, av, aok, seen = rep
        fo, co = faults
        if self.is_terminal(state):
            return []
        out = []

        def st(ctl=(js, epoch, rec), mph=mph, joined=joined, head=head,
               rep=(fph, fv, fok, av, aok, seen), faults=(fo, co)):
            return (ctl, mph, joined, head, rep, faults)

        # -- controller --------------------------------------------------
        if rec:
            # A successor controller adopts the journal before anything
            # else: planned-with-no-directive aborts, departing resumes.
            if js == "P":
                out.append(("ctl", ("ctl.abort-planned",),
                            st(ctl=("A", epoch, False))))
            else:
                out.append(("ctl", ("ctl.resume",),
                            st(ctl=("D", epoch, False))))
        elif js == "-":
            out.append(("ctl", ("ctl.observe", "ctl.plan"),
                        st(ctl=("P", epoch, False))))
        elif js == "P":
            out.append(("ctl", ("ctl.direct",),
                        st(ctl=("D", epoch, False))))
        elif js == "D" and joined:
            out.append(("ctl", ("ctl.complete",),
                        st(ctl=("C", epoch, False))))

        # -- mover -------------------------------------------------------
        if mph == "T" and js == "D" and not rec:
            out.append(("mover", ("mov.directive",), st(mph="B")))
        elif mph == "B":
            out.append(("mover", ("mov.depart",), st(mph="J")))
        elif mph == "J":
            out.append(("mover", ("mov.join",), st(mph="S")))
        elif mph == "S" and not joined:
            out.append(("mover", ("mov.arrive",), st(joined=True)))

        # -- publisher ---------------------------------------------------
        if head == 0:
            out.append(("pub", ("pub.shards", "pub.meta", "pub.head"),
                        st(head=1)))

        # -- replica -----------------------------------------------------
        if fph == "serving" and head > seen:
            out.append(("rep", ("rep.poll", "rep.fetch"),
                        st(rep=("fetched", head, True, av, aok, seen))))
            if co > 0:
                out.append(("net", ("net.shard-corrupt", "rep.poll",
                                    "rep.fetch"),
                            st(rep=("fetched", head, False, av, aok,
                                    seen),
                               faults=(fo, co - 1))))
        elif fph == "fetched":
            if "swap-before-verify" in self.mutations:
                # MUTATED: the image is staged whether or not its
                # digest reproduced the meta record.
                out.append(("rep", ("rep.verify-stage",),
                            st(rep=("staged", fv, fok, av, aok, fv))))
            elif fok:
                out.append(("rep", ("rep.verify-stage",),
                            st(rep=("staged", fv, fok, av, aok, fv))))
            else:
                out.append(("rep", ("rep.verify-reject",),
                            st(rep=("serving", 0, True, av, aok,
                                    seen))))
        elif fph == "staged":
            out.append(("rep", ("rep.swap",),
                        st(rep=("serving", 0, True, fv, fok, seen))))

        # -- faults ------------------------------------------------------
        if fo > 0 and js in "PD" and not rec:
            out.append(("net", ("net.failover",),
                        st(ctl=(js, epoch + 1, True),
                           faults=(fo - 1, co))))
        return out

    def actor_label(self, actor):
        return {"ctl": "controller", "mover": "mover", "pub": "publisher",
                "rep": "replica"}.get(actor, str(actor))


# ---------------------------------------------------------------------------
# Toy broken spec: torn commit REACHABLE (golden-counterexample fixture)
# ---------------------------------------------------------------------------
def toy_spec():
    """A deliberately broken two-donor spec: donors snapshot at
    *independent* boundaries (no membership exchange) and the joiner
    commits with **no stamp-equality guard** — the torn-commit property
    is reachable, and the shortest counterexample is the golden trace
    fixture tier-1 asserts byte-for-byte."""
    from .spec import ProtocolSpec, Transition

    return ProtocolSpec(
        name="toy-torn",
        doc="broken on purpose: no boundary exchange, no torn reject",
        roles=("donor", "joiner"),
        states={"donor": ("idle", "stepped", "snapped"),
                "joiner": ("wait", "metas", "committed")},
        transitions=(
            Transition("toy.step", "donor", "idle", "stepped",
                       "internal:step"),
            Transition("toy.snap-early", "donor", "idle", "snapped",
                       "internal:snapshot",
                       binds=("statesync.snapshot.Snapshot",)),
            Transition("toy.snap-late", "donor", "stepped", "snapped",
                       "internal:snapshot",
                       binds=("statesync.snapshot.Snapshot",)),
            Transition("toy.collect", "joiner", "wait", "metas",
                       "internal:collect",
                       binds=("statesync.stream.JoinerPuller"
                              "._collect_metas",)),
            Transition("toy.commit", "joiner", "metas", "committed",
                       "internal:commit",
                       doc="BROKEN: commits without comparing stamps"),
        ),
        properties={"torn-commit": "never commit mixed-stamp images"})


class ToyTornModel(Model):
    name = "toy-torn"

    def __init__(self, ranks: int = 2, mutations=(), *,
                 faults: bool = True) -> None:
        self.n = int(ranks)
        self.spec = (toy_spec(),)

    def initial(self):
        # donors: (step, stamp) with stamp -1 until snapped; joiner
        # phase + collected stamps.
        return (tuple((0, -1) for _ in range(self.n)), ("wait", ()))

    def describe(self, state) -> str:
        donors, (jph, metas) = state
        ds = " ".join(f"d{i}:step{s}"
                      f"{f'/snap{st}' if st >= 0 else ''}"
                      for i, (s, st) in enumerate(donors))
        return (f"[{ds}] joiner={jph}"
                f"{f'/metas{list(metas)}' if metas else ''}")

    def invariants(self, state):
        donors, (jph, metas) = state
        if jph == "committed" and len(set(metas)) > 1:
            return ["torn-commit"]
        return []

    def is_terminal(self, state) -> bool:
        _donors, (jph, _metas) = state
        return jph == "committed"

    def successors(self, state):
        donors, (jph, metas) = state
        if self.is_terminal(state):
            return []
        out = []
        for i, (step, stamp) in enumerate(donors):
            if stamp >= 0:
                continue
            if step == 0:
                out.append((i, ("toy.snap-early",),
                            (_repl(donors, i, (0, 0)), (jph, metas))))
                out.append((i, ("toy.step",),
                            (_repl(donors, i, (1, -1)), (jph, metas))))
            else:
                out.append((i, ("toy.snap-late",),
                            (_repl(donors, i, (1, 1)), (jph, metas))))
        if jph == "wait" and all(st >= 0 for _s, st in donors):
            out.append(("J", ("toy.collect",),
                        (donors, ("metas",
                                  tuple(st for _s, st in donors)))))
        if jph == "metas":
            out.append(("J", ("toy.commit",),
                        (donors, ("committed", metas))))
        return out

    def actor_label(self, actor):
        if actor == "J":
            return "joiner"
        return f"donor {actor}" if isinstance(actor, int) else str(actor)
