"""hvdflow — interprocedural rank-divergence dataflow analysis.

The compile-time half of collective fingerprinting: hvdlint's HVD101
flags a collective *syntactically* under a rank-gated branch, and the
runtime fingerprint (``HOROVOD_FINGERPRINT``) catches divergence after
one negotiation cycle — but a collective buried three calls below an
``if hvd.rank() == 0:`` branch is invisible to both until it hangs a
real world.  hvdflow closes that gap the way hvdsan (locks) and hvdmc
(protocols) did: a whole-program static pass whose findings the runtime
witness corroborates.

The pass (``flow.py``) builds, over the hvdsan call graph with its
typed receiver resolution:

1. **Collective-effect summaries** — for every function, the ordered
   stream of collective call sites it may execute (allreduce /
   allgather / broadcast / alltoall / barrier / kv_barrier /
   broadcast_object / allgather_object, plus the statesync boundary
   exchange), composed through confidently-resolved calls.
2. **Rank-taint analysis** — sources are ``hvd.rank()`` /
   ``local_rank()`` and friends, ``rank ==``/``!=`` comparisons,
   coordinator predicates and the ``.rank``-family attributes named in
   :data:`~.flow.TAINT_ATTR_SOURCES`; taint propagates through
   assignments, returns, parameters (call-site arguments) and boolean
   contexts to a fixpoint.

Rules:

- **HVD601 divergent-collective** — a collective effect reachable
  under one arm of a rank-tainted branch with no sequence-equal effect
  on the sibling arm.  Each finding carries the would-be fingerprint
  stream of both arms and the first divergent op — the static twin of
  the runtime divergence ERROR.  Rank-0-only *non*-collective work
  stays legal.
- **HVD602 divergent-loop-trip** — collectives inside a loop whose
  trip count is rank-tainted (``range(rank)``).
- **HVD603 unbounded-serve-wait** — a blocking wait reachable from the
  serving dispatch path with no ``deadline_scope``/``op_scope``/
  ``op_timeout`` bound on any interprocedural path (the flow-aware
  upgrade of HVD1003).
- **HVD604 unregistered-knob-read** — an ``os.environ``/``getenv``
  read of a ``HOROVOD_*`` name missing from the typed knob registry
  (``common/config.py``).

CLI: ``python -m horovod_tpu.analysis.hvdflow`` (or ``lint --flow`` to
ride the shared single-parse driver).  See docs/analysis.md.
"""
from .flow import (FLOW_RULE_IDS, FlowProgram,  # noqa: F401
                   analyze_flow, main)
