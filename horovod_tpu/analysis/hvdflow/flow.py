"""hvdflow core: effect summaries, rank taint, and the HVD601-604 checks.

Model
-----

Per function (one AST walk, riding the shared single-parse driver):

- an **effect tree**: the ordered sequence of collective call sites
  (``("coll", op, label, spec, submesh, site)``), unresolved calls
  (``("call", spine, site)``), branches (``("branch", test, site,
  then_effects, else_effects)``) and loops (``("loop", trip_expr,
  site, body_effects)``) the function body may execute;
- **taint facts**: assignments, returns and call-site arguments, so the
  global fixpoint can propagate rank taint through locals, returns and
  parameters;
- **HVD603 facts**: blocking waits (with their boundedness) and whether
  the function establishes a deadline guard
  (``deadline_scope``/``op_scope``/``op_timeout``);
- **HVD604 facts**: raw environment reads of ``HOROVOD_*`` literals.

Streams
-------

A function's **fingerprint stream** is its effect tree flattened
through the hvdsan call graph (typed receiver resolution; only
*confident* targets are followed, so imprecision yields missed tokens,
never phantom ones): collectives become tokens, an untainted branch
whose arms agree contributes the shared stream, an untainted branch
whose arms differ contributes one ``{a|b}`` token (data-dependent but
rank-symmetric — both ranks take the same arm), a loop contributes one
``loop[...]`` token (unknown but rank-invariant trip count).  Two arms
of a **rank-tainted** branch must produce sequence-equal streams
(HVD601); a **rank-tainted** loop trip must gate an empty stream
(HVD602).  The stream rendering in each finding is exactly the op
sequence the runtime fingerprint would fold, so a static finding and
its runtime divergence ERROR describe the same evidence.

Since collective identity grew a sharding-spec column (hvdshard), a
collective call site carrying a resolvable ``spec=`` literal renders
its token as ``op(name|spec)``; arms sequence-equal on ``op(name)`` but
unequal on spec are the HVD803 divergent-spec finding (the runtime twin
is the strict-mode fingerprint ERROR on the first spec-divergent op).
Collectives invoked through a sub-mesh receiver (``self.cross.…``,
``self.local.…``, the shm legs — SUBMESH_ATTRS) are *symmetric per
sub-mesh*: an HVD601 whose divergent tokens are ALL sub-mesh-scoped
demotes to a warning documenting the per-submesh symmetry instead of
requiring an inline suppression.
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from dataclasses import dataclass, field

from ..hvdsan.lockgraph import (Analysis, CallEvent, Finding, Program,
                                module_label, norm_path, _spine)
from ..lint import COLLECTIVE_NAMES, iter_python_files
from ..rules import RULES

# --- manifests ---------------------------------------------------------------
# The collective-effect alphabet: every eager/SPMD collective hvdlint
# knows, plus the object-pickle collectives and the statesync boundary
# exchange (a call to either IS one symmetric exchange on the wire).
FLOW_COLLECTIVES = frozenset(COLLECTIVE_NAMES) | frozenset({
    "broadcast_object", "allgather_object", "step_boundary",
})

# Rank-taint sources.  Names and attributes are a small reviewed
# manifest (docs/analysis.md): a bare name or ``.attr`` that *is* a
# per-rank value, and callables whose return differs per rank.
TAINT_NAME_SOURCES = frozenset({
    "rank", "local_rank", "cross_rank", "node_rank", "request_rank",
    "process_index", "is_coordinator", "local_joined", "joined_ranks",
    "launch_rank",
})
TAINT_ATTR_SOURCES = frozenset({
    "rank", "_rank", "local_rank", "cross_rank", "node_rank",
    "process_index", "request_rank", "launch_rank", "is_coordinator",
})
TAINT_CALL_SOURCES = frozenset({
    "rank", "local_rank", "cross_rank", "node_rank", "process_index",
    "is_coordinator",
})

# World-symmetric names: identical on every rank by construction, so
# they never carry taint even when assigned from a rank-derived
# expression (``rank, size = resolve_world()`` must not taint ``size``).
SYMMETRIC_NAMES = frozenset({
    "size", "world_size", "local_size", "cross_size", "node_size",
    "nranks", "num_ranks", "np",
})

# HVD603: the serving dispatch roots (functions whose interprocedural
# frontier must never reach an unbounded blocking wait without a
# deadline on the path), the deadline-guard vocabulary, and the
# blocking-wait vocabulary (HVD1003's set plus queue handoffs).
SERVE_DISPATCH_ROOTS = frozenset({"serve_loop"})
GUARD_NAMES = frozenset({"deadline_scope", "op_scope", "op_timeout"})
# World-formation boundary: the serve-path walk stops at (re)init —
# world formation/teardown is governed by HOROVOD_GLOO_TIMEOUT_SECONDS
# and the fault-tolerance deadlines (docs/resilience.md), not by any
# single request's SLO, and it only runs on the exceptional
# shrink/grow path where the in-flight map is being resynced anyway.
SERVE_WAIT_BOUNDARIES = frozenset({
    "core.init", "core.reinit_world", "core.shutdown",
})
WAIT_NAMES = frozenset({"recv", "recv_into", "join", "wait", "urlopen",
                        "get", "put"})
_BOUND_HINTS = ("timeout", "deadline", "poll")
_MAX_SERVE_DEPTH = 14

# Sub-mesh receiver attributes: a collective invoked through one of
# these receivers executes within a proper sub-mesh of the world
# (backend/hierarchical.py's RS(local)→AR(cross)→AG(local) legs, the
# shm twins).  Membership of each sub-mesh is a pure function of
# world-symmetric data (payload size, local_size) beneath one
# already-negotiated response, so arms whose divergent tokens are ALL
# sub-mesh-scoped are symmetric-per-submesh: HVD601 demotes them to a
# warning naming the sub-meshes instead of demanding a suppression.
# Reviewed manifest, like the ownership/LOCK_HOLD_ALLOWED idiom.
SUBMESH_ATTRS = frozenset({"cross", "local", "shm_local", "shm_cross",
                           # multi-level hierarchical ladder legs: the
                           # per-level collectives loop over
                           # `for level in self.levels[...]` receivers
                           "level"})

# Stream caps: a divergence is located within the first tokens; capping
# keeps pathological recursion bounded.
_MAX_STREAM = 48

FLOW_RULE_IDS = frozenset({"HVD601", "HVD602", "HVD603", "HVD604"})


# --- per-function facts ------------------------------------------------------
@dataclass
class FlowFunc:
    key: str
    module: str
    name: str
    path: str
    line: int
    params: list = field(default_factory=list)
    effects: list = field(default_factory=list)
    assigns: list = field(default_factory=list)   # [(names, expr)]
    returns: list = field(default_factory=list)   # [expr]
    calls: list = field(default_factory=list)     # [(spine, Call node)]
    waits: list = field(default_factory=list)     # [(name, node, bounded)]
    guard: bool = False
    tainted_locals: set = field(default_factory=set)


@dataclass
class FlowProgram:
    funcs: dict = field(default_factory=dict)     # key -> FlowFunc
    env_reads: list = field(default_factory=list)  # [(path, name, line)]

    def collect_source(self, path: str, source: str,
                       tree: ast.AST | None = None) -> None:
        if tree is None:
            tree = ast.parse(source, filename=path)
        _FlowCollector(self, norm_path(path),
                       module_label(path)).visit(tree)


def _terminal(node: ast.AST) -> str | None:
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _call_label(node: ast.Call) -> str:
    """Tensor/tag label of a collective call, for the fingerprint-style
    stream rendering: the ``name=``/``tag=`` string literal, else the
    first string-literal positional, else ''."""
    for kw in node.keywords:
        if kw.arg in ("name", "tag") and \
                isinstance(kw.value, ast.Constant) and \
                isinstance(kw.value.value, str):
            return kw.value.value
    for arg in node.args:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
    return ""


def _spec_token_of_ast(node: ast.AST) -> str:
    """Canonical spec token of a ``spec=`` argument value, when it is a
    resolvable literal: a string constant (already canonical), or a
    ``P(...)``/``PartitionSpec(...)`` call whose per-dim entries are
    constants (None, axis-name strings, or tuples/lists of axis names).
    Anything dynamic yields '' — imprecision loses spec columns, never
    invents them (the hvdflow confidence discipline)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Call) and \
            _terminal(node) in ("P", "PartitionSpec"):
        entries = []
        for arg in node.args:
            if isinstance(arg, ast.Constant) and arg.value is None:
                entries.append("*")
            elif isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str):
                entries.append(arg.value)
            elif isinstance(arg, (ast.Tuple, ast.List)) and all(
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, str) for e in arg.elts):
                entries.append("+".join(e.value for e in arg.elts))
            else:
                return ""
        if not entries:
            return "*"
        return "(" + ",".join(entries) + ")"
    return ""


def _call_spec(node: ast.Call) -> str:
    """Spec token a collective call site carries (``spec=`` keyword)."""
    for kw in node.keywords:
        if kw.arg == "spec":
            return _spec_token_of_ast(kw.value)
    return ""


def _submesh_qual(node: ast.Call) -> str:
    """The sub-mesh receiver attribute a collective is invoked through
    (SUBMESH_ATTRS), or ''."""
    sp = _spine(node.func)
    if sp:
        for part in sp[:-1]:
            if part in SUBMESH_ATTRS:
                return part
    return ""


def _wait_is_exempt(node: ast.Call, name: str) -> bool:
    """str.join / os.path.join and dict/config .get() lookalikes."""
    if name == "join":
        if not isinstance(node.func, ast.Attribute):
            return True
        base = node.func.value
        if isinstance(base, ast.Constant) and isinstance(base.value, str):
            return True
        sp = _spine(node.func)
        if sp and set(sp[:-1]) & {"path", "sep", "pathsep", "linesep",
                                  "os", "posixpath", "ntpath"}:
            return True
        return False
    if name in ("get", "put"):
        # only queue-looking receivers block (mirrors HVD1006's filter)
        if not isinstance(node.func, ast.Attribute):
            return True
        base = node.func.value
        ident = base.attr if isinstance(base, ast.Attribute) else (
            base.id if isinstance(base, ast.Name) else None)
        if ident is None or ident.isupper():
            return True
        low = ident.lower()
        return not (low == "q" or "queue" in low or low.endswith("_q"))
    return False


def _call_is_bounded(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
        if kw.arg and any(h in kw.arg.lower() for h in _BOUND_HINTS):
            return True
    for arg in node.args:
        for sub in ast.walk(arg):
            ident = sub.id if isinstance(sub, ast.Name) else (
                sub.attr if isinstance(sub, ast.Attribute) else None)
            if ident and any(h in ident.lower() for h in _BOUND_HINTS):
                return True
    return False


_ENV_SPINES = ("environ",)


def _env_read_name(node: ast.AST) -> str | None:
    """HOROVOD_* literal READ via os.environ.get / os.getenv /
    os.environ[...] (Load context only — launchers *setting* child env
    are not reads)."""
    lit = None
    if isinstance(node, ast.Call):
        name = _terminal(node)
        if name == "getenv" and node.args:
            lit = node.args[0]
        elif name == "get" and isinstance(node.func, ast.Attribute) \
                and _terminal(node.func.value) in _ENV_SPINES \
                and node.args:
            lit = node.args[0]
    elif isinstance(node, ast.Subscript) and \
            isinstance(node.ctx, ast.Load) and \
            _terminal(node.value) in _ENV_SPINES:
        lit = node.slice
    if isinstance(lit, ast.Constant) and isinstance(lit.value, str) \
            and lit.value.startswith("HOROVOD_"):
        return lit.value
    return None


_SCOPE_STMTS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


class _FlowCollector(ast.NodeVisitor):
    """Single-pass per-file fact extractor (mirrors the hvdsan
    collector's qualname scheme so FlowFunc keys line up with the
    Program's FuncRaw keys for call resolution)."""

    def __init__(self, prog: FlowProgram, path: str, label: str) -> None:
        self.p = prog
        self.path = path
        self.label = label
        self._cls_stack: list[str] = []
        self._fn_stack: list[str] = []

    def _qual(self, name: str) -> str:
        parts = [self.label] if self.label else []
        if self._cls_stack:
            parts.append(self._cls_stack[-1])
        parts.extend(self._fn_stack)
        parts.append(name)
        return ".".join(parts)

    def visit_Module(self, node: ast.Module) -> None:
        # Module-level env reads count too (import-time knob reads).
        for sub in ast.walk(node):
            name = _env_read_name(sub)
            if name is not None:
                self.p.env_reads.append(
                    (self.path, name, getattr(sub, "lineno", 1)))
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._cls_stack.append(node.name)
        for stmt in node.body:
            self.visit(stmt)
        self._cls_stack.pop()

    def _visit_function(self, node) -> None:
        fn = FlowFunc(key=self._qual(node.name), module=self.label,
                      name=node.name, path=self.path, line=node.lineno)
        args = node.args
        fn.params = [a.arg for a in (args.posonlyargs + args.args
                                     + args.kwonlyargs)]
        _FuncScan(fn).scan(node)
        self.p.funcs[fn.key] = fn
        self._fn_stack.append(node.name)
        for stmt in node.body:
            self.visit(stmt)      # nested defs get their own FlowFunc
        self._fn_stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function


class _FuncScan:
    """Effect-tree + fact extraction for ONE function body (nested
    function/class scopes are skipped — they are their own units)."""

    def __init__(self, fn: FlowFunc) -> None:
        self.fn = fn

    def scan(self, node) -> None:
        self.fn.effects = self._stmts(node.body)

    # -- expressions --------------------------------------------------------
    def _scan_expr(self, expr: ast.AST | None) -> list:
        """Effects contributed by one expression, in syntactic order;
        also records taint/wait/guard/call facts along the way."""
        out: list = []
        if expr is None:
            return out
        stack = [expr]
        while stack:
            node = stack.pop(0)
            if isinstance(node, (ast.Lambda,) + _SCOPE_STMTS):
                continue
            if isinstance(node, (ast.ListComp, ast.SetComp,
                                 ast.DictComp, ast.GeneratorExp)):
                # A comprehension is a loop: its first generator's
                # iterable is the trip count, everything inside the
                # element/conditions is the body.
                gen0 = node.generators[0]
                # the first iterable is evaluated once, before the loop
                out.extend(self._scan_expr(gen0.iter))
                inner: list = []
                for sub in ([node.elt] if hasattr(node, "elt")
                            else [node.key, node.value]):
                    inner.extend(self._scan_expr(sub))
                for g in node.generators:
                    for cond in g.ifs:
                        inner.extend(self._scan_expr(cond))
                    if g is not gen0:
                        inner.extend(self._scan_expr(g.iter))
                out.append(("loop", gen0.iter, node.lineno, inner))
                continue
            if isinstance(node, ast.NamedExpr):
                tgt = node.target
                if isinstance(tgt, ast.Name):
                    self.fn.assigns.append(((tgt.id,), node.value))
            if isinstance(node, ast.Call):
                self._note_call(node)
                name = _terminal(node)
                if name in FLOW_COLLECTIVES:
                    out.append(("coll", name, _call_label(node),
                                _call_spec(node), _submesh_qual(node),
                                node.lineno))
                else:
                    sp = _spine(node.func)
                    if sp:
                        out.append(("call", sp, node.lineno))
            stack = list(ast.iter_child_nodes(node)) + stack
        return out

    def _note_call(self, node: ast.Call) -> None:
        name = _terminal(node)
        sp = _spine(node.func)
        if sp:
            self.fn.calls.append((sp, node))
        if name in GUARD_NAMES:
            self.fn.guard = True
        if name in WAIT_NAMES and not _wait_is_exempt(node, name):
            self.fn.waits.append((name, node, _call_is_bounded(node)))

    # -- statements ---------------------------------------------------------
    def _stmts(self, stmts: list) -> list:
        out: list = []
        for st in stmts:
            if isinstance(st, _SCOPE_STMTS):
                continue
            if isinstance(st, ast.If):
                out.extend(self._scan_expr(st.test))
                out.append(("branch", st.test, st.lineno,
                            self._stmts(st.body), self._stmts(st.orelse)))
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                out.extend(self._scan_expr(st.iter))
                if isinstance(st.target, ast.Name):
                    self.fn.assigns.append(((st.target.id,), st.iter))
                elif isinstance(st.target, ast.Tuple):
                    names = tuple(e.id for e in st.target.elts
                                  if isinstance(e, ast.Name))
                    if names:
                        self.fn.assigns.append((names, st.iter))
                out.append(("loop", st.iter, st.lineno,
                            self._stmts(st.body + st.orelse)))
            elif isinstance(st, ast.While):
                out.extend(self._scan_expr(st.test))
                out.append(("loop", st.test, st.lineno,
                            self._stmts(st.body + st.orelse)))
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    out.extend(self._scan_expr(item.context_expr))
                    if _terminal(item.context_expr) in GUARD_NAMES:
                        self.fn.guard = True
                    if isinstance(item.optional_vars, ast.Name):
                        self.fn.assigns.append(
                            ((item.optional_vars.id,), item.context_expr))
                out.extend(self._stmts(st.body))
            elif isinstance(st, ast.Try) or \
                    st.__class__.__name__ == "TryStar":
                out.extend(self._stmts(st.body))
                for h in st.handlers:
                    out.extend(self._stmts(h.body))
                out.extend(self._stmts(st.orelse))
                out.extend(self._stmts(st.finalbody))
            else:
                if isinstance(st, ast.Assign):
                    if len(st.targets) == 1 and \
                            isinstance(st.targets[0], ast.Tuple) and \
                            isinstance(st.value, ast.Tuple) and \
                            len(st.targets[0].elts) == \
                            len(st.value.elts):
                        # a, b = x, y — match taint elementwise
                        for t, v in zip(st.targets[0].elts,
                                        st.value.elts):
                            if isinstance(t, ast.Name):
                                self.fn.assigns.append(((t.id,), v))
                    else:
                        names = []
                        for t in st.targets:
                            if isinstance(t, ast.Name):
                                names.append(t.id)
                            elif isinstance(t, ast.Tuple):
                                names.extend(e.id for e in t.elts
                                             if isinstance(e, ast.Name))
                        if names:
                            self.fn.assigns.append((tuple(names),
                                                    st.value))
                elif isinstance(st, ast.AnnAssign) and \
                        isinstance(st.target, ast.Name) and \
                        st.value is not None:
                    self.fn.assigns.append(((st.target.id,), st.value))
                elif isinstance(st, ast.AugAssign) and \
                        isinstance(st.target, ast.Name):
                    self.fn.assigns.append(((st.target.id,), st.value))
                elif isinstance(st, ast.Return) and st.value is not None:
                    self.fn.returns.append(st.value)
                out.extend(self._scan_expr(st))
        return out


# --- the analysis ------------------------------------------------------------
class FlowAnalysis:
    """Global taint fixpoint + stream composition + the four checks."""

    def __init__(self, program: Program, flow: FlowProgram) -> None:
        self.program = program
        self.flow = flow
        self.an = Analysis(program)
        self.an._build_indexes()
        self.findings: list[Finding] = []
        self.tainted_returns: set[str] = set()
        self.tainted_params: dict[str, set] = {}
        self._resolve_cache: dict = {}
        self._stream_cache: dict = {}

    # -- call resolution (typed, via the hvdsan graph) ----------------------
    def _resolve(self, fn: FlowFunc, spine: tuple, line: int) -> list:
        key = (fn.key, spine)
        hit = self._resolve_cache.get(key)
        if hit is not None:
            return hit
        fraw = self.program.functions.get(fn.key)
        if fraw is None:
            self._resolve_cache[key] = []
            return []
        ev = CallEvent(spine=spine, held=(), line=line)
        out = self.an._resolve_call_uncached(fraw, ev)
        self._resolve_cache[key] = out
        return out

    # -- taint ---------------------------------------------------------------
    def _expr_tainted(self, fn: FlowFunc, expr: ast.AST) -> bool:
        """Collective calls are taint SANITIZERS: an allgather'd /
        broadcast / allreduced value is identical on every rank by
        construction, so their whole subtree is skipped — branching on
        an exchanged membership view is the sanctioned symmetric idiom
        (statesync.step_boundary), not a divergence."""
        stack = [expr]
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.Lambda,) + _SCOPE_STMTS):
                continue
            if isinstance(sub, ast.Name) and \
                    (sub.id in TAINT_NAME_SOURCES
                     or sub.id in fn.tainted_locals):
                return True
            if isinstance(sub, ast.Attribute) and \
                    sub.attr in TAINT_ATTR_SOURCES:
                return True
            if isinstance(sub, ast.Call):
                name = _terminal(sub)
                if name in FLOW_COLLECTIVES:
                    continue     # symmetric result: sanitize subtree
                if name in TAINT_CALL_SOURCES:
                    return True
                sp = _spine(sub.func)
                if sp and self.tainted_returns:
                    for tkey, _conf in self._resolve(fn, sp, sub.lineno):
                        if tkey in self.tainted_returns:
                            return True
            stack.extend(ast.iter_child_nodes(sub))
        return False

    def _fix_taint(self) -> None:
        funcs = self.flow.funcs
        for fn in funcs.values():
            self.tainted_params.setdefault(fn.key, set())
        for _round in range(16):
            changed = False
            for fn in funcs.values():
                tl = set(self.tainted_params[fn.key])
                # local assignment fixpoint (order-insensitive)
                for _ in range(4):
                    before = len(tl)
                    fn.tainted_locals = tl
                    for names, expr in fn.assigns:
                        carriers = set(names) - SYMMETRIC_NAMES
                        if expr is not None and carriers and \
                                not (carriers <= tl) and \
                                self._expr_tainted(fn, expr):
                            tl |= carriers
                    if len(tl) == before:
                        break
                fn.tainted_locals = tl
                if fn.key not in self.tainted_returns and any(
                        self._expr_tainted(fn, r) for r in fn.returns):
                    self.tainted_returns.add(fn.key)
                    changed = True
                # argument -> parameter propagation
                for sp, node in fn.calls:
                    if sp[-1] in FLOW_COLLECTIVES:
                        continue    # the alphabet's terminals: opaque
                    targets = self._resolve(fn, sp, node.lineno)
                    if not targets:
                        continue
                    t_args = [a for a in node.args
                              if self._expr_tainted(fn, a)]
                    t_kws = [kw.arg for kw in node.keywords
                             if kw.arg and self._expr_tainted(fn, kw.value)]
                    if not t_args and not t_kws:
                        continue
                    for tkey, conf in targets:
                        callee = funcs.get(tkey)
                        if callee is None or not conf:
                            continue
                        params = callee.params
                        off = 1 if params and params[0] in ("self", "cls") \
                            and (len(sp) > 1 or tkey.endswith("__init__")) \
                            else 0
                        tp = self.tainted_params[tkey]
                        for i, a in enumerate(node.args):
                            j = i + off
                            if a in t_args and j < len(params) and \
                                    params[j] not in tp and \
                                    params[j] not in SYMMETRIC_NAMES:
                                tp.add(params[j])
                                changed = True
                        for kw in t_kws:
                            if kw in params and kw not in tp and \
                                    kw not in SYMMETRIC_NAMES:
                                tp.add(kw)
                                changed = True
            if not changed:
                break

    # -- streams -------------------------------------------------------------
    def _func_stream(self, key: str, stack: frozenset) -> list:
        if key in self._stream_cache:
            return self._stream_cache[key]
        fn = self.flow.funcs.get(key)
        if fn is None or key in stack:
            return []
        out = self._stream_of(fn.effects, fn, stack | {key})
        self._stream_cache[key] = out
        return out

    def _stream_of(self, effs: list, fn: FlowFunc,
                   stack: frozenset) -> list:
        """[(token, base_token, (path, line), quals)] — token is the
        spec-annotated rendering (``op(name|spec)``), base_token the
        spec-stripped one (HVD601 compares bases, HVD803 compares
        tokens), and quals the sub-mesh qualifier set — a frozenset of
        SUBMESH_ATTRS when every collective under this entry is
        sub-mesh-scoped, else None."""
        out: list = []
        for e in effs:
            kind = e[0]
            if kind == "coll":
                _, op, label, spec, qual, line = e
                base = f"{op}({label})" if label else op
                tok = f"{op}({label}|{spec})" if spec else base
                out.append((tok, base, (fn.path, line),
                            frozenset({qual}) if qual else None))
            elif kind == "call":
                _, sp, line = e
                for tkey, conf in self._resolve(fn, sp, line):
                    if conf:
                        out.extend(self._func_stream(tkey, stack))
                        break
            elif kind == "branch":
                _, test, line, then_e, else_e = e
                t = self._stream_of(then_e, fn, stack)
                o = self._stream_of(else_e, fn, stack)
                if [x[0] for x in t] == [x[0] for x in o]:
                    out.extend(t)
                elif t or o:
                    out.append((
                        "{%s|%s}" % (_render(t) or "-", _render(o) or "-"),
                        "{%s|%s}" % (_render_base(t) or "-",
                                     _render_base(o) or "-"),
                        (fn.path, line), _merge_quals(t + o)))
            elif kind == "loop":
                _, _trip, line, body_e = e
                body = self._stream_of(body_e, fn, stack)
                if body:
                    out.append((f"loop[{_render(body)}]",
                                f"loop[{_render_base(body)}]",
                                (fn.path, line), _merge_quals(body)))
            if len(out) > _MAX_STREAM:
                return out[:_MAX_STREAM]
        return out

    # -- findings ------------------------------------------------------------
    def _suppressed_span(self, path: str, start: int, end: int,
                         rule) -> bool:
        sup = self.program.suppressions.get(path)
        return bool(sup and sup.active_span(start, max(start, end), rule))

    def _emit(self, rule_key: str, severity: str, path: str, line: int,
              message: str, sites: tuple = (),
              span_end: int | None = None) -> None:
        rule = RULES[rule_key]
        if self._suppressed_span(path, line, span_end or line, rule):
            return
        self.findings.append(Finding(rule=rule, severity=severity,
                                     path=path, line=line,
                                     message=message, sites=sites))

    def _walk_effects(self, effs: list):
        for e in effs:
            yield e
            if e[0] == "branch":
                yield from self._walk_effects(e[3])
                yield from self._walk_effects(e[4])
            elif e[0] == "loop":
                yield from self._walk_effects(e[3])

    def _check_divergence(self) -> None:
        """HVD601 + HVD602 + HVD803."""
        for fn in self.flow.funcs.values():
            for e in self._walk_effects(fn.effects):
                if e[0] == "branch":
                    _, test, line, then_e, else_e = e
                    if not self._expr_tainted(fn, test):
                        continue
                    t = self._stream_of(then_e, fn, frozenset({fn.key}))
                    o = self._stream_of(else_e, fn, frozenset({fn.key}))
                    tt = [x[0] for x in t]
                    oo = [x[0] for x in o]
                    if tt == oo:
                        continue
                    k = next((i for i, (a, b) in enumerate(
                        zip(tt, oo)) if a != b), min(len(tt), len(oo)))
                    a_tok = tt[k] if k < len(tt) else "(end of stream)"
                    b_tok = oo[k] if k < len(oo) else "(end of stream)"
                    sites = tuple(e2[2] for e2 in (t + o)[:6])
                    span_end = getattr(test, "end_lineno", line)
                    if [x[1] for x in t] == [x[1] for x in o]:
                        # Sequence-equal on op×name, unequal on spec:
                        # the spec-divergence class (hvdshard HVD803).
                        self._emit(
                            "divergent-spec-collective", "error",
                            fn.path, line,
                            f"rank-tainted branch in '{fn.key}' gates "
                            f"collective arms that agree on the op "
                            f"sequence but disagree on sharding spec: "
                            f"if-arm [{_render(t) or '(empty)'}] vs "
                            f"else-arm [{_render(o) or '(empty)'}]; "
                            f"first spec-divergent op #{k + 1}: {a_tok}"
                            f" vs {b_tok}.  Negotiation proceeds (the "
                            f"ops match) and the data plane then moves "
                            f"differently-sharded bytes into one "
                            f"reduction — runtime: the strict-mode "
                            f"HOROVOD_FINGERPRINT divergence ERROR on "
                            f"the first spec-divergent op (lint "
                            f"--shard).  Make the spec rank-invariant, "
                            f"or justify with a suppression",
                            sites=sites, span_end=span_end)
                        continue
                    tq = _merge_quals(t)
                    oq = _merge_quals(o)
                    if tq is not None and oq is not None:
                        # Every divergent token is sub-mesh-scoped:
                        # symmetric per sub-mesh (the hierarchical
                        # legs), not a world-level divergence.
                        subs = ", ".join(sorted(tq | oq)) or "-"
                        self._emit(
                            "divergent-collective", "warning", fn.path,
                            line,
                            f"rank-tainted branch in '{fn.key}' gates "
                            f"collective streams that differ only "
                            f"within sub-mesh legs ({subs}): if-arm "
                            f"[{_render(t) or '(empty)'}] vs else-arm "
                            f"[{_render(o) or '(empty)'}].  Sub-mesh "
                            f"membership is a pure function of "
                            f"world-symmetric data beneath one "
                            f"negotiated response (SUBMESH_ATTRS), so "
                            f"every member of the executing sub-mesh "
                            f"takes the same arm — symmetric per "
                            f"sub-mesh, demoted from the HVD601 error",
                            sites=sites, span_end=span_end)
                        continue
                    self._emit(
                        "divergent-collective", "error", fn.path, line,
                        f"rank-tainted branch in '{fn.key}' gates a "
                        f"divergent collective stream: if-arm fingerprint"
                        f" [{_render(t) or '(empty)'}] vs else-arm "
                        f"[{_render(o) or '(empty)'}]; first divergent "
                        f"op #{k + 1}: {a_tok} vs {b_tok}.  Ranks taking"
                        f" different arms submit different collective "
                        f"sequences and the negotiation wedges (runtime:"
                        f" the HOROVOD_FINGERPRINT divergence ERROR) — "
                        f"hoist the collectives out of the rank branch "
                        f"(rank-gated non-collective work is legal), or "
                        f"justify with a suppression",
                        sites=sites,
                        span_end=span_end)
                elif e[0] == "loop":
                    _, trip, line, body_e = e
                    if trip is None or not self._expr_tainted(fn, trip):
                        continue
                    body = self._stream_of(body_e, fn,
                                           frozenset({fn.key}))
                    if not body:
                        continue
                    sites = tuple(e2[2] for e2 in body[:6])
                    self._emit(
                        "divergent-loop-trip", "error", fn.path, line,
                        f"collective stream [{_render(body)}] inside a "
                        f"loop in '{fn.key}' whose trip count is "
                        f"rank-tainted: ranks execute the body a "
                        f"different number of times, shifting every "
                        f"later op in their fingerprint streams — make "
                        f"the trip count rank-invariant, or justify "
                        f"with a suppression",
                        sites=sites,
                        span_end=getattr(trip, "end_lineno", line))

    def _check_serve_waits(self) -> None:
        """HVD603: DFS over the call graph from every serving dispatch
        root; a function's waits are bounded once ANY frame on the path
        (itself included) established a deadline guard."""
        roots = [fn for fn in self.flow.funcs.values()
                 if (fn.module.split(".")[0] == "serving"
                     or "/serving/" in fn.path)
                 and fn.name in SERVE_DISPATCH_ROOTS]
        reported: set = set()
        for root in roots:
            seen: set = set()
            stack = [(root.key, (root.name,), False)]
            while stack:
                key, pathnames, guarded = stack.pop()
                fn = self.flow.funcs.get(key)
                if fn is None:
                    continue
                g = guarded or fn.guard
                state = (key, g)
                if state in seen or len(pathnames) > _MAX_SERVE_DEPTH:
                    continue
                seen.add(state)
                if not g:
                    for name, node, bounded in fn.waits:
                        if bounded:
                            continue
                        site = (fn.path, node.lineno)
                        if site in reported:
                            continue
                        reported.add(site)
                        self._emit(
                            "unbounded-serve-wait", "error", fn.path,
                            node.lineno,
                            f"blocking '{name}' in '{fn.key}' is "
                            f"reachable from the serving dispatch root "
                            f"'{root.key}' via "
                            f"{' -> '.join(pathnames)} with no "
                            f"deadline_scope/op_scope/op_timeout bound "
                            f"anywhere on the path: one dead peer or "
                            f"wedged handoff stalls the serve loop past"
                            f" every request's SLO — bound the wait "
                            f"from the request deadline, or justify "
                            f"the external bound with a suppression")
                for sp, node in fn.calls:
                    if sp[-1] in FLOW_COLLECTIVES:
                        continue
                    for tkey, conf in self._resolve(fn, sp,
                                                    node.lineno):
                        if conf and tkey not in SERVE_WAIT_BOUNDARIES:
                            # cycles break on the seen set
                            callee = self.flow.funcs.get(tkey)
                            label = callee.name if callee else tkey
                            stack.append(
                                (tkey, pathnames + (label,), g))

    def _check_knob_reads(self) -> None:
        """HVD604: raw HOROVOD_* environment reads must name a knob the
        typed registry declares."""
        try:
            from ...common import config
            registered = set(config.all_knobs())
        except Exception:            # pragma: no cover - broken install
            return
        for path, name, line in self.flow.env_reads:
            if name in registered:
                continue
            if path.endswith("common/config.py"):
                continue             # the registry itself
            self._emit(
                "unregistered-knob-read", "error", path, line,
                f"raw environment read of {name!r}, which is not "
                f"declared in the typed knob registry "
                f"(common/config.py): undeclared knobs have no type, "
                f"default, doc line or docs/configuration.md row — "
                f"register(name, type, default, doc) it, or justify "
                f"the raw read with a suppression")

    def analyze(self) -> "FlowAnalysis":
        self._fix_taint()
        self._check_divergence()
        self._check_serve_waits()
        self._check_knob_reads()
        self.findings.sort(key=lambda f: (f.path, f.line, f.rule.id))
        return self


def _render(stream: list) -> str:
    return " -> ".join(e[0] for e in stream)


def _render_base(stream: list) -> str:
    return " -> ".join(e[1] for e in stream)


def _merge_quals(entries: list):
    """Union of the entries' sub-mesh qualifier sets, or None when any
    entry is NOT fully sub-mesh-scoped (an empty entry list merges to
    the empty set: a silent arm is vacuously scoped)."""
    quals: set = set()
    for e in entries:
        if e[3] is None:
            return None
        quals |= e[3]
    return frozenset(quals)


def analyze_flow(program: Program, flow: FlowProgram,
                 cfg=None) -> list[Finding]:
    findings = FlowAnalysis(program, flow).analyze().findings
    if cfg is not None:
        findings = [f for f in findings if cfg.wants(f.rule)]
    return findings


def analyze_paths(paths) -> list[Finding]:
    program = Program()
    flow = FlowProgram()
    for p in iter_python_files(list(paths)):
        try:
            with open(p, encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=p)
        except (OSError, SyntaxError):
            continue
        program.collect_source(p, src, tree)
        flow.collect_source(p, src, tree)
    # The engine also emits HVD803 (spec-divergent arms); that rule is
    # hvdshard's to report — the standalone CLIs partition the same way
    # the lint driver's --flow/--shard flags do.
    return [f for f in analyze_flow(program, flow)
            if f.rule.id in FLOW_RULE_IDS]


# --- CLI ---------------------------------------------------------------------
def main(argv: list[str] | None = None) -> int:
    import time as _time
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis.hvdflow",
        description="Interprocedural rank-divergence dataflow analysis "
                    "(HVD601-604; see docs/analysis.md).")
    parser.add_argument("paths", nargs="*", default=["horovod_tpu"])
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--knobs", action="store_true",
                        help="print the generated typed-knob registry "
                             "table (docs/configuration.md) and exit")
    args = parser.parse_args(argv)
    if args.knobs:
        from ...common.config import configuration_markdown
        print(configuration_markdown(), end="")
        return 0
    t0 = _time.monotonic()
    findings = analyze_paths(args.paths)
    wall_ms = round((_time.monotonic() - t0) * 1e3, 3)
    errors = [f for f in findings if f.severity == "error"]
    if args.format == "json":
        print(json.dumps({"flow": [f.json() for f in findings],
                          "wall_ms": wall_ms}, indent=2))
    elif args.format == "sarif":
        from ..hvdsan.san import sarif_payload
        print(json.dumps(sarif_payload(findings), indent=2))
    else:
        for f in findings:
            print(f.text())
        print(f"hvdflow: {len(errors)} error(s), "
              f"{len(findings) - len(errors)} warning(s) in "
              f"{', '.join(args.paths)} ({wall_ms:.1f} ms)",
              file=sys.stderr)
    return 1 if errors else 0
