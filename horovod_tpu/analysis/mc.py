"""``python -m horovod_tpu.analysis.mc`` — explicit-state model
checking of the elastic membership, statesync, and recovery protocols.

Thin entry shim over :mod:`horovod_tpu.analysis.hvdmc.cli` (kept as a
module so the documented spelling works; the package also exposes
``python -m horovod_tpu.analysis.hvdmc``)."""
import sys

from .hvdmc.cli import main

if __name__ == "__main__":
    sys.exit(main())
