"""The hvdshard whole-program pass: HVD801/802/804 over harvested
sharding facts, plus the CLI that merges in hvdflow's HVD803.

Harvest (one AST walk per file, riding the shared single-parse driver
when invoked as ``lint --shard``):

- **Rule tables** — ``ShardingRules([...])`` constructor calls whose
  first argument is a literal list of ``(pattern, P(...))`` pairs.
- **Spec literal sites** — ``P(...)``/``PartitionSpec(...)`` calls with
  constant entries, plus ``spec=`` keywords on collective calls
  (string-token or P-literal form).
- **Mesh-axis vocabulary** — tuple-of-string assignments to ``*AXES*``
  names (parallel/mesh.DEFAULT_AXES), literal string tuples passed to a
  ``Mesh(...)`` constructor (backend/xla.py's ``("world", "local")``
  device mesh must not be a false HVD802 positive), and the axis-named
  keywords of ``MeshSpec(...)``/``build_mesh(...)``.
- **Parameter-path vocabulary** — flax ``name="..."`` keyword literals
  and ``self.param("...", ...)`` first arguments; candidate paths are
  synthesized from these tokens plus the implicit flax leaf names
  (kernel/bias/scale/embedding), so a rule regex can be judged dead or
  a sibling path uncovered without executing any model code.
- **Spec-drop flows** (HVD804) — per-function: locals assigned from a
  spec-producing call (``shard_params``/``constrain``/
  ``with_sharding_constraint``/``device_put`` with a NamedSharding or
  P argument) that later flow into a collective call carrying no
  ``spec=``.

Like hvdflow, imprecision only ever *loses* facts (a dynamic table or
computed spec harvests as nothing) — the pass never invents a spec, so
every finding is anchored to literal source the author wrote.
"""
from __future__ import annotations

import argparse
import ast
import json
import sys
from dataclasses import dataclass, field

from ..hvdsan.lockgraph import Finding, Program, norm_path
from ..lint import iter_python_files
from ..rules import RULES
from ..hvdflow.flow import (FLOW_COLLECTIVES, FlowProgram, _spec_token_of_ast,
                            _terminal, analyze_flow)
from .specs import missing_axes, rule_coverage

SHARD_RULE_IDS = frozenset({"HVD801", "HVD802", "HVD803", "HVD804"})

# Calls whose result carries a sharding layout: a local assigned from
# one of these is "spec'd", and passing it to a collective without
# ``spec=`` drops the layout on the floor (HVD804).
SPEC_PRODUCERS = frozenset({
    "shard_params", "constrain", "with_sharding_constraint", "device_put",
})
# device_put only produces a layout when a sharding rides along.
_SHARDING_CTORS = ("NamedSharding", "P", "PartitionSpec")

# Implicit flax leaf names: parameters these modules create without an
# explicit ``name=`` (Dense kernels, LayerNorm scales, Embed tables).
IMPLICIT_LEAVES = ("kernel", "bias", "scale", "embedding")

# Vocabulary bound: candidate paths are the cross product of harvested
# name tokens, so cap the token set to keep the synthesis linear-ish.
_MAX_NAME_TOKENS = 128


@dataclass
class ShardProgram:
    """Whole-program sharding facts, one collect_source() per file."""
    # [(path, line, [(pattern, token, entry_line)])]
    rule_tables: list = field(default_factory=list)
    # {(path, line, token)}
    spec_sites: set = field(default_factory=set)
    # mesh axis vocabulary + first sighting of each source kind
    axis_vocab: set = field(default_factory=set)
    # parameter-path name tokens
    param_names: set = field(default_factory=set)
    # [(path, line, var, producer, collective)]
    spec_drops: list = field(default_factory=list)

    def collect_source(self, path: str, source: str,
                       tree: ast.AST | None = None) -> None:
        if tree is None:
            tree = ast.parse(source, filename=path)
        _ShardCollector(self, norm_path(path)).visit(tree)


_NAME_RX = None


def _is_pathish(s: str) -> bool:
    """A name= literal that can be a parameter-path token (identifier
    segments, optionally /-joined) — tensor tags with dots or spaces
    ("statesync.flag.3") are wire names, not param-tree paths."""
    global _NAME_RX
    if _NAME_RX is None:
        import re
        _NAME_RX = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*(/[A-Za-z0-9_]+)*$")
    return bool(s) and len(s) <= 64 and bool(_NAME_RX.match(s))


class _ShardCollector(ast.NodeVisitor):
    def __init__(self, program: ShardProgram, path: str) -> None:
        self.p = program
        self.path = path
        # P(...) nodes already consumed as rule-table entries: their
        # tokens are checked through the table, not re-reported as
        # free-standing spec sites.
        self._consumed: set = set()

    # -- harvest --------------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and "AXES" in tgt.id.upper():
                self._harvest_axis_tuple(node.value)
        self.generic_visit(node)

    def _harvest_axis_tuple(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.Tuple, ast.List)) and node.elts and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in node.elts):
            self.p.axis_vocab.update(e.value for e in node.elts)
            return True
        return False

    def visit_Call(self, node: ast.Call) -> None:
        term = _terminal(node)
        if term == "ShardingRules" and node.args:
            self._harvest_rule_table(node)
        elif term in ("P", "PartitionSpec") and id(node) not in \
                self._consumed:
            tok = _spec_token_of_ast(node)
            if tok not in ("", "*"):
                self.p.spec_sites.add((self.path, node.lineno, tok))
        elif term == "Mesh":
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                self._harvest_axis_tuple(arg)
        elif term in ("MeshSpec", "build_mesh"):
            self.p.axis_vocab.update(
                kw.arg for kw in node.keywords if kw.arg)
        elif term == "param" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            if _is_pathish(node.args[0].value):
                self.p.param_names.add(node.args[0].value)
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str) and \
                    _is_pathish(kw.value.value):
                self.p.param_names.add(kw.value.value)
            elif kw.arg == "spec":
                tok = _spec_token_of_ast(kw.value)
                if tok not in ("", "*"):
                    self.p.spec_sites.add(
                        (self.path, kw.value.lineno, tok))
                self._consumed.add(id(kw.value))
        self.generic_visit(node)

    def _harvest_rule_table(self, node: ast.Call) -> None:
        table = node.args[0]
        if not isinstance(table, (ast.Tuple, ast.List)):
            return
        entries = []
        for elt in table.elts:
            if not (isinstance(elt, (ast.Tuple, ast.List))
                    and len(elt.elts) >= 2):
                return          # dynamic table: harvest nothing
            pat, spec = elt.elts[0], elt.elts[1]
            if not (isinstance(pat, ast.Constant)
                    and isinstance(pat.value, str)):
                return
            self._consumed.add(id(spec))
            entries.append((pat.value, _spec_token_of_ast(spec),
                            elt.lineno))
        if entries:
            self.p.rule_tables.append((self.path, node.lineno, entries))

    # -- HVD804: spec-producing locals into spec-less collectives -------
    def visit_FunctionDef(self, node) -> None:
        self._scan_func(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _scan_func(self, fn) -> None:
        spec_vars: dict[str, tuple[str, int]] = {}
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call):
                prod = _terminal(stmt.value)
                if prod in SPEC_PRODUCERS and \
                        self._produces_layout(stmt.value, prod):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            spec_vars[tgt.id] = (prod, stmt.lineno)
        if not spec_vars:
            return
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            coll = _terminal(call)
            if coll not in FLOW_COLLECTIVES:
                continue
            if any(kw.arg == "spec" for kw in call.keywords):
                continue
            for arg in call.args:
                if isinstance(arg, ast.Name) and arg.id in spec_vars:
                    prod, _ = spec_vars[arg.id]
                    self.p.spec_drops.append(
                        (self.path, call.lineno, arg.id, prod, coll))
                    break

    @staticmethod
    def _produces_layout(call: ast.Call, prod: str) -> bool:
        if prod != "device_put":
            return True
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Call) and \
                    _terminal(arg) in _SHARDING_CTORS:
                return True
        return False


# ---------------------------------------------------------------------------
def _candidate_paths(names) -> list[str]:
    """Synthesized parameter-path vocabulary: harvested name tokens,
    their /-joined pairs, and each with an implicit flax leaf appended
    — enough structure for a rule regex to be judged against without
    running any model."""
    toks = sorted(names)[:_MAX_NAME_TOKENS]
    cands = set(toks)
    for a in toks:
        for b in toks:
            if a != b:
                cands.add(f"{a}/{b}")
    for c in list(cands):
        for leaf in IMPLICIT_LEAVES:
            cands.add(f"{c}/{leaf}")
    return sorted(cands)


class ShardAnalysis:
    def __init__(self, program: Program, shard: ShardProgram) -> None:
        self.program = program
        self.shard = shard
        self.findings: list[Finding] = []

    def _emit(self, rule_key: str, severity: str, path: str, line: int,
              message: str, sites: tuple = ()) -> None:
        rule = RULES[rule_key]
        sup = self.program.suppressions.get(path)
        if sup and sup.active_span(line, line, rule):
            return
        self.findings.append(Finding(rule=rule, severity=severity,
                                     path=path, line=line,
                                     message=message, sites=sites))

    # -- HVD801 ---------------------------------------------------------
    def _check_rule_tables(self) -> None:
        if not self.shard.rule_tables:
            return
        cands = _candidate_paths(self.shard.param_names)
        for path, line, entries in self.shard.rule_tables:
            rules = [(pat, tok) for pat, tok, _ in entries]
            entry_line = {pat: ln for pat, _, ln in entries}
            dead, uncovered = rule_coverage(rules, cands)
            for pat in dead:
                self._emit(
                    "dead-partition-rule", "warning", path,
                    entry_line.get(pat, line),
                    f"partition rule {pat!r} matches none of the "
                    f"{len(cands)} parameter paths synthesized from the "
                    f"harvested name vocabulary (flax name=/self.param "
                    f"literals + implicit kernel/bias/scale/embedding "
                    f"leaves): the rule documents a layout no parameter "
                    f"gets — fix the regex or delete the row")
            seen = set()
            for cpath, sib in uncovered:
                if sib in seen:
                    continue        # one representative path per rule
                seen.add(sib)
                self._emit(
                    "dead-partition-rule", "warning", path,
                    entry_line.get(sib, line),
                    f"parameter path '{cpath}' falls through to the "
                    f"replicated default while sibling rule {sib!r} "
                    f"shards its neighbours under the same parent — "
                    f"replicating one tensor of a sharded family is "
                    f"usually an anchoring bug; name the path in a rule "
                    f"or justify the replication")

    # -- HVD802 ---------------------------------------------------------
    def _check_axis_vocab(self) -> None:
        vocab = self.shard.axis_vocab
        if not vocab:
            return   # no mesh literals harvested: nothing to judge against
        sites = list(self.shard.spec_sites)
        for path, line, entries in self.shard.rule_tables:
            sites.extend((path, ln, tok) for _, tok, ln in entries)
        for path, line, tok in sorted(set(sites)):
            bad = missing_axes(tok, vocab)
            if bad:
                self._emit(
                    "spec-mesh-axis-mismatch", "error", path, line,
                    f"sharding spec {tok} names mesh "
                    f"ax{'es' if len(bad) > 1 else 'is'} "
                    f"{', '.join(repr(a) for a in bad)} absent from the "
                    f"harvested axis vocabulary "
                    f"{sorted(vocab)} (DEFAULT_AXES assignments, "
                    f"Mesh(...) constructor literals, MeshSpec/"
                    f"build_mesh axis keywords): at runtime this raises "
                    f"only when the spec is applied — or silently "
                    f"replicates under a permissive resolver")

    # -- HVD804 ---------------------------------------------------------
    def _check_spec_drops(self) -> None:
        for path, line, var, prod, coll in self.shard.spec_drops:
            self._emit(
                "spec-drop", "warning", path, line,
                f"'{var}' carries a sharding layout (assigned from "
                f"{prod}(...)) but flows into {coll}(...) without "
                f"spec=: the wire packs dims and bytes while the "
                f"layout is discarded, so the collective's fingerprint "
                f"identity degrades to the 5-column op×name×dtype×dims "
                f"form and a cross-rank spec disagreement on this "
                f"tensor goes unwitnessed — pass spec= (hvdshard)")

    def analyze(self) -> "ShardAnalysis":
        self._check_rule_tables()
        self._check_axis_vocab()
        self._check_spec_drops()
        self.findings.sort(key=lambda f: (f.path, f.line, f.rule.id))
        return self


def analyze_shard(program: Program, shard: ShardProgram,
                  cfg=None) -> list[Finding]:
    """HVD801/802/804 from the harvest; HVD803 is emitted by the
    hvdflow pass (its spec-annotated streams) and merged by the caller
    — the lint driver's partition, or main() below."""
    findings = ShardAnalysis(program, shard).analyze().findings
    if cfg is not None:
        findings = [f for f in findings if cfg.wants(f.rule)]
    return findings


def analyze_paths(paths) -> list[Finding]:
    program = Program()
    flow = FlowProgram()
    shard = ShardProgram()
    for p in iter_python_files(list(paths)):
        try:
            with open(p, encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=p)
        except (OSError, SyntaxError):
            continue
        program.collect_source(p, src, tree)
        flow.collect_source(p, src, tree)
        shard.collect_source(p, src, tree)
    findings = [f for f in analyze_flow(program, flow)
                if f.rule.id in SHARD_RULE_IDS]
    findings.extend(analyze_shard(program, shard))
    findings.sort(key=lambda f: (f.path, f.line, f.rule.id))
    return findings


# --- CLI ---------------------------------------------------------------------
def main(argv: list[str] | None = None) -> int:
    import time as _time
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis.hvdshard",
        description="Sharding-spec static analysis "
                    "(HVD801-804; see docs/analysis.md).")
    parser.add_argument("paths", nargs="*", default=["horovod_tpu"])
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    args = parser.parse_args(argv)
    t0 = _time.monotonic()
    findings = analyze_paths(args.paths)
    wall_ms = round((_time.monotonic() - t0) * 1e3, 3)
    errors = [f for f in findings if f.severity == "error"]
    if args.format == "json":
        print(json.dumps({"shard": [f.json() for f in findings],
                          "wall_ms": wall_ms}, indent=2))
    elif args.format == "sarif":
        from ..hvdsan.san import sarif_payload
        print(json.dumps(sarif_payload(findings), indent=2))
    else:
        for f in findings:
            print(f.text())
        print(f"hvdshard: {len(errors)} error(s), "
              f"{len(findings) - len(errors)} warning(s) in "
              f"{', '.join(args.paths)} ({wall_ms:.1f} ms)",
              file=sys.stderr)
    return 1 if errors else 0
