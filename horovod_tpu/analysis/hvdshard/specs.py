"""Canonical sharding-spec tokens and the rule-table checks — the ONE
implementation shared by the static pass (HVD801/802), the runtime
validator (parallel/sharding.validate) and the collective fingerprint
fold (analysis/fingerprint.py).

Deliberately dependency-free: no jax import, no analysis-layer import —
this module must be loadable from the wire/fingerprint layer of a rank
that never touches jax, and from the analyzer running on a box with no
accelerator stack at all.

The canonical token grammar::

    ""            unannotated (legacy request; folds as absent)
    "*"           explicitly replicated (PartitionSpec())
    "(tp)"        dim 0 sharded over mesh axis tp
    "(dp+fsdp,*)" dim 0 over two axes, dim 1 replicated

Tokens are strings so they ride the sp_* wire fields and the
fingerprint fold byte-for-byte identically on every rank.
"""
from __future__ import annotations

import re

__all__ = ["spec_token", "fold_token", "token_axes", "missing_axes",
           "rule_coverage"]


def spec_token(spec=None) -> str:
    """Canonical token of a PartitionSpec-like value.

    Accepts None (unannotated), an already-canonical string (passed
    through), or any iterable of per-dim entries where each entry is
    None (replicated dim), an axis name, or a tuple/list of axis names
    (a dim sharded over several axes)."""
    if spec is None:
        return ""
    if isinstance(spec, str):
        return spec.strip()
    entries = []
    for e in spec:
        if e is None:
            entries.append("*")
        elif isinstance(e, (tuple, list)):
            entries.append("+".join(str(a) for a in e))
        else:
            entries.append(str(e))
    if not entries:
        return "*"
    return "(" + ",".join(entries) + ")"


def fold_token(op: str, token: str) -> str:
    """The token as folded into the cross-rank fingerprint: ALLGATHER's
    FIRST dim is rank-local by contract (the uneven-row gather rule in
    fingerprint.describe), so its dim-0 spec entry folds as ``*`` —
    a digest that included it would flag every legitimate uneven
    gather's per-rank layout as a divergence."""
    if op != "ALLGATHER" or not token.startswith("("):
        return token
    inner = token[1:-1].split(",")
    inner[0] = "*"
    return "(" + ",".join(inner) + ")"


def token_axes(token: str) -> set[str]:
    """Mesh axis names a canonical token references."""
    if not token or token == "*":
        return set()
    inner = token[1:-1] if token.startswith("(") else token
    axes = set()
    for entry in inner.split(","):
        for ax in entry.split("+"):
            ax = ax.strip()
            if ax and ax != "*":
                axes.add(ax)
    return axes


def missing_axes(token: str, mesh_axes) -> list[str]:
    """Axes the token names that the mesh does not carry (HVD802 core)."""
    vocab = set(mesh_axes)
    return sorted(a for a in token_axes(token) if a not in vocab)


def rule_coverage(rules, paths):
    """HVD801 core, shared by the static pass and runtime validate().

    ``rules``: ordered [(pattern_str, token)] — the ShardingRules table
    (first match wins).  ``paths``: the parameter path vocabulary
    ("layer/attn/wq/kernel" strings).

    Returns ``(dead_rules, uncovered)``:

    - ``dead_rules``: patterns matching no path at all — the rule
      documents a layout no parameter gets.
    - ``uncovered``: [(path, nearest_rule_pattern)] — paths that fall
      through to the replicated default while a SIBLING path (same
      parent prefix) matched a sharded (non-replicated) rule; the
      nearest rule named is the sibling's, the one most likely meant to
      cover this path too.
    """
    compiled = []
    for pat, tok in rules:
        try:
            compiled.append((pat, re.compile(pat), tok))
        except re.error:
            compiled.append((pat, None, tok))
    hits = {pat: 0 for pat, _, _ in compiled}
    matched_by = {}
    for path in paths:
        m = None
        for pat, rx, tok in compiled:
            if rx is not None and rx.search(path):
                m = (pat, tok)
                hits[pat] += 1
                break
        matched_by[path] = m

    dead = [pat for pat, rx, _ in compiled
            if rx is not None and hits[pat] == 0]

    def _parent(p: str) -> str:
        return p.rsplit("/", 1)[0] if "/" in p else ""

    # Parent-indexed sibling lookup: the candidate vocabulary can be
    # large (synthesized path combinations), so the uncovered scan must
    # stay linear, not all-pairs.
    sharded_sib: dict[str, str] = {}
    for path in sorted(matched_by):
        m = matched_by[path]
        if m is not None and m[1] not in ("", "*"):
            sharded_sib.setdefault(_parent(path), m[0])

    uncovered = []
    for path in sorted(matched_by):
        if matched_by[path] is not None:
            continue
        sib = sharded_sib.get(_parent(path))
        if sib is not None:
            uncovered.append((path, sib))
    return dead, uncovered
