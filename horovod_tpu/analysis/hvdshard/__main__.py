import sys

from .shard import main

if __name__ == "__main__":
    sys.exit(main())
