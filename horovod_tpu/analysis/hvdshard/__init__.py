"""hvdshard — sharding-spec static analysis + the op×spec identity core.

The sharding half of the analysis suite: hvdflow proves every rank runs
the same *sequence* of collectives; hvdshard proves they agree on the
*layout* each collective moves.  Collective identity becomes
op×name×dtype×dims×**spec**: the canonical spec token
(:func:`specs.spec_token`) enters the runtime fingerprint fold
(analysis/fingerprint.py), rides Request/Response as the
feature-bit-gated ``sp_*`` wire group (FEATURE_SHARDING,
PR-15 OPTIONAL_FIELD_FEATURES discipline, HVD505-enforced), and
annotates hvdflow stream tokens as ``op(name|spec)``.

Rules (``shard.py``; catalogue in docs/analysis.md):

- **HVD801 dead-partition-rule** — a ShardingRules regex matching no
  parameter path the harvested vocabulary can produce, or a path that
  falls through to the replicated default while a sibling path matched
  a sharded rule (the finding names the path and the nearest
  non-matching rule).
- **HVD802 spec-mesh-axis-mismatch** — a PartitionSpec literal naming
  a mesh axis absent from the harvested axis vocabulary (DEFAULT_AXES
  assignments, ``Mesh(...)`` constructor literals, MeshSpec fields).
- **HVD803 divergent-spec-collective** — rank-tainted branch arms
  sequence-equal on op×name but unequal on spec (emitted by the
  hvdflow pass over its spec-annotated streams; the runtime twin is
  the strict-mode fingerprint ERROR on the first spec-divergent op).
- **HVD804 spec-drop** — a sharded value (``shard_params`` /
  ``constrain`` / ``with_sharding_constraint`` / ``device_put`` with a
  NamedSharding) flowing into a collective call that serializes dims
  but discards the spec (no ``spec=``).

This ``__init__`` stays light — ``specs`` is dependency-free and is
imported by the fingerprint/wire layer of every rank; the whole-program
pass in ``shard`` (which drags in the lint/hvdsan/hvdflow machinery) is
resolved lazily.

CLI: ``python -m horovod_tpu.analysis.hvdshard`` (or ``lint --shard``
to ride the shared single-parse driver).  See docs/analysis.md.
"""
from .specs import (fold_token, missing_axes, rule_coverage,  # noqa: F401
                    spec_token, token_axes)

_LAZY = ("SHARD_RULE_IDS", "ShardProgram", "analyze_shard",
         "analyze_paths", "main")


def __getattr__(name: str):
    if name in _LAZY:
        from . import shard
        return getattr(shard, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
