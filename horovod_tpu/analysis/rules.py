"""hvdlint rule registry, violations, and suppression parsing.

Rules are identified both by a stable numeric id (``HVD1xx`` call-symmetry,
``HVD2xx`` barrier-tag discipline, ``HVD3xx`` lock discipline, ``HVD4xx``
thread-ownership) and a human slug.  Suppressions accept either form:

    do_collective()  # hvdlint: disable=rank-gated-collective -- <why>

A file-level escape hatch (``# hvdlint: disable-file=<rule>``) in the
first ten lines suppresses a rule for the whole file.  Every suppression
in this repository must carry a justifying comment after ``--`` (the
linter itself flags bare suppressions via ``bare-suppression``).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Rule:
    id: str
    slug: str
    summary: str


_RULE_LIST = [
    Rule("HVD101", "rank-gated-collective",
         "Collective/barrier called under a rank-dependent conditional: "
         "only a subset of ranks will submit it, and the peers hang."),
    Rule("HVD102", "rank-gated-early-return",
         "Collective/barrier reachable after a rank-dependent early "
         "return/raise: the exiting ranks never submit it."),
    Rule("HVD201", "duplicate-barrier-tag",
         "Two kv_barrier call sites share one tag literal: a barrier "
         "timeout can no longer be attributed to a call site."),
    Rule("HVD202", "dynamic-barrier-tag",
         "kv_barrier tag is not a string literal: it cannot be proven "
         "identical across ranks (a rank-dependent tag misaligns every "
         "later barrier)."),
    Rule("HVD301", "collective-under-lock",
         "Collective/barrier invoked while holding a lock: if the "
         "background coordination loop (or a peer's completion callback) "
         "takes the same lock, the world deadlocks."),
    Rule("HVD401", "shared-state-write",
         "Write to controller/tensor-queue/global shared state outside "
         "the owning module: the background thread owns that state; "
         "cross-thread writes race the coordination cycle."),
    Rule("HVD501", "lock-order-inversion",
         "Cycle in the whole-program lock-acquisition graph (hvdsan): "
         "two threads taking the same locks in opposite orders deadlock "
         "the world the first time their schedules interleave — impose "
         "one global order, or document the external ordering guarantee "
         "with a suppression on an edge site."),
    Rule("HVD502", "lock-held-across-blocking",
         "Lock held across a blocking primitive (socket recv/send, "
         "urlopen, thread join, wait, ...) or a collective, through any "
         "call depth (hvdsan's interprocedural generalization of "
         "HVD301): every thread needing the lock stalls for the full "
         "wait — release first, or record the bound in the ownership "
         "manifest's LOCK_HOLD_ALLOWED with its justification."),
    Rule("HVD503", "orphan-condition-wait",
         "Condition.wait whose condition is never notified by any code "
         "path (hvdsan): the predicate is written by no other thread, "
         "so the wait can only end by timeout — or never."),
    Rule("HVD504", "cross-thread-write",
         "Write to manifest-owned shared state (analysis/hvdsan/"
         "ownership.py) from a function reachable from a thread other "
         "than the declared owner: the write races the owning thread's "
         "protocol cycle."),
    Rule("HVD505", "wire-schema-drift",
         "Request/Response encode and decode disagree on the wire field "
         "sequence, or use a primitive common/wire.py does not define "
         "on both sides: every frame after the drifting field decodes "
         "garbage on the peer (the fp_*/tm_*/trace_* growth pattern "
         "with no cross-check)."),
    Rule("HVD506", "spec-conformance",
         "The implementation drifted from a co-located hvdmc protocol "
         "spec (statesync/specs.py, resilience/specs.py), in either "
         "direction: a frame verb or handler branch the spec does not "
         "know (the model checker never explores it), or a spec "
         "transition whose bound function, required call, or message "
         "vocabulary no longer exists in the code (the checker "
         "verifies a protocol nobody runs).  Update the spec and the "
         "code in the same change."),
    Rule("HVD601", "divergent-collective",
         "A collective effect is reachable under one arm of a "
         "rank-tainted branch with no sequence-equal effect on the "
         "sibling arm (hvdflow, interprocedural): the gated ranks "
         "submit a different collective stream than their peers and "
         "the negotiation wedges — exactly the divergence runtime "
         "fingerprinting (HOROVOD_FINGERPRINT) reports as a "
         "structured ERROR.  Rank-0-only non-collective work (logging, "
         "checkpoint writes) stays legal: both arms' streams are "
         "empty and therefore equal."),
    Rule("HVD602", "divergent-loop-trip",
         "Collective effect inside a loop whose trip count is "
         "rank-tainted (e.g. `for _ in range(rank)` or a while on a "
         "rank-derived bound, hvdflow): ranks execute the collective a "
         "different number of times, shifting every later op in the "
         "stream — the off-by-one twin of HVD601 that per-line rules "
         "cannot see."),
    Rule("HVD603", "unbounded-serve-wait",
         "A blocking wait reachable from the serving dispatch path "
         "with no deadline_scope/op_scope/op_timeout bound on any "
         "interprocedural path (hvdflow's flow-aware upgrade of "
         "HVD1003): one dead peer or wedged handoff then stalls the "
         "serve loop past every request's SLO — bound the wait from "
         "the request deadline (resilience.deadline_scope) or justify "
         "the external bound with a suppression."),
    Rule("HVD604", "unregistered-knob-read",
         "os.environ/getenv read of a HOROVOD_* name that is not "
         "declared in the typed knob registry (common/config.py): "
         "undeclared knobs have no type, no default, no doc line and "
         "never appear in docs/configuration.md or the operator "
         "console — register the knob (name, type, default, doc) and "
         "read it through the registry, or justify the raw read with "
         "a suppression."),
    Rule("HVD801", "dead-partition-rule",
         "Sharding rule whose regex matches no parameter path reachable "
         "from the Trainer/serving model init, or a parameter path that "
         "falls through to the replicated default while a sibling path "
         "matched a sharded rule (hvdshard): the dead rule documents a "
         "layout nobody gets, and the fallen-through param silently "
         "re-replicates — rename the pattern to match the model's "
         "actual param paths (the finding names the nearest "
         "non-matching rule), or delete it."),
    Rule("HVD802", "spec-mesh-axis-mismatch",
         "PartitionSpec naming a mesh axis absent from every Mesh "
         "construction the call site can reach (hvdshard): "
         "jax.sharding raises at device_put time on the real mesh, or "
         "— worse — a size-1 stand-in axis silently replicates the "
         "dim. The mesh axis vocabulary is harvested from "
         "parallel/mesh.py DEFAULT_AXES and every literal Mesh(...) "
         "axis tuple; name an axis the mesh actually carries."),
    Rule("HVD803", "divergent-spec-collective",
         "Rank-tainted branch whose collective arm streams are "
         "sequence-equal on op×name but unequal on sharding spec "
         "(hvdshard's spec column over HVD601's arm-stream evidence): "
         "every rank submits the same ops, so negotiation proceeds — "
         "and then the data plane moves differently-sharded bytes into "
         "one reduction, corrupting silently where HVD601's shape "
         "would at least wedge.  The runtime twin is the strict-mode "
         "fingerprint ERROR on the first spec-divergent op."),
    Rule("HVD804", "spec-drop",
         "A value produced by a spec-carrying site (shard_params/"
         "constrain/with_sharding_constraint/NamedSharding device_put) "
         "flows into a collective that serializes dims but not the "
         "spec (no spec= at the call site, hvdshard): the wire "
         "re-replicates the tensor and the receiving ranks cannot "
         "detect the layout loss — thread the spec through "
         "(spec=, or spec_token(...)), or drop the annotation "
         "explicitly."),
    Rule("HVD701", "unjoined-thread",
         "Thread/Timer started with no join/cancel reachable from the "
         "owner's teardown path (hvdlife): every start leaks one live "
         "thread per acquisition — across elastic reinit cycles that is "
         "one thread per epoch, forever.  Join it from shutdown/close/"
         "stop (poison first, like _PeerChannel.close), record the "
         "intentional hold in LIFECYCLE_ALLOWED with its justification, "
         "or suppress at the start site."),
    Rule("HVD702", "unreleased-channel",
         "Socket/_PeerChannel/PeerMesh/HTTP-server acquisition with no "
         "close reachable from the owner's teardown path (hvdlife): the "
         "fd and its kernel buffers survive the world that created them "
         "— a long-lived process re-forming its world per elastic "
         "transition accumulates one dead connection set per epoch."),
    Rule("HVD703", "unreleased-region",
         "mmap region or opened file with no close/munmap reachable "
         "from the owner's teardown path (hvdlife): the mapping pins "
         "pages (and /dev/shm backing) past the world that staged "
         "through it; an unflushed file handle also loses its tail on "
         "hard exit."),
    Rule("HVD704", "epoch-scoped-leak",
         "Resource acquired under a world epoch (reachable from "
         "core.init/reinit_world) with NO release reachable from the "
         "teardown half of the transition (core.shutdown / "
         "reinit_world) — the elastic-specific leak no per-site rule "
         "can see: correct for one world, it leaks one resource per "
         "grow/shrink/recovery cycle, and ROADMAP's unified-fleet "
         "posture makes those cycles routine.  The runtime census "
         "witness (HOROVOD_LIFE_CENSUS) is this rule's dynamic twin."),
    Rule("HVD705", "blocking-thread-without-wakeup",
         "Thread whose body blocks unboundedly (queue get, recv, "
         "accept, wait) while its owner has no wakeup path — no "
         "poison-pill put(None), no close/shutdown/cancel/set in any "
         "teardown-reachable function (hvdlife): the static twin of "
         "the PR 5 wedged-sender fix — join-without-poison waits out "
         "the full grace and then leaks the thread anyway.  Poison "
         "first, then join."),
    Rule("HVD901", "bare-suppression",
         "hvdlint suppression without a '-- <justification>' comment."),
    Rule("HVD902", "syntax-error",
         "File could not be parsed; nothing in it was analyzed."),
    Rule("HVD1001", "thread-spawn-in-backend",
         "threading.Thread constructed inside a backend/ hot path: "
         "per-op thread spawn scales with ring steps (the regression the "
         "pipelined data plane removed); use the transport's persistent "
         "per-peer sender lanes (runner/network.py PeerMesh.send_async) "
         "instead."),
    Rule("HVD1002", "blocking-io-in-hot-path",
         "Blocking I/O (open/print/socket send*) inside a dispatch/"
         "backend hot-path function (or anywhere in telemetry/, which "
         "ships in-process with the data plane): file and terminal I/O "
         "on the dispatch path perturbs the very latencies the "
         "observability layer measures — route output through the "
         "timeline's async writer or the telemetry exporter thread."),
    Rule("HVD1003", "unbounded-blocking-wait",
         "recv/join/wait/urlopen without a timeout/deadline argument in "
         "a transport or backend module: an unbounded wait is how a "
         "dead or wedged peer turns into a whole-job deadlock — bound "
         "it with a timeout, derive a deadline from the "
         "ResilienceContext (resilience/), or justify why the wait is "
         "bounded elsewhere with a suppression."),
    Rule("HVD1005", "unbalanced-span",
         "Timeline activity_start in a backend/ module without a "
         "finally-guarded activity_end: an exception between the two "
         "leaves the span open, corrupting every later span on that "
         "tensor's trace lane (and the merged cross-rank trace built "
         "from it) — wrap the op body in try/finally with the end call "
         "in the finally block."),
    Rule("HVD1006", "unbounded-queue-in-serving",
         "Unbounded queue construction (Queue() without maxsize, any "
         "SimpleQueue) or blocking put/get without a timeout/deadline "
         "in a serving/ module: an unbounded ingress queue converts "
         "overload into unbounded latency for every later request, and "
         "an unbounded blocking put/get wedges the serve loop exactly "
         "like an unbounded transport wait (HVD1003) — bound the queue, "
         "shed at the door, and pass timeouts derived from request "
         "deadlines."),
    Rule("HVD1007", "unverified-state-frame",
         "Streamed-state consumption (unflatten_state / a frame-payload "
         "apply) in a statesync/ module inside a function with no "
         "digest/stamp verification call in scope: bytes that crossed "
         "the wire from a peer are only state after the FNV digest and "
         "(epoch, step) stamp checked out — a torn or stale snapshot "
         "applied unverified silently diverges the joiner from every "
         "donor.  Verify first (JoinerPuller.verify_round / "
         "state_digest against the stamp), or justify the read with a "
         "suppression."),
    Rule("HVD1004", "per-segment-codec-loop",
         "compress/ codec call (quantize/dequantize/from_bytes/to_bytes) "
         "inside a loop in a backend/ module: the per-segment "
         "Python-level dequant→reduce→requant chain allocates every leg "
         "and forfeits the single-pass fused kernels "
         "(compress/fused.py) — consume arriving segments with "
         "FusedKernels.decode_add and emit wire images with "
         "FusedKernels.encode instead."),
]

RULES: dict[str, Rule] = {}
for _r in _RULE_LIST:
    # Rule-id/slug uniqueness across every family (hvdlint, hvdsan,
    # hvdmc, hvdflow) is asserted at registry build time: a duplicate
    # would silently shadow an existing rule's summary and suppression
    # key, so it fails the import instead.
    if _r.id in RULES:
        raise AssertionError(
            f"duplicate rule id {_r.id!r}: already registered as "
            f"[{RULES[_r.id].slug}]")
    if _r.slug in RULES:
        raise AssertionError(
            f"duplicate rule slug {_r.slug!r}: already registered as "
            f"{RULES[_r.slug].id}")
    RULES[_r.id] = _r
    RULES[_r.slug] = _r


def undocumented_rules(doc_text: str) -> list[str]:
    """Rule ids with no ``| HVDxxx |`` row in the given documentation
    text (docs/analysis.md's rule tables) — the generated-or-verified
    contract: a new rule cannot land undocumented (CI asserts this
    returns [])."""
    return sorted(r.id for r in set(RULES.values())
                  if f"| {r.id} |" not in doc_text)


@dataclass
class Violation:
    path: str
    line: int
    col: int
    rule: Rule
    message: str

    def text(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule.id} [{self.rule.slug}] {self.message}")

    def json(self) -> dict:
        return {"path": self.path, "line": self.line, "col": self.col,
                "rule": self.rule.id, "slug": self.rule.slug,
                "message": self.message}


_SUPPRESS_RE = re.compile(
    r"#\s*hvdlint:\s*(disable(?:-file)?)\s*=\s*([\w,\s-]+?)"
    r"(?:\s*--\s*(.*))?\s*$")


@dataclass
class Suppressions:
    """Per-file suppression table parsed from source comments."""
    by_line: dict[int, set[str]] = field(default_factory=dict)
    file_wide: set[str] = field(default_factory=set)
    # Suppression comments missing a justification ("-- why"), for HVD901.
    bare: list[tuple[int, str]] = field(default_factory=list)

    def active(self, line: int, rule: Rule) -> bool:
        keys = {rule.id, rule.slug, "all"}
        if keys & self.file_wide:
            return True
        return bool(keys & self.by_line.get(line, set()))

    def active_span(self, start: int, end: int, rule: Rule) -> bool:
        """True when the rule is suppressed anywhere in the physical
        line range ``start..end`` (inclusive) — a suppression anchors
        to the whole *statement*, not one physical line, so a comment
        on the closing line of a multi-line call (or on the ``def``
        line of a decorated function) still covers it."""
        keys = {rule.id, rule.slug, "all"}
        if keys & self.file_wide:
            return True
        return any(keys & self.by_line.get(ln, set())
                   for ln in range(start, end + 1))


def parse_suppressions(source: str) -> Suppressions:
    sup = Suppressions()
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        kind, rules_raw, why = m.group(1), m.group(2), m.group(3)
        names = {r.strip() for r in rules_raw.split(",") if r.strip()}
        if not (why and why.strip()):
            sup.bare.append((lineno, text.strip()))
        if kind == "disable-file" and lineno <= 10:
            sup.file_wide |= names
        else:
            sup.by_line.setdefault(lineno, set()).update(names)
    return sup
