"""Runtime collective fingerprinting — the dynamic half of hvdlint.

Every rank folds each submitted collective request — (op, tensor name,
dtype, dims, codec) — into a rolling 64-bit hash, in submission order.
The per-rank (sequence, digest) pair plus a bounded tail of recent op
records ride the existing RequestList gather, so the coordinator can
compare the streams whenever negotiation happens and turn cross-rank
divergence into a structured ``Response.ERROR`` naming the FIRST
divergent op — long before the stall inspector's 60s warning, and
instead of the silent hang the reference runtime exhibits when ranks
disagree on *which* collectives to run (the controller's per-tensor
validation only catches disagreement on a collective's *parameters*).

Modes (``HOROVOD_FINGERPRINT``):

- ``off``    — no folding, no wire overhead (default).
- ``cycle``  — fingerprints compared on every natural negotiation cycle.
  Cache steady state (which never ships RequestLists) is not re-checked
  until the next negotiation, so detection can lag by however long the
  cache keeps hitting.
- ``strict`` — additionally forces a negotiation heartbeat every cycle,
  so divergence is caught within one background-loop cycle even in cache
  steady state, at the cost of steady-state RequestList traffic.

The comparison is sequence-aligned: ranks legitimately run ahead of each
other (that transient is the stall inspector's domain), so digests are
only compared at the highest sequence number every rank has reached, and
the divergence point is located by walking the shipped tails backward to
the smallest commonly-visible sequence where digests disagree.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ..common import config
from ..common.message import Request, RequestType
from .hvdshard.specs import fold_token

_MASK = (1 << 64) - 1
_FNV_OFFSET = 0xcbf29ce484222325
_FNV_PRIME = 0x100000001b3


def _fnv1a(data: bytes, h: int = _FNV_OFFSET) -> int:
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK
    return h


class FingerprintMode(enum.Enum):
    OFF = "off"
    CYCLE = "cycle"
    STRICT = "strict"

    @classmethod
    def parse(cls, raw: str) -> "FingerprintMode":
        try:
            return cls(str(raw).strip().lower())
        except ValueError:
            return cls.OFF


@dataclass(frozen=True)
class OpRecord:
    """One folded op: the rolling digest AFTER folding it."""
    seq: int
    digest: int
    descriptor: str

    @property
    def tensor_name(self) -> str:
        parts = self.descriptor.split("|")
        return parts[1] if len(parts) > 1 else self.descriptor


@dataclass
class Divergence:
    """First cross-rank disagreement the coordinator could locate."""
    seq: int
    # rank -> descriptor at `seq` (only ranks whose tail still covers it).
    descriptors: dict[int, str] = field(default_factory=dict)
    exact: bool = True   # False: diverged at-or-before `seq` (window edge)

    def tensor_names(self) -> list[str]:
        names = []
        for desc in self.descriptors.values():
            parts = desc.split("|")
            name = parts[1] if len(parts) > 1 else desc
            if name not in names:
                names.append(name)
        return sorted(names)

    def _spec_divergent(self) -> bool:
        """True when any located descriptor carries a sharding-spec
        column (the op×spec identity class: ops may agree while the
        spec disagrees)."""
        return any(len(d.split("|")) >= 6 and d.split("|")[5]
                   for d in self.descriptors.values())

    def message(self) -> str:
        by_rank = ", ".join(
            f"rank {r}: {_pretty(d)}"
            for r, d in sorted(self.descriptors.items()))
        where = (f"at op #{self.seq}" if self.exact
                 else f"at or before op #{self.seq} (divergence predates "
                      f"the fingerprint window; raise "
                      f"HOROVOD_FINGERPRINT_WINDOW to pin it exactly)")
        if self._spec_divergent():
            hint = (f"Every rank must submit the same collectives — "
                    f"op, name, dims AND sharding spec — in the same "
                    f"order; check for rank-gated collective or spec "
                    f"choices (hvdshard: python -m "
                    f"horovod_tpu.analysis.lint --shard reports the "
                    f"same spec-annotated per-arm streams as HVD803).")
        else:
            hint = (f"Every rank must submit the same collectives in "
                    f"the same order; check for rank-gated collective "
                    f"calls (hvdlint/hvdflow: python -m "
                    f"horovod_tpu.analysis.lint --flow reports the "
                    f"same per-arm op streams as HVD601).")
        return (f"Collective fingerprint divergence {where}: {by_rank}. "
                + hint)


def _pretty(descriptor: str) -> str:
    parts = descriptor.split("|")
    if len(parts) >= 4:
        op, name, dtype, dims = parts[:4]
        shape = dims or "scalar"
        if len(parts) >= 6 and parts[5]:
            return f"{op}({name}, {dtype}, shape={shape}, spec={parts[5]})"
        return f"{op}({name}, {dtype}, shape={shape})"
    return descriptor


def describe(req: Request, with_spec: bool = False) -> str:
    """Canonical descriptor folded into the hash:
    op|name|dtype|dims|codec[|spec].

    ALLGATHER's FIRST dim is rank-local by contract (uneven-row gather
    is the documented semantic — allgather_object payloads, serving
    completion exchanges), so it folds as ``*``: a cross-rank digest
    that included it would flag every legitimate uneven gather as a
    divergence.  Trailing dims must still agree.

    With ``with_spec`` (the tracker's fold_spec flag: on only when the
    mesh negotiated FEATURE_SHARDING, so every rank folds the same
    bytes), a non-empty ``sp_spec`` token appends as a sixth column —
    folded through :func:`hvdshard.specs.fold_token`, which wildcards
    ALLGATHER's rank-local dim-0 entry exactly like the shape rule
    above.  Unannotated requests keep the 5-column descriptor
    byte-identical to pre-sharding builds."""
    shape = list(req.tensor_shape)
    parts = [str(int(d)) for d in shape]
    from ..common.message import RequestType
    if req.request_type == RequestType.ALLGATHER and parts:
        parts[0] = "*"
    dims = "x".join(parts)
    desc = (f"{req.request_type.name}|{req.tensor_name}|"
            f"{req.tensor_type.name}|{dims}|"
            f"{req.codec}/{req.codec_block_size}")
    spec = getattr(req, "sp_spec", "")
    if with_spec and spec:
        desc += "|" + fold_token(req.request_type.name, spec)
    return desc


class FingerprintTracker:
    """Per-rank rolling fingerprint + coordinator-side comparison.

    Single-threaded by design: fold/snapshot run on the background
    coordination thread only (the same thread that owns the controller),
    so no locking is needed — and hvdlint's shared-state-write rule is
    exactly the guard that keeps it that way.
    """

    def __init__(self, mode: FingerprintMode | str = FingerprintMode.OFF,
                 window: int = 64) -> None:
        if isinstance(mode, str):
            mode = FingerprintMode.parse(mode)
        self.mode = mode
        self.window = max(int(window), 1)
        # Spec column gate: the controller sets this from the mesh's
        # negotiated features (FEATURE_SHARDING) — identical on every
        # rank by the HELLO min-proto/AND construction, so either all
        # ranks fold the spec column or none do.  A mixed-proto world
        # that negotiated sp_* away stays fingerprint-green.
        self.fold_spec = True
        self.seq = 0
        self.digest = _FNV_OFFSET
        self._tail: list[OpRecord] = []
        self._reported = False

    @classmethod
    def from_config(cls) -> "FingerprintTracker":
        return cls(FingerprintMode.parse(config.FINGERPRINT.get()),
                   config.FINGERPRINT_WINDOW.get())

    @property
    def enabled(self) -> bool:
        return self.mode is not FingerprintMode.OFF

    @property
    def strict(self) -> bool:
        return self.mode is FingerprintMode.STRICT

    # --- worker side -------------------------------------------------------
    def fold(self, req: Request) -> None:
        """Fold one submitted request, once (re-queued cache hits pass
        through compute_response_list again and must not double-count).
        JOIN is excluded: joining is rank-asymmetric by design."""
        if not self.enabled or req.request_type == RequestType.JOIN:
            return
        if getattr(req, "_fp_folded", False):
            return
        req._fp_folded = True  # type: ignore[attr-defined]
        desc = describe(req, with_spec=self.fold_spec)
        self.seq += 1
        self.digest = _fnv1a(desc.encode(), self.digest)
        self._tail.append(OpRecord(self.seq, self.digest, desc))
        if len(self._tail) > self.window:
            del self._tail[0]

    def snapshot(self) -> tuple[int, int, list[OpRecord]]:
        return self.seq, self.digest, list(self._tail)

    # --- coordinator side --------------------------------------------------
    def check_gathered(
            self,
            per_rank: list[tuple[int, int, list[OpRecord]]]
    ) -> Divergence | None:
        """Compare gathered (seq, digest, tail) triples; None = consistent
        (or not comparable yet).  Reports at most once per tracker: a
        divergent stream stays divergent, and one structured error is the
        actionable signal — repeating it every cycle would bury it."""
        if not self.enabled or self._reported or len(per_rank) < 2:
            return None
        div = find_divergence(per_rank)
        if div is not None:
            self._reported = True
        return div

    def reset(self) -> None:
        self.seq = 0
        self.digest = _FNV_OFFSET
        self._tail.clear()
        self._reported = False


def find_divergence(
        per_rank: list[tuple[int, int, list[OpRecord]]]
) -> Divergence | None:
    """Locate the first divergent op across per-rank fingerprint streams.

    Digests are comparable only at equal sequence numbers, so the probe
    set is the intersection of sequences every rank can still produce a
    digest for (its current head plus its shipped tail), capped at the
    slowest rank's head.  Within that set the first sequence where
    digests disagree is the divergence point; if even the earliest
    commonly-visible sequence disagrees, the true first divergence
    scrolled out of the window and is reported as inexact.
    """
    heads = [seq for seq, _, _ in per_rank]
    common_head = min(heads)
    if common_head <= 0:
        return None

    # rank -> {seq: digest}, rank -> {seq: descriptor}
    digests: list[dict[int, int]] = []
    descs: list[dict[int, str]] = []
    for seq, digest, tail in per_rank:
        d = {rec.seq: rec.digest for rec in tail}
        d[seq] = digest
        digests.append(d)
        descs.append({rec.seq: rec.descriptor for rec in tail})

    probe_seqs = set(digests[0])
    for d in digests[1:]:
        probe_seqs &= set(d)
    probe_seqs = sorted(s for s in probe_seqs if 0 < s <= common_head)
    if not probe_seqs:
        return None   # windows no longer overlap: not comparable

    latest = probe_seqs[-1]
    if len({d[latest] for d in digests}) == 1:
        return None   # consistent up to the slowest rank's head

    first = next(s for s in probe_seqs
                 if len({d[s] for d in digests}) > 1)
    # `first` is exact iff an earlier probe sequence agreed (every probe
    # before `first` did, by construction) or it is op #1; when the
    # earliest commonly-visible sequence already disagrees, the true
    # first divergence scrolled out of the window.
    exact = first == 1 or probe_seqs[0] < first
    divergence = Divergence(seq=first, exact=exact)
    for rank, dd in enumerate(descs):
        if first in dd:
            divergence.descriptors[rank] = dd[first]
    if not divergence.descriptors:
        # Head-only digest (empty tails): name nothing but still report.
        divergence.exact = False
    return divergence
