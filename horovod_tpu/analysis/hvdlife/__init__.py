"""hvdlife — whole-program resource-lifecycle analysis + runtime
census witness (HVD701-705; see docs/analysis.md).

- :mod:`.life` — the static pass: acquisition harvest, release-verb
  pairing, teardown reachability over the hvdsan call graph, the
  epoch-scoped-leak rule, and the ``LIFECYCLE_ALLOWED`` manifest.
- :mod:`.census` — the runtime twin: a thread/fd/socket/mmap census
  snapshotted around world transitions (``HOROVOD_LIFE_CENSUS``),
  dumped rank-stamped like the hvdsan witness, diffed against its own
  baseline in CI.

Rides the single-parse lint driver (``python -m
horovod_tpu.analysis.lint --life``) and runs standalone as
``python -m horovod_tpu.analysis.hvdlife``.
"""
from .census import (CensusWitness, census_diff, dump_census,
                     load_census_dumps, take_census, witness)
from .life import (LIFECYCLE_ALLOWED, LIFE_RULE_IDS, LifeAnalysis,
                   LifeProgram, analyze_life, analyze_paths)

__all__ = [
    "CensusWitness", "LIFECYCLE_ALLOWED", "LIFE_RULE_IDS",
    "LifeAnalysis", "LifeProgram", "analyze_life", "analyze_paths",
    "census_diff", "dump_census", "load_census_dumps", "take_census",
    "witness",
]
