"""Runtime resource census — the dynamic twin of the hvdlife static
pass (HVD704's witness).

A census is one snapshot of the process's live resources:

- **threads** by *normalized* name (``hvd-send-3`` → ``hvd-send-*``,
  ``Thread-12`` → ``Thread-*``) with counts — the per-peer/per-stream
  numbering must not make two healthy worlds look different;
- **fds** from ``/proc/self/fd`` classified by target (``sockets``,
  ``shm_fds``, ``pipes``, ``files``, total ``fds``);
- **shm_maps**: ``/dev/shm``-backed regions in ``/proc/self/maps`` —
  the shm staging plane's mmap footprint (anonymous maps are malloc
  noise and deliberately excluded).

Under ``HOROVOD_LIFE_CENSUS=1`` the process-global :class:`
CensusWitness` snapshots around every world transition (``core.init``
tail, ``core.reinit_world`` entry) and dumps rank-stamped JSON at
shutdown/atexit (``HOROVOD_LIFE_CENSUS_FILE``), exactly like the
hvdsan lock witness.  CI diffs the snapshots: after an elastic cycle
returns the world to its original shape, the census must equal the
baseline — a drift names the leaked resource class the static pass
should have caught (and the seeded HVD704 fixture proves both halves
fire on the same leak).

Off mode is the usual zero-cost contract: one cached knob read, no
snapshots, no /proc reads, no files.
"""
from __future__ import annotations

import json
import os
import threading

__all__ = ["CensusWitness", "census_diff", "dump_census",
           "load_census_dumps", "take_census", "witness"]

# Census keys compared by census_diff (fds total is reported but not
# diffed by default: harness pipes/log handles churn legitimately).
DIFF_KEYS = ("threads", "sockets", "shm_fds", "shm_maps")


def _normalize_thread(name: str) -> str:
    """Collapse per-peer/per-stream numbering so two healthy worlds of
    the same shape census identically."""
    base = name.rstrip("0123456789")
    if base != name and base.endswith(("-", "_")):
        return base + "*"
    return name


def take_census(label: str = "") -> dict:
    threads: dict[str, int] = {}
    for t in threading.enumerate():
        if not t.is_alive():
            continue
        key = _normalize_thread(t.name)
        threads[key] = threads.get(key, 0) + 1
    out = {"label": label, "threads": dict(sorted(threads.items())),
           "fds": 0, "sockets": 0, "shm_fds": 0, "pipes": 0,
           "files": 0, "shm_maps": 0}
    fd_dir = "/proc/self/fd"
    try:
        entries = os.listdir(fd_dir)
    except OSError:
        entries = []
    for fd in entries:
        try:
            target = os.readlink(os.path.join(fd_dir, fd))
        except OSError:
            continue           # the fd of the listdir itself, races
        out["fds"] += 1
        if target.startswith("socket:"):
            out["sockets"] += 1
        elif target.startswith("/dev/shm/"):
            out["shm_fds"] += 1
        elif target.startswith("pipe:"):
            out["pipes"] += 1
        elif target.startswith("/"):
            out["files"] += 1
    try:
        with open("/proc/self/maps") as f:
            out["shm_maps"] = sum(1 for line in f
                                  if "/dev/shm/" in line)
    except OSError:
        pass
    return out


def socket_details() -> list[str]:
    """Endpoint description of every live socket fd ("tcp
    127.0.0.1:4242 -> 127.0.0.1:9999 ESTABLISHED"), by joining
    /proc/self/fd inodes against /proc/net/tcp{,6} — the census
    drift diagnostic: a leaked-socket finding should name the peer."""
    states = {"01": "ESTABLISHED", "02": "SYN_SENT", "03": "SYN_RECV",
              "04": "FIN_WAIT1", "05": "FIN_WAIT2", "06": "TIME_WAIT",
              "07": "CLOSE", "08": "CLOSE_WAIT", "09": "LAST_ACK",
              "0A": "LISTEN", "0B": "CLOSING"}

    def _addr(hexaddr: str) -> str:
        ip, _, port = hexaddr.partition(":")
        if len(ip) == 8:
            octets = [str(int(ip[i:i + 2], 16))
                      for i in range(6, -2, -2)]
            host = ".".join(octets)
        else:
            host = ip
        return f"{host}:{int(port, 16)}"

    table: dict[str, str] = {}
    for proto in ("tcp", "tcp6", "udp", "udp6"):
        try:
            with open(f"/proc/net/{proto}") as f:
                next(f)
                for line in f:
                    parts = line.split()
                    inode = parts[9]
                    table[inode] = (
                        f"{proto} {_addr(parts[1])} -> "
                        f"{_addr(parts[2])} "
                        f"{states.get(parts[3], parts[3])}")
        except (OSError, StopIteration, IndexError):
            continue
    out = []
    fd_dir = "/proc/self/fd"
    try:
        entries = os.listdir(fd_dir)
    except OSError:
        return out
    for fd in entries:
        try:
            target = os.readlink(os.path.join(fd_dir, fd))
        except OSError:
            continue
        if target.startswith("socket:["):
            inode = target[len("socket:["):-1]
            out.append(f"fd {fd}: "
                       f"{table.get(inode, f'socket inode {inode}')}")
    return sorted(out)


def census_diff(baseline: dict, now: dict,
                keys=DIFF_KEYS) -> list[str]:
    """Human-readable drift of ``now`` against ``baseline`` (empty =
    the resource fabric returned to its baseline shape)."""
    problems: list[str] = []
    for key in keys:
        if key == "threads":
            a = baseline.get("threads", {})
            b = now.get("threads", {})
            for name in sorted(set(a) | set(b)):
                ca, cb = a.get(name, 0), b.get(name, 0)
                if ca != cb:
                    problems.append(
                        f"threads[{name}]: {ca} -> {cb} "
                        f"({'leaked' if cb > ca else 'lost'} "
                        f"{abs(cb - ca)})")
        else:
            ca, cb = baseline.get(key, 0), now.get(key, 0)
            if ca != cb:
                problems.append(f"{key}: {ca} -> {cb} "
                                f"({'+' if cb > ca else ''}{cb - ca})")
    return problems


# ---------------------------------------------------------------------------
# The witness (HOROVOD_LIFE_CENSUS)
# ---------------------------------------------------------------------------
class CensusWitness:
    """Labeled census snapshots around world transitions, dumped
    rank-stamped at exit — the hvdsan witness mold."""

    def __init__(self, enabled: bool) -> None:
        self.enabled = enabled
        self.rank = 0
        self.snapshots: list[dict] = []
        self._lock = threading.Lock()

    def note(self, label: str, rank: int | None = None) -> dict | None:
        if not self.enabled:
            return None
        snap = take_census(label)
        with self._lock:
            if rank is not None:
                self.rank = rank
            self.snapshots.append(snap)
        return snap

    def payload(self) -> dict:
        with self._lock:
            return {"rank": self.rank,
                    "snapshots": list(self.snapshots)}


_witness: CensusWitness | None = None
_atexit_registered = False


def witness() -> CensusWitness:
    """The process witness; enabled iff HOROVOD_LIFE_CENSUS (checked
    once — the knob is launcher-set, never flipped mid-run)."""
    global _witness, _atexit_registered
    if _witness is None:
        from ...common import config
        _witness = CensusWitness(bool(config.LIFE_CENSUS.get()))
        if _witness.enabled and not _atexit_registered:
            import atexit
            atexit.register(dump_census)
            _atexit_registered = True
    return _witness


def _rank_path(path: str, rank: int) -> str:
    if "{rank}" in path:
        return path.format(rank=rank)
    if rank == 0:
        return path
    root, dot, ext = path.rpartition(".")
    return f"{root}.r{rank}.{ext}" if dot else f"{path}.r{rank}"


def dump_census(path: str | None = None) -> str | None:
    """Write the witness snapshots as rank-stamped JSON (write-then-
    rename, the flight-dump discipline: a concurrent reader never sees
    a torn file); returns the path, or None when off/empty."""
    w = _witness
    if w is None or not w.enabled or not w.snapshots:
        return None
    payload = w.payload()
    if path is None:
        from ...common import config
        path = config.LIFE_CENSUS_FILE.get()
    path = _rank_path(path, payload["rank"])
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
    except OSError:
        return None
    return path


def load_census_dumps(paths) -> list[dict]:
    out = []
    for p in paths:
        with open(p) as f:
            out.append(json.load(f))
    return out


def check_dumps(payloads) -> list[str]:
    """CI check: within each rank's dump, the LAST snapshot labeled
    like the FIRST (same world shape) must census-equal it.  The
    convention: the battery labels its baseline and its return-to-
    baseline snapshot with the same ``baseline:`` prefix."""
    problems: list[str] = []
    for payload in payloads:
        rank = payload.get("rank", "?")
        snaps = payload.get("snapshots", [])
        base = next((s for s in snaps
                     if s.get("label", "").startswith("baseline")),
                    None)
        if base is None:
            continue
        finals = [s for s in snaps
                  if s.get("label", "").startswith("baseline")
                  and s is not base]
        for fin in finals:
            for problem in census_diff(base, fin):
                problems.append(
                    f"rank {rank} [{base['label']} -> "
                    f"{fin['label']}]: {problem}")
    return sorted(problems)
