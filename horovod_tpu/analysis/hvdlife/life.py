"""hvdlife — whole-program resource-lifecycle analysis (HVD701-705).

Every prior pass verifies *use*: hvdlint checks call symmetry per line,
hvdsan checks lock order and ownership, hvdmc checks protocol shape,
hvdflow checks rank dataflow.  Nothing verifies **release** — and the
runtime is a per-process fabric of long-lived machinery (the background
loop, per-peer sender lanes, shm regions, rendezvous watchers, stream
workers, the timeline writer, heartbeat monitor, autoscale controller,
statesync watcher, the preempt backstop timer, the metrics exporter,
per-epoch PeerMesh channel sets) that is re-created on **every elastic
world transition**.  A resource leaked once per ``reinit_world`` is a
production outage at fleet scale.

Model (riding the shared single-parse driver, ``lint --life``):

1. **Harvest**: every acquisition site — ``threading.Thread``/``Timer``
   starts (including package Thread *subclasses*), socket /
   ``_PeerChannel`` / ``PeerMesh`` / HTTP-server creation, ``mmap``
   regions, opened files, registered signal handlers — becomes a typed
   resource keyed by its creation ``file:line`` (the hvdsan identity
   scheme) and, when stored, by its binding ``module.Class.attr``.
2. **Release pairing**: each resource kind carries required release
   verbs (``join``/``cancel``/``close``/``shutdown``/``munmap``/
   re-``signal``).  A release site counts when its receiver resolves to
   the resource's binding attribute — directly, through a loop over the
   owning container (``for ch in self._channels.values(): ch.close()``),
   or through a local alias (``writer, self._writer = self._writer,
   None`` then ``writer.join()``).
3. **Teardown reachability**: the release must live in a function
   reachable from a *teardown root* (``shutdown``/``close``/``stop``/
   ``__exit__``/``__del__``/``cancel``/``finalize``/``reinit_world``)
   through the hvdsan call graph (typed resolution — the
   release-via-helper case is exactly a one-hop walk), or in the
   acquiring function itself (the ``listener.close()``-after-formation
   shape and ``finally`` blocks).
4. **Epoch scoping** (HVD704): an acquisition reachable from the world
   formation roots (module-level ``init``/``reinit_world``) whose
   release is NOT reachable from the teardown half of the transition is
   the elastic-specific leak — correct once, leaked once per
   grow/shrink cycle.  The runtime census witness
   (:mod:`.census`, ``HOROVOD_LIFE_CENSUS``) is the dynamic twin.

Ownership-transfer rules keep the pass quiet on the tree's sanctioned
idioms: a ``with``-managed acquisition is released by construction;
registration into a ``*resources*`` container (``_global.resources``)
transfers ownership to ``core.shutdown``'s drain loop; a local that is
passed onward (``self._attach(r, mm, path)``) transfers to the callee's
owner.  Intentional process-lifetime holds go into the reviewed
:data:`LIFECYCLE_ALLOWED` manifest (the ``LOCK_HOLD_ALLOWED`` mold) —
every report lists the matched allowances so the justification stays
visible.

Like every pass here the heuristics are deliberately lexical where
types run out; imprecision is tuned to lose findings, never to invent
them, and the census witness closes the gap from the runtime side.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from ..hvdsan.lockgraph import (Analysis, CallEvent, Finding, Program,
                                module_label, norm_path, _spine)
from ..rules import RULES

__all__ = ["LIFECYCLE_ALLOWED", "LIFE_RULE_IDS", "LifeAnalysis",
           "LifeProgram", "analyze_life", "analyze_paths"]

LIFE_RULE_IDS = frozenset({"HVD701", "HVD702", "HVD703", "HVD704",
                           "HVD705"})

# --- the resource taxonomy ---------------------------------------------------
# ctor terminal -> (kind, release verbs).  Threading ctors are handled
# separately (Thread subclasses join the set per program).  Thread
# releases accept the owner-API verbs too: a package Thread subclass's
# stop()/close() encapsulates its own poison+join (StreamDispatcher,
# the heartbeat monitor), and requiring the literal join would force
# every owner to reach through the abstraction.
_THREAD_VERBS = frozenset({"join", "stop", "close", "shutdown",
                           "cancel"})
_TIMER_VERBS = frozenset({"cancel", "join", "close", "stop"})
_CLOSE_VERBS = frozenset({"close", "server_close", "shutdown", "stop"})
_SIGNAL_VERBS = frozenset({"signal"})

# Package classes owning a closeable kernel object (sockets, fds, shm
# regions, an HTTP server + its pool).  Curated, reviewable — exactly
# like hvdlint's vocabularies; a new resource class gets a row here and
# a doc line in docs/analysis.md.  KVBlockPool (ISSUE 14) and
# KVStreamMesh qualify: the pool's blocks index HBM rows in the model
# cache and its residency accounting must not outlive the executor
# across reinit_world cycles (the refcount-leak census), and the
# stream mesh owns sockets plus drain threads.
_CHANNEL_CTORS = frozenset({
    "PeerMesh", "_PeerChannel", "ShmWorld", "MetricsExporter",
    "RendezvousServer", "ThreadingHTTPServer", "HTTPServer",
    "KVBlockPool", "KVStreamMesh",
    # Rendezvous control plane (ISSUE 15): the WAL writer owns an fd +
    # the group-commit fsync lane, the replicator owns the log-tail
    # thread, the ControlPlane owns all three lease/tail/wal resources
    # — each must have a close reachable from a teardown root or it
    # leaks one fd + threads per elastic reinit cycle (HVD702/704).
    "WalWriter", "Replicator", "ControlPlane",
})

_KIND_RULE = {
    "thread": "unjoined-thread",
    "timer": "unjoined-thread",
    "channel": "unreleased-channel",
    "socket": "unreleased-channel",
    "signal": "unreleased-channel",
    "mmap": "unreleased-region",
    "file": "unreleased-region",
}
_KIND_VERBS = {
    "thread": _THREAD_VERBS,
    "timer": _TIMER_VERBS,
    "channel": _CLOSE_VERBS,
    "socket": _CLOSE_VERBS,
    "signal": _SIGNAL_VERBS,
    "mmap": frozenset({"close"}),
    "file": frozenset({"close"}),
}

# Teardown roots: a release is proven only when its function is one of
# these (by name) or reachable from one through the call graph.
_TEARDOWN_NAMES = frozenset({
    "shutdown", "close", "stop", "finalize", "cancel", "teardown",
    "reinit_world", "exit",
})
_TEARDOWN_DUNDERS = frozenset({"__exit__", "__del__"})

# World-formation roots for HVD704: module-level functions only —
# ``Trainer.init`` and friends are per-object lifecycles, not world
# epochs.
_EPOCH_ROOT_NAMES = frozenset({"init", "reinit_world"})

# HVD705: blocking primitives a thread body can wedge on, and the
# wakeup verbs an owner must be able to reach to unblock it (poison
# put(None) is detected separately).
_BLOCK_NAMES = frozenset({
    "get", "recv", "recv_into", "recv_bytes", "accept", "wait",
    "select", "serve_forever", "join",
})
_WAKEUP_VERBS = frozenset({
    "close", "shutdown", "cancel", "set", "server_close", "stop",
})
_BOUND_HINTS = ("timeout", "deadline", "poll", "interval", "grace")
_MAX_THREAD_DEPTH = 8

# ---------------------------------------------------------------------------
# Reviewed process-lifetime allowances (the LOCK_HOLD_ALLOWED mold):
# resource key -> why the missing release is intentional.  Keys are the
# binding identity ("module.Class.attr") or, for unbound acquisitions,
# the acquiring function ("module.Class.func").  Every report lists the
# entries that matched, so the justification stays reviewable in one
# place instead of scattering inline suppressions.
# ---------------------------------------------------------------------------
LIFECYCLE_ALLOWED: dict[str, str] = {
    "elastic.rpc.RpcServer._accept_loop":
        "one daemon thread per accepted RPC connection, by design "
        "(workers keep one connection open for the job's lifetime): "
        "each thread exits when its client disconnects or when "
        "RpcServer.close() closes the listener and the conn sockets' "
        "peers vanish — there is no handle list to join because the "
        "connection set is the client population, not owned state",
    "elastic.driver.ElasticDriver._launch_worker":
        "one fire-and-forget thread per spawned worker process whose "
        "body IS create_worker_fn's blocking wait on that process: it "
        "exits exactly when the worker exits, and ElasticDriver.join "
        "awaits the results table the threads feed — joining the "
        "threads themselves would duplicate the worker-exit protocol",
    "statesync.service.StateSyncService._install_preempt_handler":
        "the SIGTERM grace handler is PROCESS-lifetime by design: the "
        "StateSyncService survives every world transition (it is not "
        "owned by core), and a preemption must be catchable at any "
        "epoch — restoring SIG_DFL at close would turn the scheduler's "
        "next SIGTERM into an instant kill with no bye| stamp",
    "telemetry.flight._chain_sigterm":
        "the flight recorder's SIGTERM chain handler is process-"
        "lifetime: it wraps whatever handler exists and re-raises, and "
        "unregistering would drop the crash evidence exactly on the "
        "path that needs it",
    "runner.safe_shell_exec.execute":
        "the kill-event watcher thread exits with the watched child "
        "(daemon; the event wait is its wakeup), and execute() itself "
        "awaits the child before returning",
    "runner.launch.launch_static":
        "per-slot runner threads are the launcher's foreground work: "
        "launch_static joins them inline (same function, including the "
        "KeyboardInterrupt arm) and their blocking wait is the child "
        "process itself — the terminate event set by the signal "
        "handler is the wakeup, and the process exits with them",
    "runner.run_api.run":
        "per-host remote-dispatch threads are joined inline by the "
        "same call (foreground fan-out, not background machinery)",
    "resilience.chaos.ChaosEngine._fire_coord":
        "the coordpause SIGCONT Timer is fire-and-forget by design: "
        "it must deliver the resume even if the injecting rank's "
        "engine (or the collective that fired the action) is torn "
        "down first — cancelling it at teardown would leave the "
        "rendezvous primary SIGSTOPped forever",
    "runner.launch.start_rendezvous":
        "ownership transfer by return value: the replica-set handles "
        "are returned as a LIST to the launch path (launch_static / "
        "launch_elastic), whose teardown stops every server in its "
        "finally block — the list shape is what the lexical "
        "returned-local transfer rule cannot see",
    "runner.controlplane._main":
        "the replica CLI's SIGTERM handler is process-lifetime: the "
        "process IS the replica (the chaos coordkill/coordpause "
        "target), and the handler's stop-event set is the orderly "
        "shutdown path until exit",
}


def blocking_allowed(key: str) -> bool:
    return key in LIFECYCLE_ALLOWED


# ---------------------------------------------------------------------------
# Per-file facts
# ---------------------------------------------------------------------------
@dataclass
class Acquisition:
    kind: str
    ctor: str
    path: str
    line: int
    col: int
    module: str
    cls: str | None
    funckey: str | None          # None = module level (import time)
    funcname: str | None
    attr: str | None             # binding attribute (owner field)
    local: str | None            # local name when bound to a plain local
    managed: bool = False        # `with` context expression
    registered: bool = False     # appended into a *resources* registry
    transferred: bool = False    # passed onward / returned
    unbound: bool = False        # Thread(...).start() style
    end_line: int = 0
    thread_name: str | None = None
    thread_target: tuple | None = None

    @property
    def key(self) -> str:
        parts = [self.module] if self.module else []
        if self.cls:
            parts.append(self.cls)
        if self.attr:
            parts.append(self.attr)
        elif self.funcname:
            parts.append(self.funcname)
        return ".".join(parts)

    @property
    def site(self) -> str:
        return f"{self.path}:{self.line}"


@dataclass
class ReleaseSite:
    verb: str
    attr: str                    # resolved binding attribute ("" unknown)
    funckey: str
    path: str
    line: int


@dataclass
class _FuncFacts:
    key: str
    name: str
    cls: str | None
    module: str
    path: str
    # local alias -> source binding attribute (writer = self._writer)
    aliases: dict = field(default_factory=dict)
    # loop var -> container binding attribute (for ch in self._chans...)
    loop_binds: dict = field(default_factory=dict)
    # unbounded blocking calls for HVD705: [(name, line)]
    blocking: list = field(default_factory=list)
    # bare names passed as call arguments (local-escape detection)
    arg_names: set = field(default_factory=set)
    # this scope establishes a deadline guard (resilience=/StreamGuard)
    guarded: bool = False
    # owner-side wakeup evidence: verbs + poison put(None)
    wakeups: set = field(default_factory=set)
    poisons: bool = False


@dataclass
class LifeProgram:
    acquisitions: list = field(default_factory=list)
    releases: list = field(default_factory=list)
    funcs: dict = field(default_factory=dict)         # key -> _FuncFacts
    thread_classes: dict = field(default_factory=dict)  # Cls -> run key
    # Capitalized ctor calls not (yet) classifiable: a Thread SUBCLASS
    # may be defined in a file collected after its construction site,
    # so classification completes at analysis time.
    candidates: list = field(default_factory=list)

    def collect_source(self, path: str, source: str,
                       tree: ast.AST | None = None) -> None:
        if tree is None:
            tree = ast.parse(source, filename=path)
        _LifeCollector(self, norm_path(path),
                       module_label(path)).collect(tree)


def _is_bounded(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg and any(h in kw.arg.lower() for h in _BOUND_HINTS):
            return True
    for arg in node.args:
        for sub in ast.walk(arg):
            ident = sub.id if isinstance(sub, ast.Name) else (
                sub.attr if isinstance(sub, ast.Attribute) else None)
            if ident and any(h in ident.lower() for h in _BOUND_HINTS):
                return True
    return False


def _join_exempt(node: ast.Call) -> bool:
    """str.join / os.path.join — mirrors hvdlint/hvdsan."""
    if not isinstance(node.func, ast.Attribute):
        return True
    base = node.func.value
    if isinstance(base, ast.Constant) and isinstance(base.value, str):
        return True
    sp = _spine(node.func)
    return bool(sp and set(sp[:-1]) & {"path", "sep", "pathsep",
                                       "linesep", "os", "posixpath",
                                       "ntpath"})


def _name_literal(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        head = ""
        for v in node.values:
            if isinstance(v, ast.Constant):
                head += str(v.value)
            else:
                return head + "*"
        return head
    return None


def _binding_attr(spine: tuple | None) -> tuple[str | None, str | None]:
    """(attr, local) binding of an assignment-target spine.

    ``self._watcher`` / ``_global.background_thread`` /
    ``self._socks[peer]`` bind to the named attribute; a bare local
    (``mm``) binds locally; a plain-local container store
    (``accepted[peer] = conn``) is an ownership transfer the container's
    consumer owns."""
    if not spine:
        return None, None
    named = [p for p in spine if p not in ("[]", "()")]
    if not named:
        return None, None
    if len(spine) == 1:
        return None, spine[0]                # plain local binding
    root = spine[0]
    if root in ("self", "cls") or root.startswith("_") or \
            root[:1].isupper():
        return named[-1] if named[-1] not in ("self", "cls") \
            else None, None
    return None, None                        # local container: transfer


class _LifeCollector:
    """One walk per file with a parent map: acquisition context
    (with/assign/arg/return) needs one level of ancestry the visitor
    pattern hides."""

    def __init__(self, program: LifeProgram, path: str,
                 module: str) -> None:
        self.p = program
        self.path = path
        self.module = module

    # -- entry ------------------------------------------------------------
    def collect(self, tree: ast.AST) -> None:
        parents: dict[int, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
        # function scope map: node -> (funckey, funcname, cls)
        self._scopes(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self._note_class(node)
            elif isinstance(node, ast.Call):
                self._note_call(node, parents)
        self._gather_stmt_facts(tree)

    def _scopes(self, tree: ast.AST) -> None:
        """Assign every node its enclosing (funckey, name, cls) using
        lockgraph's _qual convention so funckeys line up with the
        hvdsan call graph."""
        self._scope_of: dict[int, tuple] = {}

        def walk(node, cls, fnparts):
            for child in ast.iter_child_nodes(node):
                ncls, nparts = cls, fnparts
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    nparts = fnparts + [child.name]
                elif isinstance(child, ast.ClassDef):
                    ncls, nparts = child.name, []
                if nparts:
                    parts = [self.module] if self.module else []
                    if ncls:
                        parts.append(ncls)
                    parts.extend(nparts)
                    self._scope_of[id(child)] = (".".join(parts),
                                                 nparts[-1], ncls)
                walk(child, ncls, nparts)

        walk(tree, None, [])
        # ensure facts rows exist for every function
        for key, name, cls in set(self._scope_of.values()):
            self.p.funcs.setdefault(key, _FuncFacts(
                key=key, name=name, cls=cls, module=self.module,
                path=self.path))

    def _scope(self, node: ast.AST):
        return self._scope_of.get(id(node), (None, None, None))

    def _note_class(self, node: ast.ClassDef) -> None:
        for b in node.bases:
            sp = _spine(b)
            if sp and sp[-1] == "Thread":
                parts = [self.module] if self.module else []
                parts += [node.name, "run"]
                self.p.thread_classes[node.name] = ".".join(parts)

    # -- calls ------------------------------------------------------------
    def _classify_ctor(self, sp: tuple,
                       node: ast.Call) -> tuple[str, str] | None:
        name = sp[-1]
        if name == "Thread":
            return ("thread", name)
        if name == "Timer":
            return ("timer", name)
        if name in _CHANNEL_CTORS:
            return ("channel", name)
        if name in self.p.thread_classes:
            return ("thread", name)
        if name == "socket" and len(sp) >= 2 and sp[-2] == "socket":
            return ("socket", name)
        if name == "create_connection":
            return ("socket", name)
        if name == "mmap" and (len(sp) == 1 or sp[-2] == "mmap"):
            return ("mmap", name)
        if name == "open" and len(sp) == 1:
            return ("file", name)
        if name == "signal" and len(sp) >= 2 and sp[-2] == "signal" \
                and len(node.args) >= 2:
            return ("signal", name)
        return None

    def _note_call(self, node: ast.Call, parents: dict) -> None:
        sp = _spine(node.func)
        funckey, funcname, cls = self._scope(node)
        if sp:
            self._note_release(sp, node, funckey)
            self._note_func_facts(sp, node, funckey)
        ctor = self._classify_ctor(sp, node) if sp else None
        if funckey is None:
            return
        if ctor is None:
            name = sp[-1] if sp else ""
            if name[:1].isupper() and len(sp) <= 2:
                acq = Acquisition(
                    kind="candidate", ctor=name, path=self.path,
                    line=node.lineno, col=node.col_offset + 1,
                    module=self.module, cls=cls, funckey=funckey,
                    funcname=funcname, attr=None, local=None,
                    end_line=node.end_lineno or node.lineno)
                self._classify_context(acq, node, parents)
                self.p.candidates.append(acq)
            return
        kind, name = ctor
        acq = Acquisition(
            kind=kind, ctor=name, path=self.path, line=node.lineno,
            col=node.col_offset + 1, module=self.module, cls=cls,
            funckey=funckey, funcname=funcname, attr=None, local=None,
            end_line=node.end_lineno or node.lineno)
        if kind in ("thread", "timer"):
            if name in self.p.thread_classes:
                acq.thread_target = (name, "run")
            for kw in node.keywords:
                if kw.arg == "target":
                    acq.thread_target = _spine(kw.value)
                elif kw.arg in ("name", "function"):
                    if kw.arg == "function":
                        acq.thread_target = _spine(kw.value)
                    else:
                        acq.thread_name = _name_literal(kw.value)
            if kind == "timer" and acq.thread_target is None and \
                    len(node.args) >= 2:
                acq.thread_target = _spine(node.args[1])
        if kind == "signal":
            acq.attr = None          # registration is inherently unbound
        self._classify_context(acq, node, parents)
        self.p.acquisitions.append(acq)

    def _classify_context(self, acq: Acquisition, node: ast.Call,
                          parents: dict) -> None:
        """Walk up: with-item, assignment target, registration,
        transfer, or unbound chained call."""
        cur: ast.AST = node
        while True:
            parent = parents.get(id(cur))
            if parent is None:
                return
            if isinstance(parent, ast.withitem) and \
                    parent.context_expr is cur:
                acq.managed = True
                return
            if isinstance(parent, (ast.Assign, ast.AnnAssign)) and \
                    getattr(parent, "value", None) is not None:
                targets = parent.targets \
                    if isinstance(parent, ast.Assign) else [parent.target]
                for t in targets:
                    attr, local = _binding_attr(_spine(t))
                    if attr or local:
                        acq.attr, acq.local = attr, local
                        return
                acq.transferred = True       # tuple/starred target etc.
                return
            if isinstance(parent, ast.Call) and cur is not parent.func:
                # ctor appears as an argument: registration or transfer
                psp = _spine(parent.func)
                if psp and psp[-1] in ("append", "extend", "add") and \
                        any("resources" in s for s in psp[:-1]
                            if s not in ("[]", "()")):
                    acq.registered = True
                else:
                    acq.transferred = True
                return
            if isinstance(parent, ast.Attribute) and parent.value is cur:
                acq.unbound = True           # Thread(...).start()
                return
            if isinstance(parent, ast.Return):
                acq.transferred = True       # factory: caller owns it
                return
            if isinstance(parent, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp, ast.List,
                                   ast.Tuple, ast.Starred, ast.IfExp,
                                   ast.expr)) and not \
                    isinstance(parent, ast.Call):
                cur = parent
                continue
            cur = parent

    # -- releases + per-function facts -----------------------------------
    def _note_release(self, sp: tuple, node: ast.Call,
                      funckey: str | None) -> None:
        verb = sp[-1]
        if funckey is None:
            return
        if verb == "signal" and len(sp) >= 2 and sp[-2] == "signal":
            self.p.releases.append(ReleaseSite(
                verb="signal", attr="", funckey=funckey,
                path=self.path, line=node.lineno))
            return
        if verb not in (_THREAD_VERBS | _TIMER_VERBS | _CLOSE_VERBS):
            return
        if verb == "join" and _join_exempt(node):
            return
        recv = sp[:-1]
        named = [p for p in recv if p not in ("[]", "()",
                                              "self", "cls")]
        attr = named[-1] if named else (recv[0] if recv else "")
        self.p.releases.append(ReleaseSite(
            verb=verb, attr=attr, funckey=funckey, path=self.path,
            line=node.lineno))

    def _note_func_facts(self, sp: tuple, node: ast.Call,
                         funckey: str | None) -> None:
        if funckey is None:
            return
        fn = self.p.funcs.get(funckey)
        if fn is None:
            return
        name = sp[-1]
        if name in _BLOCK_NAMES and not _is_bounded(node):
            exempt = name == "join" and _join_exempt(node)
            if name == "get":
                # dict/config .get() lookalikes: the blocking-get half
                # bites only on queue-reading receivers (hvdlint
                # HVD1006's receiver filter).
                recv = [s.lower() for s in sp[:-1]
                        if s not in ("[]", "()", "self", "cls")]
                exempt = not any(r == "q" or "queue" in r
                                 or r.endswith("_q") for r in recv)
            if not exempt:
                fn.blocking.append((name, node.lineno))
        if name in _WAKEUP_VERBS:
            fn.wakeups.add(name)
        if name in ("put", "put_nowait") and any(
                isinstance(a, ast.Constant) and a.value is None
                for a in node.args):
            fn.poisons = True
        for arg in node.args:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name):
                    fn.arg_names.add(sub.id)
        for kw in node.keywords:
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Name):
                    fn.arg_names.add(sub.id)
        if "Guard" in name:
            fn.guarded = True
        for kw in node.keywords:
            if kw.arg == "resilience":
                fn.guarded = True

    # -- statement facts: alias forwarding + loop binds -------------------
    def _gather_stmt_facts(self, tree: ast.AST) -> None:
        """Loop-variable and local-alias binds the release matcher
        resolves receivers through (``for ch in self._channels.
        values(): ch.close()``; ``writer, self._writer = self._writer,
        None`` then ``writer.join()``), plus local→attr forwarding for
        acquisitions bound to a local first (``timer = Timer(...)``
        then ``self._grace_timer = timer``)."""
        fwd: dict[tuple, str] = {}     # (funckey, local) -> attr
        for node in ast.walk(tree):
            funckey, _name, _cls = self._scope(node)
            if funckey is None:
                continue
            fn = self.p.funcs.get(funckey)
            if fn is None:
                continue
            if isinstance(node, (ast.For, ast.AsyncFor)):
                targets = [node.target] if isinstance(node.target,
                                                      ast.Name) \
                    else (node.target.elts
                          if isinstance(node.target, ast.Tuple) else [])
                it = node.iter
                # descend through list(...)/sorted(...)-style wrappers
                # (snapshot-copy iteration: `for k, v in
                # list(self._donors.items())`)
                while isinstance(it, ast.Call) and \
                        isinstance(it.func, ast.Name) and \
                        it.func.id in ("list", "sorted", "tuple",
                                       "set", "reversed") and \
                        len(it.args) == 1:
                    it = it.args[0]
                isp = _spine(it)
                if isp and targets:
                    named = [s for s in isp
                             if s not in ("[]", "()", "self", "cls",
                                          "values", "items", "keys")]
                    if named:
                        # tuple unpacking over .items(): every element
                        # binds to the container (lexically — the
                        # release matcher only needs the attr)
                        for t in targets:
                            if isinstance(t, ast.Name):
                                fn.loop_binds[t.id] = named[-1]
            elif isinstance(node, ast.Assign):
                if len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Tuple) and \
                        isinstance(node.value, ast.Tuple) and \
                        len(node.targets[0].elts) == \
                        len(node.value.elts):
                    pairs = list(zip(node.targets[0].elts,
                                     node.value.elts))
                else:
                    pairs = [(t, node.value) for t in node.targets]
                for t, v in pairs:
                    if isinstance(t, ast.Name):
                        vsp = _spine(v)
                        if vsp is None and isinstance(v, ast.Call) \
                                and len(v.args) == 1:
                            # resources = list(_global.resources)
                            vsp = _spine(v.args[0])
                        if vsp and len(vsp) > 1:
                            named = [s for s in vsp
                                     if s not in ("[]", "()", "self",
                                                  "cls")]
                            if named:
                                fn.aliases[t.id] = named[-1]
                    elif isinstance(v, ast.Name):
                        # self._grace_timer = timer: forward the
                        # local-bound acquisition to the attr
                        attr, _local = _binding_attr(_spine(t))
                        if attr:
                            fwd[(funckey, v.id)] = attr
        for acq in self.p.acquisitions + self.p.candidates:
            if acq.local is not None and acq.attr is None:
                attr = fwd.get((acq.funckey, acq.local))
                if attr:
                    acq.attr, acq.local = attr, None


# ---------------------------------------------------------------------------
# Analysis
# ---------------------------------------------------------------------------
class LifeAnalysis:
    """Release-reachability over the hvdsan call graph + the census of
    thread roots the runtime witness normalizes against."""

    def __init__(self, program: Program, life: LifeProgram) -> None:
        self.program = program
        self.life = life
        self.an = Analysis(program)
        self.an._build_indexes()
        self.findings: list[Finding] = []
        self.allowed_hits: list[tuple[str, str]] = []
        self._adj: dict[str, list[str]] = {}
        self._resolve_cache: dict = {}
        self.teardown_reach: set[str] = set()
        self.epoch_reach: set[str] = set()
        # thread name -> body funckey (the hvdlife thread universe)
        self.thread_roots: dict[str, str] = {}

    # -- call graph -------------------------------------------------------
    def _build_adj(self) -> None:
        for fraw in self.program.functions.values():
            outs: list[str] = []
            for ev in fraw.calls:
                for tkey, _conf in self.an.resolve_call(fraw, ev):
                    if tkey:
                        outs.append(tkey)
            self._adj[fraw.key] = outs

    def _reach_from(self, roots) -> set[str]:
        seen: set[str] = set()
        stack = list(roots)
        while stack:
            k = stack.pop()
            if k in seen:
                continue
            seen.add(k)
            stack.extend(self._adj.get(k, ()))
        return seen

    def _teardown_roots(self) -> list[str]:
        out = []
        for fraw in self.program.functions.values():
            name = fraw.name
            if name in _TEARDOWN_DUNDERS or \
                    name.lstrip("_") in _TEARDOWN_NAMES:
                out.append(fraw.key)
        return out

    def _epoch_roots(self) -> list[str]:
        out = []
        for f in self.program.functions.values():
            if f.name not in _EPOCH_ROOT_NAMES or f.cls is not None:
                continue
            # module-level only (not nested): key == "<module>.<name>"
            expect = f"{f.module}.{f.name}" if f.module else f.name
            if f.key == expect:
                out.append(f.key)
        return out

    # -- release matching -------------------------------------------------
    def _release_attr(self, rel: ReleaseSite) -> str:
        """Resolve the release receiver through the function's loop
        binds and local aliases."""
        fn = self.life.funcs.get(rel.funckey)
        attr = rel.attr
        if fn is not None and attr:
            attr = fn.loop_binds.get(attr, fn.aliases.get(attr, attr))
        return attr

    def _rel_module(self, rel: ReleaseSite) -> str | None:
        fn = self.life.funcs.get(rel.funckey)
        return fn.module if fn is not None else None

    def _released(self, acq: Acquisition) -> bool:
        verbs = _KIND_VERBS[acq.kind]
        if acq.kind == "signal":
            # release = a re-registration reachable from teardown
            return any(r.verb == "signal"
                       and r.funckey != acq.funckey
                       and r.funckey in self.teardown_reach
                       and self._rel_module(r) == acq.module
                       for r in self.life.releases)
        for rel in self.life.releases:
            if rel.verb not in verbs:
                continue
            # Same-module discipline: a same-named attribute in another
            # module must never count as this resource's release (the
            # heartbeat monitor's `_thread.join` is not the exporter's).
            if self._rel_module(rel) != acq.module:
                continue
            attr = self._release_attr(rel)
            if acq.attr is not None:
                if attr != acq.attr:
                    continue
                if rel.funckey in self.teardown_reach or \
                        rel.funckey == acq.funckey:
                    return True
            elif acq.local is not None:
                # local-bound: a release on the same local (or its
                # forwarded attr) inside the same function suffices
                if rel.funckey != acq.funckey:
                    continue
                if rel.attr == acq.local or attr == acq.local:
                    return True
        return False

    # -- HVD705 -----------------------------------------------------------
    def _resolve_target(self, acq: Acquisition) -> str | None:
        if acq.thread_target is None:
            return None
        if len(acq.thread_target) == 2 and \
                acq.thread_target[0] in self.life.thread_classes:
            return self.life.thread_classes[acq.thread_target[0]]
        fraw = self.program.functions.get(acq.funckey or "")
        if fraw is None:
            return None
        cached = self._resolve_cache.get((acq.funckey,
                                          acq.thread_target))
        if cached is not None:
            return cached or None
        ev = CallEvent(spine=acq.thread_target, held=(), line=acq.line)
        targets = self.an._resolve_call_uncached(fraw, ev)
        hit = targets[0][0] if targets else ""
        self._resolve_cache[(acq.funckey, acq.thread_target)] = hit
        return hit or None

    def _thread_blocks_unbounded(self, root: str) -> tuple | None:
        """(name, path, line) of the first unbounded blocking call
        reachable from the thread body, honoring deadline guards."""
        stack = [(root, 0, False)]
        seen: set = set()
        while stack:
            key, depth, guarded = stack.pop()
            fn = self.life.funcs.get(key)
            g = guarded or (fn.guarded if fn else False)
            if (key, g) in seen or depth > _MAX_THREAD_DEPTH:
                continue
            seen.add((key, g))
            if fn is not None and not g and fn.blocking:
                name, line = fn.blocking[0]
                return name, fn.path, line
            for nxt in self._adj.get(key, ()):
                stack.append((nxt, depth + 1, g))
        return None

    def _owner_has_wakeup(self, acq: Acquisition) -> bool:
        """Any teardown-root (or teardown-reachable) function of the
        acquiring class/module carries a poison put(None) or a wakeup
        verb — the path that can unblock the thread before its join."""
        prefix = ".".join(filter(None, [acq.module, acq.cls]))
        for fn in self.life.funcs.values():
            if acq.cls:
                if not fn.key.startswith(prefix + "."):
                    continue
            elif fn.module != acq.module:
                continue
            if fn.key not in self.teardown_reach:
                continue
            if fn.poisons or fn.wakeups:
                return True
        return False

    # -- findings ---------------------------------------------------------
    def _suppressed(self, path: str, start: int, end: int, rule) -> bool:
        sup = self.program.suppressions.get(path)
        return bool(sup and sup.active_span(start, max(start, end),
                                            rule))

    def _emit(self, rule_key: str, severity: str, acq: Acquisition,
              message: str) -> None:
        rule = RULES[rule_key]
        if self._suppressed(acq.path, acq.line, acq.end_line, rule):
            return
        self.findings.append(Finding(
            rule=rule, severity=severity, path=acq.path, line=acq.line,
            message=message, sites=((acq.path, acq.line),)))

    def _check_releases(self) -> None:
        for acq in self.life.acquisitions:
            if acq.managed or acq.registered or acq.transferred:
                continue
            if acq.funckey is None:
                continue            # import-time: process lifetime
            if blocking_allowed(acq.key):
                self.allowed_hits.append((acq.key,
                                          LIFECYCLE_ALLOWED[acq.key]))
                continue
            if acq.unbound and acq.kind in ("thread", "timer"):
                # fire-and-forget Thread(...).start(): no handle exists
                # to join — same leak, clearer message
                self._emit(
                    "unjoined-thread", "error", acq,
                    f"'{acq.ctor}' started at {acq.site} without "
                    f"keeping a handle: nothing can ever join it — "
                    f"bind it to an owner field and join from the "
                    f"owner's teardown (poison first), or record the "
                    f"intentional hold in LIFECYCLE_ALLOWED")
                continue
            if self._released(acq):
                continue
            verbs = "/".join(sorted(_KIND_VERBS[acq.kind]))
            epoch = acq.funckey in self.epoch_reach
            if epoch:
                self._emit(
                    "epoch-scoped-leak", "error", acq,
                    f"{acq.kind} '{acq.ctor}' acquired at {acq.site} "
                    f"(binding {acq.key}) is reachable from the world "
                    f"formation path (init/reinit_world) but NO "
                    f"{verbs} release on it is reachable from the "
                    f"teardown half of the transition "
                    f"(shutdown/reinit_world): one {acq.kind} leaks "
                    f"per elastic world cycle — release it in the "
                    f"owner's teardown, register it in the resources "
                    f"drain, or record the hold in LIFECYCLE_ALLOWED")
            else:
                self._emit(
                    _KIND_RULE[acq.kind], "error", acq,
                    f"{acq.kind} '{acq.ctor}' acquired at {acq.site} "
                    f"(binding {acq.key}) has no {verbs} release "
                    f"reachable from a teardown path "
                    f"(shutdown/close/stop/__exit__): the {acq.kind} "
                    f"outlives its owner — release it from the owner's "
                    f"teardown, or record the intentional hold in "
                    f"LIFECYCLE_ALLOWED with its justification")

    def _check_wakeups(self) -> None:
        for acq in self.life.acquisitions:
            if acq.kind != "thread" or acq.funckey is None:
                continue
            if blocking_allowed(acq.key):
                continue
            root = self._resolve_target(acq)
            if root is None:
                continue
            hit = self._thread_blocks_unbounded(root)
            if hit is None:
                continue
            if self._owner_has_wakeup(acq):
                continue
            name, bpath, bline = hit
            self._emit(
                "blocking-thread-without-wakeup", "error", acq,
                f"thread started at {acq.site} blocks unboundedly on "
                f"'{name}' ({bpath}:{bline}) and its owner has no "
                f"wakeup path — no poison put(None), no close/shutdown/"
                f"cancel/set in any teardown-reachable function: a "
                f"join can only wait out the grace and leak the thread "
                f"(the wedged-sender shape).  Poison the queue or shut "
                f"the socket down first, then join")

    def _harvest_thread_roots(self) -> None:
        for acq in self.life.acquisitions:
            if acq.kind not in ("thread", "timer"):
                continue
            root = self._resolve_target(acq)
            if root is None:
                continue
            name = acq.thread_name or f"thread@{acq.site}"
            if root not in self.thread_roots or (
                    acq.thread_name and
                    self.thread_roots[root].startswith("thread@")):
                self.thread_roots[root] = name
        # Manifest names OVERRIDE harvest placeholders (hvdsan's
        # _fix_threads order): Thread subclasses and Timer callbacks
        # get their stable names from ownership.THREAD_ROOTS.
        from ..hvdsan.ownership import THREAD_ROOTS
        for tname, (funckey, _why) in THREAD_ROOTS.items():
            if funckey in self.program.functions:
                self.thread_roots[funckey] = tname

    def analyze(self) -> "LifeAnalysis":
        # Late classification: Thread-subclass constructions recorded
        # as candidates (the class may live in a later-collected file).
        for acq in self.life.candidates:
            if acq.ctor in self.life.thread_classes:
                acq.kind = "thread"
                if acq.thread_target is None:
                    acq.thread_target = (acq.ctor, "run")
                self.life.acquisitions.append(acq)
        # Local escape: a local-bound resource later passed as an
        # argument transfers ownership to the callee's owner (the
        # `self._attach(r, mm, path)` / resources-registration shapes).
        for acq in self.life.acquisitions:
            if acq.local is not None and not acq.transferred:
                fn = self.life.funcs.get(acq.funckey)
                if fn is not None and acq.local in fn.arg_names:
                    acq.transferred = True
        self._build_adj()
        self.teardown_reach = self._reach_from(self._teardown_roots())
        self.epoch_reach = self._reach_from(self._epoch_roots())
        self._harvest_thread_roots()
        self._check_releases()
        self._check_wakeups()
        self.findings.sort(key=lambda f: (f.path, f.line, f.rule.id))
        return self

    def report_lines(self) -> list[str]:
        lines = [f"hvdlife: {len(self.life.acquisitions)} acquisition "
                 f"site(s), {len(self.life.releases)} release site(s), "
                 f"{len(self.thread_roots)} thread root(s)"]
        for key, why in sorted(set(self.allowed_hits)):
            lines.append(f"  allowed-hold {key} -- {why}")
        return lines


def analyze_life(program: Program, life: LifeProgram,
                 cfg=None) -> list[Finding]:
    findings = LifeAnalysis(program, life).analyze().findings
    if cfg is not None:
        findings = [f for f in findings if cfg.wants(f.rule)]
    return findings


def analyze_paths(paths) -> LifeAnalysis:
    from ..lint import iter_python_files
    program = Program()
    life = LifeProgram()
    for p in iter_python_files(list(paths)):
        try:
            with open(p, encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=p)
        except (OSError, SyntaxError):
            continue
        program.collect_source(p, src, tree)
        life.collect_source(p, src, tree)
    return LifeAnalysis(program, life).analyze()
