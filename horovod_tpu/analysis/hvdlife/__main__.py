"""``python -m horovod_tpu.analysis.hvdlife`` — standalone CLI for the
resource-lifecycle pass (HVD701-705) and the census-witness diff."""
from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis.hvdlife",
        description="Whole-program resource-lifecycle analysis "
                    "(HVD701-705) with a runtime census witness "
                    "(see docs/analysis.md).")
    parser.add_argument("paths", nargs="*", default=["horovod_tpu"])
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--census", nargs="*", default=[],
                        help="rank-stamped census dumps "
                             "(HOROVOD_LIFE_CENSUS_FILE) to check: "
                             "each rank's return-to-baseline snapshot "
                             "must equal its baseline")
    args = parser.parse_args(argv)

    from .census import check_dumps, load_census_dumps
    from .life import analyze_paths

    t0 = time.monotonic()
    analysis = analyze_paths(args.paths)
    drift = check_dumps(load_census_dumps(args.census)) \
        if args.census else []
    wall_ms = round((time.monotonic() - t0) * 1e3, 3)
    findings = analysis.findings
    errors = [f for f in findings if f.severity == "error"]

    if args.format == "json":
        print(json.dumps({
            "life": [f.json() for f in findings],
            "census": drift,
            "allowed": sorted(set(analysis.allowed_hits)),
            "threads": dict(sorted(analysis.thread_roots.items())),
            "wall_ms": wall_ms,
        }, indent=2))
    elif args.format == "sarif":
        from ..hvdsan.san import sarif_payload
        print(json.dumps(sarif_payload(findings), indent=2))
    else:
        for line in analysis.report_lines():
            print(line)
        for f in findings:
            print(f.text())
        for p in drift:
            print(f"hvdlife: CENSUS DRIFT: {p}")
        print(f"hvdlife: {len(errors)} error(s), "
              f"{len(findings) - len(errors)} warning(s) in "
              f"{', '.join(args.paths)} ({wall_ms:.1f} ms)",
              file=sys.stderr)
    return 1 if (errors or drift) else 0


if __name__ == "__main__":
    sys.exit(main())
