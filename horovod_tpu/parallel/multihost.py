"""Multi-host JAX world formation over the rendezvous control plane.

The TPU analogue of GlooContext initialization (reference:
horovod/common/gloo/gloo_context.cc:136-152): where the reference reads
HOROVOD_RANK/SIZE from the launcher's env and connects a Gloo full mesh
through the rendezvous HTTP store, we negotiate a JAX coordinator address
through the same KV store and call `jax.distributed.initialize`, after
which `jax.devices()` spans every process and `build_mesh` can lay a
hybrid ICI×DCN mesh over the whole pod.

Must run BEFORE any JAX backend initializes in the process (the same
constraint as NCCL unique-id exchange happening before the first
collective, reference: ops/nccl_operations.cc:61-94).
"""
from __future__ import annotations

import os
import socket
import threading
from typing import Any

import logging

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_initialized_here = False
# (rank, size, kv, epoch) of the live world; drives ordered teardown.
_world: tuple | None = None

_COORD_SCOPE = "jaxdist"


def is_initialized() -> bool:
    return _initialized_here


def init_jax_distributed(rank: int, size: int, kv: Any = None,
                         coordinator_address: str | None = None,
                         local_device_ids: list[int] | None = None,
                         timeout: float = 120.0) -> bool:
    """Form the multi-process JAX world; returns True if initialized.

    Rank 0 picks a free port and publishes ``host:port`` under the
    ``jaxdist`` scope of the rendezvous KV store; everyone else blocks on
    that key, then all processes call ``jax.distributed.initialize``.
    Pass ``coordinator_address`` explicitly to skip the KV negotiation
    (e.g. on TPU pods where GCE metadata supplies it).
    """
    global _initialized_here
    with _lock:
        if _initialized_here or size <= 1:
            return _initialized_here
        import jax

        epoch = os.environ.get("HOROVOD_RENDEZVOUS_EPOCH", "0")
        key = f"coord:{epoch}"
        if coordinator_address is None:
            if kv is None:
                raise ValueError(
                    "init_jax_distributed needs a rendezvous KV client or "
                    "an explicit coordinator_address")
            if rank == 0:
                from ..runner.network import free_port
                host = socket.gethostbyname(socket.gethostname())
                coordinator_address = f"{host}:{free_port()}"
                kv.put(_COORD_SCOPE, key, coordinator_address.encode())
            else:
                coordinator_address = kv.wait(_COORD_SCOPE, key,
                                              timeout).decode()

        cpu_gloo = os.environ.get("JAX_PLATFORMS", "") == "cpu"
        if cpu_gloo:
            # Cross-process collectives on the CPU backend need the gloo
            # implementation (the virtual-mesh test path; real deployments
            # ride ICI/DCN through the TPU runtime instead).
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:  # noqa: BLE001 - older jaxlib: no such knob
                pass
            if not (os.environ.get("JAX_COMPILATION_CACHE_DIR")
                    or jax.config.jax_compilation_cache_dir):
                # The compile→barrier→dispatch pattern (Trainer.step →
                # kv_barrier) only shrinks skew if the post-barrier
                # dispatch can reload the AOT compile from a persistent
                # cache — lower().compile() does not seed jit's
                # in-memory executable cache. Configure a host-shared
                # cache when the caller hasn't.
                try:
                    jax.config.update("jax_compilation_cache_dir",
                                      "/tmp/horovod_tpu_jax_cache")
                except Exception:  # noqa: BLE001 - knob absent
                    pass
            # JAX declines to persist programs that compiled faster than
            # jax_persistent_cache_min_compile_time_secs (default 1s), so
            # a fast-compiling step would silently repeat its AOT compile
            # after the barrier — exactly the skew the compile→barrier→
            # dispatch pattern exists to remove.  Persist everything.
            try:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0)
            except Exception:  # noqa: BLE001 - older jax: knob absent
                pass

        # Elastic worlds must SURVIVE peer death: without recoverability
        # the coordination service FATALs the surviving processes when the
        # shutdown barrier fails (absl fatal, not an exception), killing
        # the elastic retry loop before it can re-rendezvous.
        if os.environ.get("HOROVOD_ELASTIC"):
            try:
                jax.config.update("jax_enable_recoverability", True)
            except Exception:  # noqa: BLE001 - older jax: knob absent
                pass
        heartbeat = int(os.environ.get(
            "HOROVOD_JAX_HEARTBEAT_TIMEOUT_SECONDS", "100"))
        logger.debug("jax.distributed.initialize rank=%d size=%d coord=%s",
                     rank, size, coordinator_address)
        # Older jaxlibs lack some tuning kwargs (e.g. 0.4.x has no
        # heartbeat_timeout_seconds): filter by the actual signature so
        # world formation works across the supported jax range.
        import inspect
        init_kwargs = dict(
            coordinator_address=coordinator_address,
            num_processes=size, process_id=rank,
            local_device_ids=local_device_ids,
            heartbeat_timeout_seconds=heartbeat,
            initialization_timeout=int(timeout))
        try:
            accepted = set(inspect.signature(
                jax.distributed.initialize).parameters)
            init_kwargs = {k: v for k, v in init_kwargs.items()
                           if k in accepted}
        except (TypeError, ValueError):  # C-level signature: keep all
            pass
        jax.distributed.initialize(**init_kwargs)
        if cpu_gloo:
            # Eagerly form the gloo transport pairs while every process
            # is still in init lockstep (reference parity: the gloo
            # context connects its pairs AT init, gloo_context.cc, not
            # lazily). Without this the pairs connect at the first REAL
            # collective — which under per-process compile skew can sit
            # beyond gloo's connect timeout and fail world formation
            # exactly when the program is largest.
            try:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices("horovod_tpu_init")  # hvdlint: disable=collective-under-lock -- init-time only: _lock orders init/shutdown on user threads (the background loop never takes it), every rank reaches this line by construction, and the barrier carries its own timeout
            except Exception:  # noqa: BLE001 - barrier is best-effort
                logger.debug("init barrier skipped", exc_info=True)
        global _world, _barrier_seq, _cpu_gloo_world
        _world = (rank, size, kv, epoch)
        # Every member of the (possibly re-formed elastic) world starts
        # the barrier sequence from zero — a survivor carrying its old
        # counter would wait on keys no newcomer ever writes.
        _barrier_seq = 0
        _cpu_gloo_world = cpu_gloo
        _initialized_here = True
        return True


_barrier_seq = 0
_cpu_gloo_world = False


def kv_barrier(tag: str, timeout: float = 300.0) -> None:
    """Rendezvous-KV barrier across the world — pure HTTP, NO collective.

    gloo forms a fresh transport context per compiled program, and its
    pair-connect timeout is a hardcoded ~30 s: any cross-rank skew
    larger than that (per-process compile of a big program on a loaded
    host) fails the program's FIRST collective with "Gloo context
    initialization failed: Connect timeout". A barrier that is itself a
    collective inherits the same bound, so this one rides the rendezvous
    KV instead. No-op outside a multi-process world.

    SYMMETRIC-CALL CONTRACT: every rank must call kv_barrier the same
    number of times, in the same order — keys are derived from an
    implicit per-process sequence counter, so an asymmetric extra call
    on one rank (e.g. constructing an extra Trainer, or ranks
    disagreeing on sync_compile_needed() because JAX_PLATFORMS differed
    at world formation) permanently misaligns every later barrier.
    hvdlint proves this contract statically (rank-gated-collective /
    duplicate-barrier-tag / dynamic-barrier-tag rules), and
    HOROVOD_FINGERPRINT checks the controller-plane half of it at
    runtime — see docs/analysis.md.  A
    timeout therefore means ONE of two distinct faults, and the raised
    error carries enough state (rank/tag/seq/waited-on key) to tell
    them apart: a dead or wedged peer (its key for THIS seq never
    appears), or a seq mismatch (the peer is alive but publishing under
    a different sequence number)."""
    global _barrier_seq
    if not _initialized_here or _world is None:
        return
    rank, size, kv, epoch = _world
    if kv is None or size <= 1:
        return
    with _lock:
        _barrier_seq += 1
        seq = _barrier_seq
    key = f"{epoch}:{tag}:{seq}"
    kv.put("barrier", f"{key}:{rank}", b"1")
    for r in range(size):
        try:
            kv.wait("barrier", f"{key}:{r}", timeout)
        except TimeoutError as exc:
            raise TimeoutError(
                _barrier_timeout_diagnosis(kv, key, rank, size, tag, seq,
                                           timeout)) from exc


def _barrier_timeout_diagnosis(kv, key: str, rank: int, size: int,
                               tag: str, seq: int,
                               timeout: float) -> str:
    """Name WHICH ranks are missing from the barrier (one probe per
    rank), cross-checked against the resilience liveness table when
    fault tolerance is on — the most common multihost debugging session
    ('who is stuck?') becomes a one-line answer instead of a single
    anonymous key timeout."""
    missing: list[int] = []
    for r in range(size):
        try:
            if kv.get("barrier", f"{key}:{r}") is None:
                missing.append(r)
        except Exception:  # noqa: BLE001 - KV gone: report what we know
            missing.append(r)
    dead: list[int] = []
    try:
        from ..resilience import active_state
        state = active_state()
        if state is not None:
            dead = sorted(set(missing) & state.failed_ranks())
    except Exception:  # noqa: BLE001 - diagnosis must never mask the timeout
        pass
    verdict = (f"rank(s) {dead} are DEAD/unreachable per the liveness "
               f"table — elastic recovery or HOROVOD_ON_FAILURE applies."
               if dead else
               "all missing ranks still heartbeat (or fault tolerance is "
               "off): either they are wedged/slow, or the barrier "
               "sequence numbers have diverged — every rank must call "
               "kv_barrier symmetrically (same count, same order); check "
               "for rank-dependent Trainer construction or JAX_PLATFORMS "
               "skew at world formation.")
    return (f"kv_barrier timeout: rank {rank}/{size} waited {timeout}s on "
            f"tag={tag!r} seq={seq}; missing ranks: "
            f"{missing or '<none — raced to completion>'} "
            f"(keys barrier/{key}:<r>). {verdict}")


def sync_compile_needed() -> bool:
    """True when the compile→barrier→dispatch pattern is required: a
    multi-process world on the CPU/gloo backend (see kv_barrier). Reads
    the decision RECORDED at world formation — a later JAX_PLATFORMS
    mutation must not make step-time behavior disagree with how the
    world was actually formed."""
    return _initialized_here and _cpu_gloo_world


def shutdown_jax_distributed() -> None:
    global _initialized_here, _world
    with _lock:
        if not _initialized_here:
            return
        import jax

        # ORDERED teardown under elastic.  With recoverability on, the
        # coordination service's shutdown barrier no longer blocks, so the
        # coordinator can tear the service down while peers are still
        # connected; a client that outlives the service is killed by
        # jaxlib's error-polling thread (LOG(FATAL), client.h:80 — the
        # callback that could soften it isn't reachable from Python, and
        # jaxlib 0.9's binding for it aborts on std::bad_cast).  A FATALed
        # survivor exits nonzero, gets its healthy host blacklisted, and
        # can sink the elastic job.  So: non-coordinator ranks disconnect
        # FIRST (service still up -> clean ShutdownTask, poll thread
        # stops), publishing a 'bye' marker to the rendezvous KV; the
        # coordinator waits for the markers (bounded grace — a dead peer
        # never writes one, and its agent is gone so it cannot FATAL)
        # before taking the service down.
        rank_size_kv = _world
        _world = None
        if rank_size_kv is not None and os.environ.get("HOROVOD_ELASTIC"):
            rank, size, kv, epoch = rank_size_kv
            if kv is not None and size > 1:
                import time
                if rank == 0:
                    # Dead peers never write a marker, so a plain
                    # wait-for-all would stall the full grace on every
                    # failure-triggered re-form.  Settle heuristic: live
                    # peers disconnect within moments of each other, so
                    # stop once no NEW marker has arrived for settle_s
                    # (grace remains the hard cap for starved hosts).
                    grace = float(os.environ.get(
                        "HOROVOD_JAX_TEARDOWN_GRACE_SECONDS", "30"))
                    settle = min(grace, float(os.environ.get(
                        "HOROVOD_JAX_TEARDOWN_SETTLE_SECONDS", "10")))
                    deadline = time.monotonic() + grace
                    last_progress = time.monotonic()
                    pending = set(range(1, size))
                    while pending:
                        now = time.monotonic()
                        if now > deadline or now > last_progress + settle:
                            break
                        for r in list(pending):
                            try:
                                if kv.get(_COORD_SCOPE,
                                          f"bye:{epoch}:{r}") is not None:
                                    pending.discard(r)
                                    last_progress = time.monotonic()
                            except Exception:  # noqa: BLE001 - kv gone
                                pending.clear()
                                break
                        if pending:
                            time.sleep(0.05)
                    if pending:
                        logger.warning(
                            "proceeding with coordination-service "
                            "teardown; ranks %s never disconnected "
                            "(dead peers cannot, live ones may FATAL)",
                            sorted(pending))
                else:
                    try:
                        jax.distributed.shutdown()
                    except Exception as exc:  # noqa: BLE001
                        logger.warning("jax.distributed.shutdown failed: "
                                       "%s", exc)
                        _force_clear_distributed_state()
                    try:
                        kv.put(_COORD_SCOPE, f"bye:{epoch}:{rank}", b"1")
                    except Exception:  # noqa: BLE001 - launcher gone
                        pass
                    _clear_backends()
                    _initialized_here = False
                    return
        try:
            jax.distributed.shutdown()
        except Exception as exc:  # noqa: BLE001 - best-effort teardown
            logger.warning("jax.distributed.shutdown failed: %s", exc)
            _force_clear_distributed_state()
        _clear_backends()
        _initialized_here = False


def _force_clear_distributed_state() -> None:
    """A failed disconnect (e.g. the coordinator tore down first after a
    peer death) leaves jax's global State partially populated, and the
    next initialize() would raise "should only be called once".  Finish
    the teardown field by field."""
    try:
        from jax._src import distributed as _dist_mod
        gs = _dist_mod.global_state
        for attr in ("preemption_sync_manager", "client", "service"):
            obj = getattr(gs, attr, None)
            if obj is not None:
                try:
                    obj.shutdown()
                except Exception:  # noqa: BLE001
                    pass
                setattr(gs, attr, None)
        gs.coordinator_address = None
    except Exception as exc:  # noqa: BLE001
        logger.warning("forced distributed-state cleanup failed: %s", exc)


def _clear_backends() -> None:
    """Evict the live backends: device lists from the old world would
    otherwise survive the shutdown, and the next jax.distributed.initialize
    (elastic re-rendezvous, SURVEY §7 "elastic re-init on TPU") could not
    re-form the client.  Validated in-process: see
    tests/test_elastic_integration.py (elastic XLA world) — shutdown →
    clear → initialize works on the gloo CPU plane."""
    try:
        import jax.extend.backend as _xb
        _xb.clear_backends()
    except Exception as exc:  # noqa: BLE001
        logger.warning("clear_backends failed: %s", exc)


def should_init(size: int) -> bool:
    """Policy for the `auto` knob: form the JAX world on multi-process
    launches unless the process is pinned to the CPU backend (tests pin
    JAX_PLATFORMS=cpu and drive multi-process JAX explicitly)."""
    from ..common import config
    mode = config.parse_tristate(config.JAX_DISTRIBUTED.get())
    if mode is True:
        return size > 1
    if mode is False:
        return False
    # auto: a real accelerator backend will be used
    return size > 1 and os.environ.get("JAX_PLATFORMS", "") != "cpu"


def make_global_array(mesh, spec, array):
    """Build a global `jax.Array` from a process-local view of the full
    array: each process contributes only the shards the sharding places on
    its addressable devices. Works identically single- and multi-process
    (the multi-host data-feed path; the reference never needs this because
    each rank's framework owns its local batch outright)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    arr = np.asarray(array)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def make_global_batch(mesh, spec, batch: dict) -> dict:
    """`make_global_array` over a dict of per-example arrays."""
    import jax
    return {k: make_global_array(mesh, spec, v) if hasattr(v, "shape")
            else v for k, v in batch.items()}
