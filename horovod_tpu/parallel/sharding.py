"""Parameter/activation sharding rules.

A lightweight, framework-agnostic path→PartitionSpec rule system: params
are placed by matching their pytree path against ordered regex rules,
first match wins, default replicated. This plays the role the reference
never needed (it only ever sees whole replicated tensors) but which a
mesh-native framework requires to express tp/fsdp/ep layouts.
"""
from __future__ import annotations

import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


class ShardingRules:
    """Ordered (regex, PartitionSpec) rules.

    >>> rules = ShardingRules([
    ...     (r".*attention.*kernel", P(None, "tp")),
    ...     (r".*mlp/up.*kernel",    P(None, "tp")),
    ...     (r".*mlp/down.*kernel",  P("tp", None)),
    ... ])
    """

    def __init__(self, rules: Sequence[tuple[str, P]] = (),
                 default: P = P()) -> None:
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]
        self._default = default

    def spec_for(self, path: str, leaf=None) -> P:
        for pat, spec in self._rules:
            if pat.search(path):
                if leaf is not None and len(spec) > getattr(leaf, "ndim", 99):
                    continue   # rule doesn't fit this rank; keep looking
                return spec
        return self._default

    def tree_specs(self, tree: Any) -> Any:
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: self.spec_for(_path_str(path), leaf), tree)


def named_sharding(mesh: Mesh, spec: P = P()) -> NamedSharding:
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_params(params: Any, mesh: Mesh,
                 rules: ShardingRules | None = None) -> Any:
    """Place a parameter pytree onto the mesh according to the rules
    (default: fully replicated, the reference's DP layout)."""
    rules = rules or ShardingRules()
    specs = rules.tree_specs(params)
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(
            leaf, NamedSharding(mesh, spec)), params, specs)


def constrain(x: jax.Array, mesh: Mesh, spec: P) -> jax.Array:
    """Annotate an intermediate's layout inside jit."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
