"""Parameter/activation sharding rules.

A lightweight, framework-agnostic path→PartitionSpec rule system: params
are placed by matching their pytree path against ordered regex rules,
first match wins, default replicated. This plays the role the reference
never needed (it only ever sees whole replicated tensors) but which a
mesh-native framework requires to express tp/fsdp/ep layouts.
"""
from __future__ import annotations

import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..analysis.hvdshard.specs import (missing_axes, rule_coverage,
                                       spec_token)


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


class ShardingRules:
    """Ordered (regex, PartitionSpec) rules.

    >>> rules = ShardingRules([
    ...     (r".*attention.*kernel", P(None, "tp")),
    ...     (r".*mlp/up.*kernel",    P(None, "tp")),
    ...     (r".*mlp/down.*kernel",  P("tp", None)),
    ... ])
    """

    def __init__(self, rules: Sequence[tuple[str, P]] = (),
                 default: P = P()) -> None:
        self._patterns = [pat for pat, _ in rules]
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]
        self._default = default

    def spec_for(self, path: str, leaf=None) -> P:
        for pat, spec in self._rules:
            if pat.search(path):
                if leaf is not None and len(spec) > getattr(leaf, "ndim", 99):
                    continue   # rule doesn't fit this rank; keep looking
                return spec
        return self._default

    def tree_specs(self, tree: Any) -> Any:
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: self.spec_for(_path_str(path), leaf), tree)

    def validate(self, mesh: Mesh, params: Any) -> list[str]:
        """Human-readable problems in this rule table against a REAL
        mesh and parameter tree — the runtime consumer of the same
        analysis core (specs.rule_coverage/missing_axes) hvdshard's
        HVD801/HVD802 run statically over harvested literals: one
        implementation, two call sites, so the static pass and the
        runtime check can never disagree on what a dead rule or an
        unknown axis is.  Returns [] when the table is coherent; the
        Trainer logs (or, strictly, raises on) anything else."""
        problems: list[str] = []
        mesh_axes = tuple(mesh.axis_names)
        for (_, spec), pat in zip(self._rules, self._patterns):
            bad = missing_axes(spec_token(spec), mesh_axes)
            if bad:
                problems.append(
                    f"rule {pat!r} names mesh ax"
                    f"{'es' if len(bad) > 1 else 'is'} "
                    f"{', '.join(repr(a) for a in bad)} absent from the "
                    f"mesh {mesh_axes} (HVD802)")
        paths: list[str] = []
        jax.tree_util.tree_map_with_path(
            lambda path, leaf: paths.append(_path_str(path)), params)
        table = [(pat, spec_token(spec))
                 for (_, spec), pat in zip(self._rules, self._patterns)]
        dead, uncovered = rule_coverage(table, paths)
        for pat in dead:
            problems.append(
                f"rule {pat!r} matches no parameter path in this tree "
                f"(HVD801 dead rule)")
        for path, sib in uncovered:
            problems.append(
                f"path '{path}' falls through to the replicated default "
                f"while sibling rule {sib!r} shards its neighbours "
                f"(HVD801 uncovered path)")
        return problems


def named_sharding(mesh: Mesh, spec: P = P()) -> NamedSharding:
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_params(params: Any, mesh: Mesh,
                 rules: ShardingRules | None = None) -> Any:
    """Place a parameter pytree onto the mesh according to the rules
    (default: fully replicated, the reference's DP layout)."""
    rules = rules or ShardingRules()
    specs = rules.tree_specs(params)
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(
            leaf, NamedSharding(mesh, spec)), params, specs)


def constrain(x: jax.Array, mesh: Mesh, spec: P) -> jax.Array:
    """Annotate an intermediate's layout inside jit."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
