"""Ulysses-style sequence parallelism: all-to-all head/sequence reshard.

The reference's ``alltoall`` primitive (operations.cc:1136-1198) is exactly
the transport a Ulysses SP needs (SURVEY §5.7); here it is the XLA
``all_to_all`` over the "sp" mesh axis: sequence-sharded activations
[B, T/n, H, D] reshard to head-sharded [B, T, H/n, D], run *any* full-
sequence attention locally (dense or the Pallas flash kernel), and reshard
back.  Two all-to-alls per attention instead of a ring of n permutes —
cheaper when H >= n and sequence chunks are large.

Run inside shard_map over the "sp" axis (composes with "dp" batch axes).
"""
from __future__ import annotations

from typing import Callable

import jax
from jax import lax

from .ring_attention import local_attention


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis: str = "sp", causal: bool = False,
                      sm_scale: float | None = None,
                      attn_fn: Callable | None = None,
                      axis_size: int | None = None) -> jax.Array:
    """q, k, v: local shards [B, T_local, H, D]; heads H must be divisible
    by the axis size.  ``attn_fn(q, k, v, causal=..., sm_scale=...)`` runs
    full-sequence attention on the head shard (defaults to dense local
    attention; pass ops.flash_attention for the fused kernel)."""
    n = axis_size if axis_size is not None else lax.psum(1, axis)
    if isinstance(n, jax.Array):
        raise ValueError(
            "ulysses_attention needs the static axis size; pass axis_size= "
            "or run under shard_map where psum(1, axis) is static")
    if attn_fn is None:
        attn_fn = local_attention
    if n == 1:
        return attn_fn(q, k, v, causal=causal, sm_scale=sm_scale)

    h = q.shape[2]
    if h % n:
        raise ValueError(f"{h} heads not divisible by sp={n}")

    def seq_to_heads(x):
        # [B, T/n, H, D] → [B, T, H/n, D]: split the head dim across the
        # axis, gather the sequence dim.
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    out = attn_fn(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v),
                  causal=causal, sm_scale=sm_scale)
    return heads_to_seq(out)
