"""Pipeline parallelism over the "pp" mesh axis.

The reference has no pipeline parallelism (SURVEY §2.6) — on TPU the
idiomatic form is an SPMD collective-permute pipeline (GPipe schedule):
every pp rank holds one stage's parameters; microbatches enter at stage 0,
activations hop to the next stage via ``ppermute`` each tick, and after
``num_microbatches + num_stages - 1`` ticks every microbatch has crossed
every stage.  The loop is a ``lax.scan``, so XLA overlaps each tick's
compute with the neighbor transfer — the classic fill/drain bubble is the
only overhead.

Run inside shard_map with the "pp" axis manual, stage-stacked params
sharded ``P("pp")`` on their leading axis.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, x: jax.Array, *,
                   axis: str = "pp",
                   num_microbatches: int | None = None,
                   axis_size: int | None = None) -> jax.Array:
    """Run ``x`` through a pipeline of stages.

    - ``stage_fn(params, h) -> h``: one stage's computation; identical
      activation shapes at every stage boundary.
    - ``stage_params``: THIS rank's stage parameters (leading stage dim
      already sharded away by shard_map).
    - ``x``: the local batch [B, ...]; it is split into microbatches along
      the leading dim.  Every pp rank receives the same batch and returns
      the same output (replicated semantics), so the surrounding data/
      optimizer code need not care about pipelining.

    Returns stage_{n-1}(...stage_0(x)) for the full batch.
    """
    n = axis_size if axis_size is not None else lax.psum(1, axis)
    if isinstance(n, jax.Array):
        raise ValueError(
            "pipeline_apply needs the static stage count; pass axis_size= "
            "or run under shard_map where psum(1, axis) is static")
    if n == 1:
        return stage_fn(stage_params, x)
    m = num_microbatches or n
    b = x.shape[0]
    assert b % m == 0, f"batch {b} not divisible by {m} microbatches"
    micro = x.reshape(m, b // m, *x.shape[1:])

    stage_idx = lax.axis_index(axis)
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]   # to the next stage
    total_ticks = m + n - 1

    def tick(carry, t):
        outputs, buf = carry
        # Stage 0 ingests microbatch t (or zeros once drained).
        feed = micro[jnp.minimum(t, m - 1)] * (t < m)
        h_in = jnp.where(stage_idx == 0, feed, buf)
        h_out = stage_fn(stage_params, h_in)
        # The last stage's output for microbatch (t - (n-1)) is complete.
        out_idx = t - (n - 1)
        is_valid = out_idx >= 0
        outputs = lax.cond(
            is_valid,
            lambda o: o.at[jnp.maximum(out_idx, 0)].set(
                jnp.where(stage_idx == n - 1, h_out, o[jnp.maximum(out_idx, 0)])),
            lambda o: o,
            outputs)
        # Activations hop to the next stage (the wrap-around into stage 0
        # is overwritten by the feed next tick).
        buf_next = lax.ppermute(h_out, axis, fwd_perm)
        return (outputs, buf_next), None

    def _vary(x):
        """Mark a replicated literal as axis-varying (vma) for shard_map
        type checking; API renamed pvary → pcast across jax versions."""
        if hasattr(lax, "pcast"):
            return lax.pcast(x, (axis,), to="varying")
        if hasattr(lax, "pvary"):
            return lax.pvary(x, (axis,))
        return x

    out_shape = jax.eval_shape(stage_fn, stage_params, micro[0])
    outputs0 = _vary(jnp.zeros((m,) + tuple(out_shape.shape),
                               out_shape.dtype))
    buf0 = _vary(jnp.zeros_like(micro[0]))

    (outputs, _), _ = lax.scan(tick, (outputs0, buf0),
                               jnp.arange(total_ticks))
    # Only the last stage holds real outputs; broadcast them to every pp
    # rank so the result is replicated over the axis.
    outputs = lax.psum(
        jnp.where(stage_idx == n - 1, outputs, jnp.zeros_like(outputs)),
        axis)
    return outputs.reshape(b, *outputs.shape[2:])
