"""TPU-native parallelism layer: device meshes, SPMD collectives, and the
fused gradient-synchronization pipeline.

This package is the data-plane heart of horovod_tpu (SURVEY §7 step 3):
where the reference dispatches NCCL/MPI calls from a C++ background thread
(reference: horovod/common/ops/nccl_operations.cc), we compile collectives
into the training step itself — `jax.lax.psum` / `all_gather` /
`ppermute` / `all_to_all` over a `jax.sharding.Mesh`, traced once under
`jit` and executed on the ICI fabric by XLA.

Topology model (reference: horovod/common/common.h:119-136 — GLOBAL /
LOCAL / CROSS communicators): ICI mesh axes play the "local" role, the
DCN (inter-host) axis plays "cross"; hierarchical reductions ride ICI
first, then DCN.
"""
from .mesh import MeshSpec, build_mesh, axis_size, data_axes, DEFAULT_AXES
from .collectives import (allreduce, allgather, alltoall, broadcast,
                          reduce_scatter, adasum_allreduce, device_collective)
from .grad_sync import (GradSyncConfig, build_grad_sync,
                        init_error_feedback, init_ring_optimizer_state,
                        ring_chunk_size, sync_and_apply, sync_gradients,
                        sync_gradients_ef)
from .sharding import (ShardingRules, shard_params, named_sharding,
                       constrain, replicated)
from .ring_attention import local_attention, ring_attention
from .ulysses import ulysses_attention

__all__ = [
    "ring_attention", "local_attention", "ulysses_attention",
    "MeshSpec", "build_mesh", "axis_size", "data_axes", "DEFAULT_AXES",
    "allreduce", "allgather", "alltoall", "broadcast", "reduce_scatter",
    "adasum_allreduce", "device_collective",
    "GradSyncConfig", "build_grad_sync", "sync_gradients",
    "sync_gradients_ef", "init_error_feedback", "sync_and_apply",
    "init_ring_optimizer_state", "ring_chunk_size",
    "ShardingRules", "shard_params", "named_sharding", "constrain",
    "replicated",
]
