"""Fused gradient synchronization — tensor fusion + compression + reduce,
compiled into the training step.

This is the SPMD re-design of the reference's hot path (SURVEY §3.2): where
the reference's background thread batches gradient tensors into a 64 MB
fusion buffer and calls ncclAllReduce per batch (reference:
horovod/common/controller.cc:778-915 FuseResponses;
ops/nccl_operations.cc:126-184), we bucket the gradient pytree into
fusion-threshold-sized flat buffers *at trace time* and emit one AllReduce
HLO per bucket. XLA schedules them back-to-back on ICI with no host in the
loop — negotiation cost is zero because SPMD guarantees every rank runs the
identical program (the property the reference's controller exists to
establish dynamically).

Compression:
- fp16/bf16 mirror horovod.torch.Compression.fp16 (reference:
  horovod/torch/compression.py:46-63): cast the bucket to a 16-bit wire
  type before the reduce, cast back after, with the reduction itself
  carried out in the wire dtype exactly like the reference's fp16 NCCL
  allreduce.
- int8/uint4 are the EQuARX-style block-quantized allreduce
  (compress/jax_ops.py): XLA fuses per-block quantize → all_to_all →
  fp32 reduce → requantize → all_gather into the step program, moving
  ~1/4 (int8) / ~1/8 (uint4) of the fp32 bytes over ICI/DCN.  With
  ``error_feedback=True`` the quantization error threads through
  ``sync_gradients_ef`` as explicit residual state (EF-SGD), so it is
  re-injected next step instead of lost.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from .collectives import allreduce, adasum_allreduce

_WIRE_DTYPES = {"fp16": jnp.float16, "bf16": jnp.bfloat16,
                "none": None, None: None}
_QUANTIZED = ("int8", "uint4")


def _quantized_codec(compression):
    if compression in _QUANTIZED:
        from ..compress import codec_from_name
        return codec_from_name(compression)
    return None


@dataclasses.dataclass(frozen=True)
class GradSyncConfig:
    """Knobs mirroring the reference env contract
    (reference: common/common.h:66-96 HOROVOD_FUSION_THRESHOLD et al.)."""
    axes: tuple[str, ...] = ("dp",)
    op: str = "average"                   # sum | average | adasum
    compression: str | None = None        # fp16 | bf16 | int8 | uint4 | None
    # Quantization block for int8/uint4 (elements; even for uint4).
    compression_block_size: int = 256
    # EF-SGD residual re-injection for the quantized codecs; state
    # threads through sync_gradients_ef (see init_error_feedback).
    error_feedback: bool = False
    fusion_threshold_bytes: int = 64 * 1024 * 1024
    # Hierarchical two-stage reduction (reference: HOROVOD_HIERARCHICAL_
    # ALLREDUCE + NCCLHierarchicalAllreduce, nccl_operations.cc:187-398):
    # reduce-scatter over the LOCAL (ICI, axes[1:]) leg, allreduce the
    # shards over the CROSS (DCN, axes[0]) leg, all-gather back over local.
    # With a flat mesh XLA usually derives this itself; the explicit form
    # pins the decomposition (and the wire dtype per leg) when profiling
    # says it matters.
    hierarchical: bool = False
    # Adasum is applied per-tensor (the reference computes per-layer dot
    # products, adasum.h:38-552); sum/average fuse into buckets.


def _bucketize(leaves: list[jax.Array], threshold: int,
               itemsize: int | None = None) -> list[list[int]]:
    """Greedy size-ordered bucketing, preserving leaf order inside a
    bucket (the reference fuses in request order with look-ahead,
    controller.cc:778-915). `itemsize` overrides the leaf dtype width so
    buckets are sized in *wire* bytes when compression is active."""
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i, leaf in enumerate(leaves):
        nbytes = leaf.size * (itemsize or leaf.dtype.itemsize)
        if cur and cur_bytes + nbytes > threshold:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def sync_gradients(grads: Any, config: GradSyncConfig = GradSyncConfig()
                   ) -> Any:
    """Reduce a gradient pytree over the mesh axes. Call inside a
    shard_mapped / jitted train step."""
    out, _ = _sync_impl(grads, config, None)
    return out


def init_error_feedback(grads: Any) -> Any:
    """Zero EF residual state matching a gradient pytree (fp32 — the
    residual must hold error finer than the wire can carry)."""
    return jax.tree_util.tree_map(
        lambda g: jnp.zeros(jnp.shape(g), jnp.float32), grads)


def sync_gradients_ef(grads: Any, residuals: Any,
                      config: GradSyncConfig) -> tuple[Any, Any]:
    """Error-feedback variant: quantization error of THIS step's wire is
    returned as residual state and re-added to the next step's gradients
    (EF-SGD), recovering uncompressed convergence for the quantized
    codecs.  Thread ``residuals`` through the jitted step; initialize
    with :func:`init_error_feedback`.  For non-quantized codecs the
    residuals pass through untouched."""
    if _quantized_codec(config.compression) is None:
        return sync_gradients(grads, config), residuals
    return _sync_impl(grads, config, residuals)


def _sync_impl(grads: Any, config: GradSyncConfig,
               residuals: Any | None) -> tuple[Any, Any | None]:
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads, residuals
    codec = _quantized_codec(config.compression)
    wire = _WIRE_DTYPES[config.compression] if codec is None else None

    if config.op == "adasum":
        if codec is not None:
            raise ValueError(
                "adasum does not compose with quantized compression "
                "(int8/uint4): the scale-adaptive dot products would be "
                "computed on quantized blocks. Use none, fp16 or bf16.")
        # Per-tensor combine (the reference computes per-layer dot
        # products, adasum.h:38-552); compression composes around the
        # exchange exactly as in the sum path.
        out = []
        for leaf in leaves:
            v = leaf
            if wire is not None and jnp.issubdtype(leaf.dtype, jnp.floating):
                v = v.astype(wire)
            out.append(adasum_allreduce(v, config.axes).astype(leaf.dtype))
        return jax.tree_util.tree_unflatten(treedef, out), residuals

    res_leaves: list | None = None
    if residuals is not None:
        res_leaves = jax.tree_util.tree_flatten(residuals)[0]
        if len(res_leaves) != len(leaves):
            raise ValueError(
                "error-feedback residual pytree does not match the "
                "gradient pytree; initialize with init_error_feedback()")
    res_out = list(res_leaves) if res_leaves is not None else None

    out: list[jax.Array | None] = [None] * len(leaves)
    # Group leaves by dtype so each fused buffer is homogeneous, same as
    # the reference's per-dtype responses (controller.cc ConstructResponse
    # dtype consistency check).
    by_dtype: dict[Any, list[int]] = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(leaf.dtype), []).append(i)

    for dtype, idxs in by_dtype.items():
        group = [leaves[i] for i in idxs]
        quantized = codec is not None and jnp.issubdtype(dtype,
                                                         jnp.floating)
        if quantized:
            # Buckets sized in wire bytes: ~1 byte/elem (int8) or
            # ~0.5 (uint4) + block metadata; 1 is a close upper bound.
            wire_itemsize: int | None = 1
        else:
            wire_itemsize = jnp.dtype(wire).itemsize \
                if wire is not None and jnp.issubdtype(dtype, jnp.floating) \
                else None
        for bucket in _bucketize(group, config.fusion_threshold_bytes,
                                 wire_itemsize):
            members = [idxs[j] for j in bucket]
            flat = jnp.concatenate(
                [leaves[i].reshape(-1) for i in members]) \
                if len(members) > 1 else leaves[members[0]].reshape(-1)
            if quantized:
                from ..compress.jax_ops import quantized_allreduce
                # The quantized exchange is already its own two-phase
                # (scatter-reduce/gather) decomposition, so the explicit
                # hierarchical split does not apply on top of it.
                if res_out is not None:
                    rflat = jnp.concatenate(
                        [res_leaves[i].reshape(-1) for i in members]) \
                        if len(members) > 1 \
                        else res_leaves[members[0]].reshape(-1)
                    flat, new_res = quantized_allreduce(
                        flat, config.axes, config.op, codec,
                        config.compression_block_size, residual=rflat)
                    offset = 0
                    for i in members:
                        n = leaves[i].size
                        res_out[i] = new_res[offset:offset + n].reshape(
                            leaves[i].shape)
                        offset += n
                else:
                    flat = quantized_allreduce(
                        flat, config.axes, config.op, codec,
                        config.compression_block_size)
            else:
                if wire is not None and jnp.issubdtype(dtype, jnp.floating):
                    flat = flat.astype(wire)
                if config.hierarchical and len(config.axes) >= 2:
                    flat = _hierarchical_allreduce(flat, config.axes,
                                                   config.op)
                else:
                    flat = allreduce(flat, config.axes, config.op)
            flat = flat.astype(dtype)
            offset = 0
            for i in members:
                n = leaves[i].size
                out[i] = flat[offset:offset + n].reshape(leaves[i].shape)
                offset += n
    synced = jax.tree_util.tree_unflatten(treedef, out)
    if res_out is None:
        return synced, residuals
    res_treedef = jax.tree_util.tree_flatten(residuals)[1]
    return synced, jax.tree_util.tree_unflatten(res_treedef, res_out)


def _hierarchical_allreduce(flat: jax.Array, axes: Sequence[str],
                            op: str) -> jax.Array:
    """reduce_scatter(local) → allreduce(cross) → all_gather(local)
    (reference: NCCLHierarchicalAllreduce's ReduceScatter → cross-node
    MPI_Allreduce → AllGather split, nccl_operations.cc:250-372, including
    its remainder handling via padding)."""
    from jax import lax

    cross, locals_ = axes[0], tuple(axes[1:])
    local_size = 1
    for a in locals_:
        local_size *= lax.psum(1, a)
    n = flat.shape[0]
    pad = (-n) % local_size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    # Sum-scatter over the combined local axes, innermost first.
    shard = flat
    for a in locals_:
        shard = lax.psum_scatter(shard, a, scatter_dimension=0, tiled=True)
    shard = lax.psum(shard, cross)
    full = shard
    for a in reversed(locals_):
        full = lax.all_gather(full, a, axis=0, tiled=True)
    if pad:
        full = full[:n]
    if op == "average":
        world = lax.psum(1, cross) * local_size
        full = full / world
    return full


def build_grad_sync(mesh, config: GradSyncConfig = GradSyncConfig()):
    """Host-level compiled sync over stacked per-rank gradients: each leaf
    has leading dim = prod(axis sizes); mainly for tests and the eager
    API."""
    from jax.sharding import PartitionSpec as P

    from ..common.jax_compat import shard_map

    spec = P(config.axes)

    def _sync(grads):
        return sync_gradients(grads, config)

    mapped = shard_map(_sync, mesh=mesh, in_specs=spec, out_specs=spec,
                       check_vma=False)
    return jax.jit(mapped)
