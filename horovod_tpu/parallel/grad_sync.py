"""Fused gradient synchronization — tensor fusion + compression + reduce,
compiled into the training step.

This is the SPMD re-design of the reference's hot path (SURVEY §3.2): where
the reference's background thread batches gradient tensors into a 64 MB
fusion buffer and calls ncclAllReduce per batch (reference:
horovod/common/controller.cc:778-915 FuseResponses;
ops/nccl_operations.cc:126-184), we bucket the gradient pytree into
fusion-threshold-sized flat buffers *at trace time* and emit one AllReduce
HLO per bucket. XLA schedules them back-to-back on ICI with no host in the
loop — negotiation cost is zero because SPMD guarantees every rank runs the
identical program (the property the reference's controller exists to
establish dynamically).

Compression mirrors horovod.torch.Compression.fp16 (reference:
horovod/torch/compression.py:46-63): cast the bucket to a 16-bit wire type
before the reduce, cast back after, with the reduction itself carried out
in the wire dtype exactly like the reference's fp16 NCCL allreduce.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from .collectives import allreduce, adasum_allreduce

_WIRE_DTYPES = {"fp16": jnp.float16, "bf16": jnp.bfloat16,
                "none": None, None: None}


@dataclasses.dataclass(frozen=True)
class GradSyncConfig:
    """Knobs mirroring the reference env contract
    (reference: common/common.h:66-96 HOROVOD_FUSION_THRESHOLD et al.)."""
    axes: tuple[str, ...] = ("dp",)
    op: str = "average"                   # sum | average | adasum
    compression: str | None = None        # fp16 | bf16 | None
    fusion_threshold_bytes: int = 64 * 1024 * 1024
    # Hierarchical two-stage reduction (reference: HOROVOD_HIERARCHICAL_
    # ALLREDUCE + NCCLHierarchicalAllreduce, nccl_operations.cc:187-398):
    # reduce-scatter over the LOCAL (ICI, axes[1:]) leg, allreduce the
    # shards over the CROSS (DCN, axes[0]) leg, all-gather back over local.
    # With a flat mesh XLA usually derives this itself; the explicit form
    # pins the decomposition (and the wire dtype per leg) when profiling
    # says it matters.
    hierarchical: bool = False
    # Adasum is applied per-tensor (the reference computes per-layer dot
    # products, adasum.h:38-552); sum/average fuse into buckets.


def _bucketize(leaves: list[jax.Array], threshold: int,
               itemsize: int | None = None) -> list[list[int]]:
    """Greedy size-ordered bucketing, preserving leaf order inside a
    bucket (the reference fuses in request order with look-ahead,
    controller.cc:778-915). `itemsize` overrides the leaf dtype width so
    buckets are sized in *wire* bytes when compression is active."""
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i, leaf in enumerate(leaves):
        nbytes = leaf.size * (itemsize or leaf.dtype.itemsize)
        if cur and cur_bytes + nbytes > threshold:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def sync_gradients(grads: Any, config: GradSyncConfig = GradSyncConfig()
                   ) -> Any:
    """Reduce a gradient pytree over the mesh axes. Call inside a
    shard_mapped / jitted train step."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    wire = _WIRE_DTYPES[config.compression]

    if config.op == "adasum":
        # Per-tensor combine (the reference computes per-layer dot
        # products, adasum.h:38-552); compression composes around the
        # exchange exactly as in the sum path.
        out = []
        for leaf in leaves:
            v = leaf
            if wire is not None and jnp.issubdtype(leaf.dtype, jnp.floating):
                v = v.astype(wire)
            out.append(adasum_allreduce(v, config.axes).astype(leaf.dtype))
        return jax.tree_util.tree_unflatten(treedef, out)

    out: list[jax.Array | None] = [None] * len(leaves)
    # Group leaves by dtype so each fused buffer is homogeneous, same as
    # the reference's per-dtype responses (controller.cc ConstructResponse
    # dtype consistency check).
    by_dtype: dict[Any, list[int]] = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(leaf.dtype), []).append(i)

    for dtype, idxs in by_dtype.items():
        group = [leaves[i] for i in idxs]
        wire_itemsize = jnp.dtype(wire).itemsize \
            if wire is not None and jnp.issubdtype(dtype, jnp.floating) \
            else None
        for bucket in _bucketize(group, config.fusion_threshold_bytes,
                                 wire_itemsize):
            members = [idxs[j] for j in bucket]
            flat = jnp.concatenate(
                [leaves[i].reshape(-1) for i in members]) \
                if len(members) > 1 else leaves[members[0]].reshape(-1)
            if wire is not None and jnp.issubdtype(dtype, jnp.floating):
                flat = flat.astype(wire)
            if config.hierarchical and len(config.axes) >= 2:
                flat = _hierarchical_allreduce(flat, config.axes, config.op)
            else:
                flat = allreduce(flat, config.axes, config.op)
            flat = flat.astype(dtype)
            offset = 0
            for i in members:
                n = leaves[i].size
                out[i] = flat[offset:offset + n].reshape(leaves[i].shape)
                offset += n
    return jax.tree_util.tree_unflatten(treedef, out)


def _hierarchical_allreduce(flat: jax.Array, axes: Sequence[str],
                            op: str) -> jax.Array:
    """reduce_scatter(local) → allreduce(cross) → all_gather(local)
    (reference: NCCLHierarchicalAllreduce's ReduceScatter → cross-node
    MPI_Allreduce → AllGather split, nccl_operations.cc:250-372, including
    its remainder handling via padding)."""
    from jax import lax

    cross, locals_ = axes[0], tuple(axes[1:])
    local_size = 1
    for a in locals_:
        local_size *= lax.psum(1, a)
    n = flat.shape[0]
    pad = (-n) % local_size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    # Sum-scatter over the combined local axes, innermost first.
    shard = flat
    for a in locals_:
        shard = lax.psum_scatter(shard, a, scatter_dimension=0, tiled=True)
    shard = lax.psum(shard, cross)
    full = shard
    for a in reversed(locals_):
        full = lax.all_gather(full, a, axis=0, tiled=True)
    if pad:
        full = full[:n]
    if op == "average":
        world = lax.psum(1, cross) * local_size
        full = full / world
    return full


def build_grad_sync(mesh, config: GradSyncConfig = GradSyncConfig()):
    """Host-level compiled sync over stacked per-rank gradients: each leaf
    has leading dim = prod(axis sizes); mainly for tests and the eager
    API."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    spec = P(config.axes)

    def _sync(grads):
        return sync_gradients(grads, config)

    mapped = shard_map(_sync, mesh=mesh, in_specs=spec, out_specs=spec,
                       check_vma=False)
    return jax.jit(mapped)
