"""Fused gradient synchronization — tensor fusion + compression + reduce,
compiled into the training step.

This is the SPMD re-design of the reference's hot path (SURVEY §3.2): where
the reference's background thread batches gradient tensors into a 64 MB
fusion buffer and calls ncclAllReduce per batch (reference:
horovod/common/controller.cc:778-915 FuseResponses;
ops/nccl_operations.cc:126-184), we bucket the gradient pytree into
fusion-threshold-sized flat buffers *at trace time* and emit one AllReduce
HLO per bucket. XLA schedules them back-to-back on ICI with no host in the
loop — negotiation cost is zero because SPMD guarantees every rank runs the
identical program (the property the reference's controller exists to
establish dynamically).

Compression:
- fp16/bf16 mirror horovod.torch.Compression.fp16 (reference:
  horovod/torch/compression.py:46-63): cast the bucket to a 16-bit wire
  type before the reduce, cast back after, with the reduction itself
  carried out in the wire dtype exactly like the reference's fp16 NCCL
  allreduce.
- int8/uint4 are the EQuARX-style block-quantized allreduce
  (compress/jax_ops.py): XLA fuses per-block quantize → all_to_all →
  fp32 reduce → requantize → all_gather into the step program, moving
  ~1/4 (int8) / ~1/8 (uint4) of the fp32 bytes over ICI/DCN.  With
  ``error_feedback=True`` the quantization error threads through
  ``sync_gradients_ef`` as explicit residual state (EF-SGD), so it is
  re-injected next step instead of lost.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .collectives import allreduce, adasum_allreduce

_WIRE_DTYPES = {"fp16": jnp.float16, "bf16": jnp.bfloat16,
                "none": None, None: None}
_QUANTIZED = ("int8", "uint4")


def _quantized_codec(compression):
    if compression in _QUANTIZED:
        from ..compress import codec_from_name
        return codec_from_name(compression)
    return None


@dataclasses.dataclass(frozen=True)
class GradSyncConfig:
    """Knobs mirroring the reference env contract
    (reference: common/common.h:66-96 HOROVOD_FUSION_THRESHOLD et al.)."""
    axes: tuple[str, ...] = ("dp",)
    op: str = "average"                   # sum | average | adasum
    compression: str | None = None        # fp16 | bf16 | int8 | uint4 | None
    # Quantization block for int8/uint4 (elements; even for uint4).
    compression_block_size: int = 256
    # EF-SGD residual re-injection for the quantized codecs; state
    # threads through sync_gradients_ef (see init_error_feedback).
    error_feedback: bool = False
    fusion_threshold_bytes: int = 64 * 1024 * 1024
    # Hierarchical two-stage reduction (reference: HOROVOD_HIERARCHICAL_
    # ALLREDUCE + NCCLHierarchicalAllreduce, nccl_operations.cc:187-398):
    # reduce-scatter over the LOCAL (ICI, axes[1:]) leg, allreduce the
    # shards over the CROSS (DCN, axes[0]) leg, all-gather back over local.
    # With a flat mesh XLA usually derives this itself; the explicit form
    # pins the decomposition (and the wire dtype per leg) when profiling
    # says it matters.
    hierarchical: bool = False
    # Adasum is applied per-tensor (the reference computes per-layer dot
    # products, adasum.h:38-552); sum/average fuse into buckets.

    # --- fused loss-scaling + global-norm clipping -----------------------
    # Both ride the SAME compiled pass as the reduce (and quantize/EF):
    # the squared norm is taken on the already-hot reduced flat buckets
    # and the combined unscale×clip factor folds into the existing
    # slice-out multiply — no separate tree traversals, no second pass
    # over gradient memory (the fusion arXiv:2305.06942 argues for).
    # `loss_scale`: the loss was pre-multiplied by this factor (mixed-
    # precision loss scaling); gradients are unscaled by 1/loss_scale
    # after the reduce (norms are computed on UNSCALED values).
    loss_scale: float | None = None
    # Clip the global (all-leaf) L2 norm of the reduced, unscaled
    # gradients to this value (optax.clip_by_global_norm semantics).
    clip_global_norm: float | None = None

    # --- optimizer-in-ring (ZeRO-style; arXiv:2305.06942) ----------------
    # Apply the optax update during the last reduce-scatter leg: each
    # rank updates only its shard of the flat parameter buffer (optimizer
    # state sharded over ranks), and the UPDATED PARAMS — not gradients —
    # ride the closing all-gather.  Wire volume is identical to a plain
    # allreduce, but the update math runs once per shard instead of once
    # per replica and the optimizer state is 1/world per rank.  Opt-in:
    # use sync_and_apply() (or Trainer with this flag) instead of
    # sync_gradients + tx.update.  Composes with the cast codecs on both
    # legs and the quantized codecs on the gradient leg only (updated
    # params always ride full-width or cast wires — block-quantizing
    # parameters would accumulate reconstruction error step over step).
    optimizer_in_ring: bool = False


def _bucketize(leaves: list[jax.Array], threshold: int,
               itemsize: int | None = None) -> list[list[int]]:
    """Greedy size-ordered bucketing, preserving leaf order inside a
    bucket (the reference fuses in request order with look-ahead,
    controller.cc:778-915). `itemsize` overrides the leaf dtype width so
    buckets are sized in *wire* bytes when compression is active."""
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i, leaf in enumerate(leaves):
        nbytes = leaf.size * (itemsize or leaf.dtype.itemsize)
        if cur and cur_bytes + nbytes > threshold:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def sync_gradients(grads: Any, config: GradSyncConfig = GradSyncConfig()
                   ) -> Any:
    """Reduce a gradient pytree over the mesh axes. Call inside a
    shard_mapped / jitted train step."""
    out, _ = _sync_impl(grads, config, None)
    return out


def init_error_feedback(grads: Any) -> Any:
    """Zero EF residual state matching a gradient pytree (fp32 — the
    residual must hold error finer than the wire can carry)."""
    return jax.tree_util.tree_map(
        lambda g: jnp.zeros(jnp.shape(g), jnp.float32), grads)


def sync_gradients_ef(grads: Any, residuals: Any,
                      config: GradSyncConfig) -> tuple[Any, Any]:
    """Error-feedback variant: quantization error of THIS step's wire is
    returned as residual state and re-added to the next step's gradients
    (EF-SGD), recovering uncompressed convergence for the quantized
    codecs.  Thread ``residuals`` through the jitted step; initialize
    with :func:`init_error_feedback`.  For non-quantized codecs the
    residuals pass through untouched."""
    if _quantized_codec(config.compression) is None:
        return sync_gradients(grads, config), residuals
    return _sync_impl(grads, config, residuals)


def _sync_impl(grads: Any, config: GradSyncConfig,
               residuals: Any | None) -> tuple[Any, Any | None]:
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads, residuals
    codec = _quantized_codec(config.compression)
    wire = _WIRE_DTYPES[config.compression] if codec is None else None

    if config.op == "adasum":
        if codec is not None:
            raise ValueError(
                "adasum does not compose with quantized compression "
                "(int8/uint4): the scale-adaptive dot products would be "
                "computed on quantized blocks. Use none, fp16 or bf16.")
        if config.loss_scale is not None or \
                config.clip_global_norm is not None:
            raise ValueError(
                "adasum does not compose with fused loss-scaling/"
                "clipping: the scale-adaptive combine is not linear in "
                "the gradients, so post-hoc unscaling would change the "
                "update direction. Unscale/clip before sync instead.")
        # Per-tensor combine (the reference computes per-layer dot
        # products, adasum.h:38-552); compression composes around the
        # exchange exactly as in the sum path.
        out = []
        for leaf in leaves:
            v = leaf
            if wire is not None and jnp.issubdtype(leaf.dtype, jnp.floating):
                v = v.astype(wire)
            out.append(adasum_allreduce(v, config.axes).astype(leaf.dtype))
        return jax.tree_util.tree_unflatten(treedef, out), residuals

    res_leaves: list | None = None
    if residuals is not None:
        res_leaves = jax.tree_util.tree_flatten(residuals)[0]
        if len(res_leaves) != len(leaves):
            raise ValueError(
                "error-feedback residual pytree does not match the "
                "gradient pytree; initialize with init_error_feedback()")
    res_out = list(res_leaves) if res_leaves is not None else None

    out: list[jax.Array | None] = [None] * len(leaves)
    # Reduced flat buckets, slice-out deferred: (member leaf idxs, flat
    # reduced buffer, dtype, floating).  Deferral lets the fused
    # loss-scaling/clipping factor — which needs the GLOBAL norm across
    # every bucket — fold into the one multiply the slice-out pass
    # already performs, instead of a second traversal.
    reduced_buckets: list[tuple[list[int], jax.Array, Any, bool]] = []
    # Group leaves by dtype so each fused buffer is homogeneous, same as
    # the reference's per-dtype responses (controller.cc ConstructResponse
    # dtype consistency check).
    by_dtype: dict[Any, list[int]] = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(leaf.dtype), []).append(i)

    for dtype, idxs in by_dtype.items():
        group = [leaves[i] for i in idxs]
        quantized = codec is not None and jnp.issubdtype(dtype,
                                                         jnp.floating)
        if quantized:
            # Buckets sized in wire bytes: ~1 byte/elem (int8) or
            # ~0.5 (uint4) + block metadata; 1 is a close upper bound.
            wire_itemsize: int | None = 1
        else:
            wire_itemsize = jnp.dtype(wire).itemsize \
                if wire is not None and jnp.issubdtype(dtype, jnp.floating) \
                else None
        for bucket in _bucketize(group, config.fusion_threshold_bytes,
                                 wire_itemsize):
            members = [idxs[j] for j in bucket]
            flat = jnp.concatenate(
                [leaves[i].reshape(-1) for i in members]) \
                if len(members) > 1 else leaves[members[0]].reshape(-1)
            if quantized:
                from ..compress.jax_ops import quantized_allreduce
                # The quantized exchange is already its own two-phase
                # (scatter-reduce/gather) decomposition, so the explicit
                # hierarchical split does not apply on top of it.
                if res_out is not None:
                    rflat = jnp.concatenate(
                        [res_leaves[i].reshape(-1) for i in members]) \
                        if len(members) > 1 \
                        else res_leaves[members[0]].reshape(-1)
                    flat, new_res = quantized_allreduce(
                        flat, config.axes, config.op, codec,
                        config.compression_block_size, residual=rflat)
                    offset = 0
                    for i in members:
                        n = leaves[i].size
                        res_out[i] = new_res[offset:offset + n].reshape(
                            leaves[i].shape)
                        offset += n
                else:
                    flat = quantized_allreduce(
                        flat, config.axes, config.op, codec,
                        config.compression_block_size)
            else:
                if wire is not None and jnp.issubdtype(dtype, jnp.floating):
                    flat = flat.astype(wire)
                if config.hierarchical and len(config.axes) >= 2:
                    flat = _hierarchical_allreduce(flat, config.axes,
                                                   config.op)
                else:
                    flat = allreduce(flat, config.axes, config.op)
            reduced_buckets.append(
                (members, flat, dtype, jnp.issubdtype(dtype,
                                                      jnp.floating)))

    factor = _scale_clip_factor(
        config, [flat for _, flat, _, floating in reduced_buckets
                 if floating])
    for members, flat, dtype, floating in reduced_buckets:
        if factor is not None and floating:
            # The combined 1/loss_scale × clip factor rides the same
            # pass as the wire-dtype restore — XLA fuses both into one
            # elementwise kernel over the already-hot bucket.
            flat = (flat.astype(jnp.float32) * factor).astype(dtype)
        else:
            flat = flat.astype(dtype)
        offset = 0
        for i in members:
            n = leaves[i].size
            out[i] = flat[offset:offset + n].reshape(leaves[i].shape)
            offset += n
    synced = jax.tree_util.tree_unflatten(treedef, out)
    if res_out is None:
        return synced, residuals
    res_treedef = jax.tree_util.tree_flatten(residuals)[1]
    return synced, jax.tree_util.tree_unflatten(res_treedef, res_out)


def _scale_clip_factor(config: GradSyncConfig,
                       flats: "list[jax.Array]"):
    """Combined 1/loss_scale × global-norm-clip factor for the reduced
    flat buckets (None when neither knob is set).  The squared norm is
    computed on the buckets the sync pass just produced — no second tree
    traversal — and matches optax.clip_by_global_norm on the unscaled
    gradients: factor = inv · min(1, clip / (‖g‖ · inv))."""
    if config.loss_scale is None and config.clip_global_norm is None:
        return None
    inv = jnp.float32(1.0) if config.loss_scale is None \
        else jnp.float32(1.0 / config.loss_scale)
    if config.clip_global_norm is None:
        return inv
    gsq = jnp.float32(0.0)
    for flat in flats:
        f32 = flat.astype(jnp.float32)
        gsq = gsq + jnp.vdot(f32, f32)
    gnorm = jnp.sqrt(gsq) * inv            # norm of the UNSCALED grads
    clip = jnp.float32(config.clip_global_norm)
    return inv * jnp.minimum(1.0, clip / jnp.maximum(gnorm, 1e-16))


# ---------------------------------------------------------------------------
# Optimizer-in-ring (ZeRO-style fused sync+update; arXiv:2305.06942)
# ---------------------------------------------------------------------------
def ring_chunk_size(n_params: int, world_size: int,
                    config: GradSyncConfig) -> int:
    """Per-rank flat shard length for the optimizer-in-ring layout: the
    flat parameter buffer padded to world × chunk, chunk block-aligned
    when a quantized codec rides the gradient leg (so each rank's wire
    rows quantize on block boundaries)."""
    chunk = -(-n_params // max(world_size, 1))
    if _quantized_codec(config.compression) is not None:
        bs = config.compression_block_size
        chunk = -(-chunk // bs) * bs
    return chunk


def init_ring_optimizer_state(tx, params: Any, world_size: int,
                              config: GradSyncConfig) -> Any:
    """Optimizer state for ONE rank's flat fp32 shard (call per rank, or
    inside shard_map where every rank initializes its own shard).  The
    update math runs on the flat buffer, so only elementwise-style
    transforms (sgd/adam/adamw/lamb-like: state mirrors the params or is
    scalar) are supported — per-layer-norm transforms would need the
    leaf boundaries the flat layout erases."""
    n = sum(int(np.prod(jnp.shape(leaf)))
            for leaf in jax.tree_util.tree_leaves(params))
    chunk = ring_chunk_size(n, world_size, config)
    return tx.init(jnp.zeros((chunk,), jnp.float32))


def sync_and_apply(tx, grads: Any, params: Any, opt_state: Any,
                   config: GradSyncConfig) -> tuple[Any, Any]:
    """Fused gradient sync + optimizer update (optimizer-in-ring): call
    inside a shard_mapped / jitted train step in place of
    ``sync_gradients`` + ``tx.update`` + ``apply_updates``.

      1. flatten the gradient pytree into ONE fp32 buffer, padded to
         world × chunk;
      2. reduce-scatter it over ``config.axes`` — quantized codecs ship
         int8/uint4 rows through the same all_to_all leg as
         compress/jax_ops, cast codecs ship 16-bit words;
      3. apply the optax update on THIS RANK'S shard only (``opt_state``
         is the shard state from :func:`init_ring_optimizer_state` —
         ZeRO-style, 1/world of the replicated state);
      4. all-gather the UPDATED PARAM shards (cast codec honored) and
         unflatten back to the parameter pytree.

    Fused loss-scaling/clipping (config.loss_scale /
    clip_global_norm) applies on the reduced shard with one extra scalar
    psum for the global norm.  Returns ``(new_params, new_opt_state)``.

    The update math runs in fp32 on the flat buffer (master-weights
    style: params are widened for the update and cast back to their own
    dtypes), so results match sync-then-update to fp32 round-off, not
    bitwise, for sub-fp32 parameter dtypes."""
    import optax
    from jax import lax

    if config.op not in ("sum", "average"):
        raise ValueError(
            f"optimizer-in-ring supports op=sum|average, not "
            f"{config.op!r} (adasum's per-tensor combine needs the leaf "
            f"boundaries the flat shard layout erases)")
    if config.error_feedback:
        raise ValueError(
            "optimizer-in-ring does not thread error-feedback state yet; "
            "use sync_gradients_ef + tx.update, or drop error_feedback")
    axes = (config.axes,) if isinstance(config.axes, str) \
        else tuple(config.axes)
    if not axes:
        raise ValueError(
            "optimizer-in-ring needs explicit mesh axes (pure-GSPMD "
            "mode has no manual axis to shard the update over)")

    g_leaves, g_treedef = jax.tree_util.tree_flatten(grads)
    p_leaves, p_treedef = jax.tree_util.tree_flatten(params)
    if len(g_leaves) != len(p_leaves):
        raise ValueError(
            "gradient and parameter pytrees do not match")
    if not g_leaves:
        return params, opt_state

    world = 1
    for a in axes:
        world = world * lax.psum(1, a)       # concrete at trace time
    n = sum(leaf.size for leaf in g_leaves)
    chunk = ring_chunk_size(n, world, config)
    padded_n = chunk * world

    g32 = jnp.concatenate([leaf.reshape(-1).astype(jnp.float32)
                           for leaf in g_leaves]) \
        if len(g_leaves) > 1 else g_leaves[0].reshape(-1).astype(
            jnp.float32)
    if padded_n > n:
        g32 = jnp.concatenate(
            [g32, jnp.zeros(padded_n - n, jnp.float32)])

    codec = _quantized_codec(config.compression)
    wire = _WIRE_DTYPES[config.compression] if codec is None else None
    if codec is not None:
        # Quantized gradient leg: the scatter-reduce half of
        # compress/jax_ops.quantized_allreduce — int8/uint4 rows +
        # block metadata through all_to_all, fp32 dequant+sum at the
        # owner.  One quantization of my contributions; the reduced
        # shard never requantizes (it feeds the update directly).
        from ..compress.jax_ops import dequantize_rows, quantize_rows
        bs = config.compression_block_size
        x = g32.reshape(world, chunk)
        q, s, zp = quantize_rows(x, codec, bs)
        q = lax.all_to_all(q, axes, split_axis=0, concat_axis=0,
                           tiled=True)
        s = lax.all_to_all(s, axes, split_axis=0, concat_axis=0,
                           tiled=True)
        zp = lax.all_to_all(zp, axes, split_axis=0, concat_axis=0,
                            tiled=True)
        g_shard = dequantize_rows(q, s, zp, codec, bs).sum(axis=0)
    else:
        leg = g32 if wire is None else g32.astype(wire)
        for a in axes:
            leg = lax.psum_scatter(leg, a, scatter_dimension=0,
                                   tiled=True)
        g_shard = leg.astype(jnp.float32)
    if config.op == "average":
        g_shard = g_shard / world

    # Fused unscale + clip on the shard: one scalar psum for the global
    # norm, factor folded into the shard multiply.
    if config.loss_scale is not None or \
            config.clip_global_norm is not None:
        inv = jnp.float32(1.0) if config.loss_scale is None \
            else jnp.float32(1.0 / config.loss_scale)
        if config.clip_global_norm is not None:
            gsq = jnp.vdot(g_shard, g_shard)
            for a in axes:
                gsq = lax.psum(gsq, a)
            gnorm = jnp.sqrt(gsq) * inv
            clip = jnp.float32(config.clip_global_norm)
            factor = inv * jnp.minimum(1.0, clip
                                       / jnp.maximum(gnorm, 1e-16))
        else:
            factor = inv
        g_shard = g_shard * factor

    # My shard of the flat fp32 master params.
    idx = jnp.int32(0)
    for a in axes:
        idx = idx * lax.psum(1, a) + lax.axis_index(a)
    p32 = jnp.concatenate([leaf.reshape(-1).astype(jnp.float32)
                           for leaf in p_leaves]) \
        if len(p_leaves) > 1 else p_leaves[0].reshape(-1).astype(
            jnp.float32)
    if padded_n > n:
        p32 = jnp.concatenate(
            [p32, jnp.zeros(padded_n - n, jnp.float32)])
    p_shard = lax.dynamic_slice(p32, (idx * chunk,), (chunk,))

    updates, new_opt_state = tx.update(g_shard, opt_state, p_shard)
    p_new = optax.apply_updates(p_shard, updates)

    # Updated params — not gradients — ride the closing all-gather.
    full = p_new if wire is None else p_new.astype(wire)
    for a in reversed(axes):
        full = lax.all_gather(full, a, axis=0, tiled=True)
    full = full[:n].astype(jnp.float32)

    out: list = []
    offset = 0
    for leaf in p_leaves:
        k = leaf.size
        out.append(full[offset:offset + k].reshape(leaf.shape)
                   .astype(leaf.dtype))
        offset += k
    return jax.tree_util.tree_unflatten(p_treedef, out), new_opt_state


def _hierarchical_allreduce(flat: jax.Array, axes: Sequence[str],
                            op: str) -> jax.Array:
    """reduce_scatter(local) → allreduce(cross) → all_gather(local)
    (reference: NCCLHierarchicalAllreduce's ReduceScatter → cross-node
    MPI_Allreduce → AllGather split, nccl_operations.cc:250-372, including
    its remainder handling via padding)."""
    from jax import lax

    cross, locals_ = axes[0], tuple(axes[1:])
    local_size = 1
    for a in locals_:
        local_size *= lax.psum(1, a)
    n = flat.shape[0]
    pad = (-n) % local_size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    # Sum-scatter over the combined local axes, innermost first.
    shard = flat
    for a in locals_:
        shard = lax.psum_scatter(shard, a, scatter_dimension=0, tiled=True)
    shard = lax.psum(shard, cross)
    full = shard
    for a in reversed(locals_):
        full = lax.all_gather(full, a, axis=0, tiled=True)
    if pad:
        full = full[:n]
    if op == "average":
        world = lax.psum(1, cross) * local_size
        full = full / world
    return full


def build_grad_sync(mesh, config: GradSyncConfig = GradSyncConfig()):
    """Host-level compiled sync over stacked per-rank gradients: each leaf
    has leading dim = prod(axis sizes); mainly for tests and the eager
    API."""
    from jax.sharding import PartitionSpec as P

    from ..common.jax_compat import shard_map

    spec = P(config.axes)

    def _sync(grads):
        return sync_gradients(grads, config)

    mapped = shard_map(_sync, mesh=mesh, in_specs=spec, out_specs=spec,
                       check_vma=False)
    return jax.jit(mapped)
