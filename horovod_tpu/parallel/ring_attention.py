"""Ring attention: exact attention over sequences sharded across the "sp"
mesh axis.

The reference has no sequence parallelism (SURVEY §5.7) — its only related
primitive is alltoall.  On TPU the idiomatic transport is the ICI ring:
each device holds a [B, T/n, H, D] shard of Q, K, V; K/V blocks rotate
around the ring via ``ppermute`` (neighbor exchange ≈ one ICI hop per step)
while each device accumulates its queries' attention over every block with
online-softmax merging.  Compute and transfer overlap naturally: XLA
schedules the next permute while the current block's matmuls run on the MXU.

Differentiable by construction (lax.scan + ppermute are both transparent to
autodiff); wrap the per-block attention in ``jax.checkpoint`` upstream if
the residuals of long rings blow past HBM.

Must run inside ``shard_map`` over a mesh with the given axis, e.g.::

    out = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis="sp", causal=True),
        mesh, in_specs=(P("dp", "sp"), ...), out_specs=P("dp", "sp"))(...)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _chunk_attention(q, k, v, sm_scale, mask):
    """Dense attention over one KV chunk.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]; mask: [Tq, Tk] bool or None.
    Returns unnormalized ``o`` [B, Tq, H, D] f32 (= exp(s - m) @ v), the
    softmax denominator ``l`` and the log-sum-exp, both [B, H, Tq] f32.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                               # [B,H,Tq]
    # Fully-masked rows: clamp m so p underflows to 0 instead of becoming
    # exp(NEG_INF - NEG_INF) = 1, and lse stays ~NEG_INF.
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])
    l = jnp.sum(p, axis=-1)                               # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    lse = jnp.where(l > 0.0, m_safe + jnp.log(jnp.maximum(l, 1e-30)),
                    NEG_INF)
    return o, l, lse


def _merge(o_acc, lse_acc, o_c, l_c, lse_c):
    """Online-softmax merge of the running (normalized o, lse) with one
    chunk's (unnormalized o, l, lse)."""
    l_safe = jnp.maximum(l_c, 1e-30)
    o_c = o_c / l_safe.transpose(0, 2, 1)[..., None]      # normalize chunk
    lse_new = jnp.logaddexp(lse_acc, lse_c)
    wp = jnp.exp(lse_acc - lse_new).transpose(0, 2, 1)[..., None]
    wc = jnp.exp(lse_c - lse_new).transpose(0, 2, 1)[..., None]
    return o_acc * wp + o_c * wc, lse_new


def local_attention(q, k, v, causal: bool = False,
                    sm_scale: float | None = None):
    """Single-shard dense attention (the ring degenerate case)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    mask = None
    if causal:
        t, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(tk)[None, :]
    o, l, _ = _chunk_attention(q, k, v, sm_scale, mask)
    l_safe = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / l_safe).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis: str = "sp", causal: bool = False,
                   sm_scale: float | None = None,
                   axis_size: int | None = None) -> jax.Array:
    """Exact attention with the sequence sharded over mesh axis ``axis``.

    q, k, v: local shards [B, T_local, H, D] (BTHD); returns the local
    output shard in q's dtype.  Run inside shard_map.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    n = axis_size if axis_size is not None else lax.psum(1, axis)
    if isinstance(n, jax.Array):
        raise ValueError(
            "ring_attention needs the static ring size; pass axis_size= "
            "or run under shard_map where psum(1, axis) is static")
    if n == 1:
        return local_attention(q, k, v, causal=causal, sm_scale=sm_scale)

    my_idx = lax.axis_index(axis)
    b, t_local, h, d = q.shape
    perm = [(i, (i - 1) % n) for i in range(n)]   # receive from right

    def ring_step(carry, s):
        o_acc, lse_acc, k_cur, v_cur = carry
        # The chunk held at step s originated at ring position
        # (my_idx + s) mod n.
        src = (my_idx + s) % n
        if causal:
            q_pos = my_idx * t_local + jnp.arange(t_local)[:, None]
            kv_pos = src * t_local + jnp.arange(t_local)[None, :]
            mask = q_pos >= kv_pos
        else:
            mask = None
        o_c, l_c, lse_c = _chunk_attention(q, k_cur, v_cur, sm_scale, mask)
        o_new, lse_new = _merge(o_acc, lse_acc, o_c, l_c, lse_c)
        k_next = lax.ppermute(k_cur, axis, perm)
        v_next = lax.ppermute(v_cur, axis, perm)
        return (o_new, lse_new, k_next, v_next), None

    # Build the initial carry FROM q so it carries q's device-varying axes
    # (plain constants would be "unvarying" and trip the scan vma check
    # under shard_map).
    o0 = jnp.zeros_like(q, dtype=jnp.float32)
    lse0 = jnp.sum(o0, axis=-1).transpose(0, 2, 1) + NEG_INF  # [B,H,T]
    (o, _, _, _), _ = lax.scan(ring_step, (o0, lse0, k, v), jnp.arange(n))
    return o.astype(q.dtype)
