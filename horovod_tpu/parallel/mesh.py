"""Device-mesh construction for SPMD training.

The mesh is the TPU analogue of the reference's communicator hierarchy
(reference: horovod/common/common.h:119-136 Communicator::{GLOBAL,LOCAL,
CROSS}; mpi/mpi_controller.cc:44-79 rank/local/cross discovery): instead of
building MPI communicators at runtime we declare named axes once and let
XLA compile collectives over them.

Axis order is chosen for ICI locality — the innermost axes map to
physically adjacent devices, so the bandwidth-hungriest parallelism (tensor
parallelism) always rides the shortest links:

    pp  > dp > fsdp > ep > sp > tp      (outermost ... innermost)

When the job spans multiple hosts the outermost non-trivial axis is placed
on the DCN dimension (`create_hybrid_device_mesh`), mirroring how the
reference splits hierarchical collectives into an intra-node NCCL leg and a
cross-node MPI leg (reference: ops/nccl_operations.cc:187-398).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# outermost → innermost
DEFAULT_AXES: tuple[str, ...] = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Requested parallelism degrees; ``dp=-1`` means "all remaining
    devices" (the common case: fix model axes, scale data parallel)."""
    pp: int = 1     # pipeline stages
    dp: int = -1    # pure data parallel (gradient allreduce axis)
    fsdp: int = 1   # data parallel with sharded params/optimizer state
    ep: int = 1     # expert parallel (MoE all_to_all axis)
    sp: int = 1     # sequence/context parallel (ring attention axis)
    tp: int = 1     # tensor parallel (matmul sharding axis)

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {a: getattr(self, a) for a in DEFAULT_AXES}
        fixed = math.prod(v for v in sizes.values() if v > 0)
        if sizes["dp"] == -1:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {fixed} ({sizes})")
            sizes["dp"] = n_devices // fixed
            fixed *= sizes["dp"]
        if fixed != n_devices:
            raise ValueError(
                f"mesh axes {sizes} require {fixed} devices, have "
                f"{n_devices}")
        return sizes


def build_mesh(spec: MeshSpec | None = None,
               devices: Sequence[jax.Device] | None = None,
               **axis_sizes: int) -> Mesh:
    """Build a named `jax.sharding.Mesh`.

    Usage: ``build_mesh(dp=4, tp=2)`` or ``build_mesh(MeshSpec(tp=4))``.
    Single-host: uses `mesh_utils.create_device_mesh` so axis order maps
    onto the physical ICI torus. Multi-host: hybrid mesh with the
    outermost non-trivial axis spanning DCN.
    """
    if spec is None:
        spec = MeshSpec(**axis_sizes)
    elif axis_sizes:
        spec = dataclasses.replace(spec, **axis_sizes)
    if devices is None:
        devices = jax.devices()
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in DEFAULT_AXES)

    from jax.experimental import mesh_utils
    # DCN granule = TPU slice when the runtime reports one (multi-slice
    # pods), else the owning process (CPU multi-process worlds). A single
    # multi-host slice is one ICI domain — no DCN split at all.
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if None not in slice_ids and len(slice_ids) > 1:
        n_granules, by_process = len(slice_ids), False
    else:
        n_granules = len({getattr(d, "process_index", 0) for d in devices})
        by_process = True
        if None not in slice_ids:
            n_granules = 1   # one slice: pure ICI even across processes
    if n_granules > 1:
        # Split the outermost non-trivial axis across DCN granules
        # (ICI = "local", DCN = "cross"; reference: common.h:119-136).
        if len(devices) % n_granules:
            raise ValueError(
                f"{len(devices)} devices do not divide evenly over "
                f"{n_granules} DCN granules")
        dcn_shape, ici_shape = [], []
        remaining_dcn = n_granules
        for dim in shape:
            g = math.gcd(dim, remaining_dcn)
            dcn_shape.append(g)
            ici_shape.append(dim // g)
            remaining_dcn //= g
        if remaining_dcn != 1:
            raise ValueError(
                f"cannot split {n_granules} granules over mesh shape "
                f"{shape}")
        dev_array = mesh_utils.create_hybrid_device_mesh(
            tuple(ici_shape), tuple(dcn_shape), devices=devices,
            process_is_granule=by_process)
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(shape,
                                                      devices=devices)
        except (ValueError, AssertionError):
            dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, DEFAULT_AXES)


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """The axes gradients are reduced over: every data-parallel-like axis
    that is larger than 1 (dp always; fsdp contributes after its
    reduce-scatter leg)."""
    return tuple(a for a in ("dp", "fsdp") if axis_size(mesh, a) > 1)
