"""Mesh-axis collectives: the XLA data plane.

Two usage modes:

1. **Inside a shard_mapped / jitted program** — `allreduce(x, axis="dp")`
   etc. take *axis names* and lower straight to XLA collective HLOs
   (AllReduce / AllGather / AllToAll / CollectivePermute), which ride the
   ICI fabric. This replaces the reference's NCCL op dispatch
   (reference: horovod/common/ops/nccl_operations.cc:126-184).

2. **Host-level, via `device_collective`** — wraps an axis-name collective
   in `jit(shard_map(...))` over a stacked leading dimension; used by the
   XLA backend of the enqueue API and by tests.

`adasum_allreduce` implements the scale-insensitive Adasum reduction
(reference: horovod/common/ops/adasum/adasum.h:38-552) as recursive
distance-doubling over a mesh axis with `ppermute` exchanges: at level
``l`` ranks pair up (partner = rank XOR 2^l), exchange vectors, and combine

    a' = a·(1 − a·b / 2‖a‖²) + b·(1 − a·b / 2‖b‖²)

The pairwise tree matches the reference's VHDD order, so results agree
with `ops.adasum.adasum_reference` to fp precision.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..common.jax_compat import shard_map


def _axes(axis: str | Sequence[str]) -> tuple[str, ...]:
    return (axis,) if isinstance(axis, str) else tuple(axis)


# ---------------------------------------------------------------------------
# In-program collectives (use inside shard_map / jit)
# ---------------------------------------------------------------------------
def allreduce(x: jax.Array, axis: str | Sequence[str] = "dp",
              op: str = "sum") -> jax.Array:
    """psum / pmean over mesh axes (reference: ncclAllReduce,
    nccl_operations.cc:160)."""
    ax = _axes(axis)
    if op == "sum":
        return lax.psum(x, ax)
    if op in ("average", "mean"):
        return lax.pmean(x, ax)
    if op == "max":
        return lax.pmax(x, ax)
    if op == "min":
        return lax.pmin(x, ax)
    if op == "adasum":
        return adasum_allreduce(x, ax)
    raise ValueError(f"unknown reduce op {op!r}")


def allgather(x: jax.Array, axis: str = "dp", concat_axis: int = 0,
              tiled: bool = True) -> jax.Array:
    """Gather shards from every rank along the mesh axis
    (reference: NCCLAllgather, nccl_operations.cc:434-559)."""
    return lax.all_gather(x, axis, axis=concat_axis, tiled=tiled)


def reduce_scatter(x: jax.Array, axis: str = "dp",
                   scatter_axis: int = 0) -> jax.Array:
    """Sum then scatter shards (reference: ncclReduceScatter leg of the
    hierarchical allreduce, nccl_operations.cc:250-372)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                            tiled=True)


def alltoall(x: jax.Array, axis: str = "ep", split_axis: int = 0,
             concat_axis: int = 0) -> jax.Array:
    """Exchange equal splits with every rank on the axis
    (reference: NCCLAlltoall, nccl_operations.cc:567-619)."""
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def broadcast(x: jax.Array, axis: str = "dp", root: int = 0) -> jax.Array:
    """Every rank takes root's value (reference: NCCLBroadcast,
    nccl_operations.cc:401-432). Implemented as a masked psum — one
    AllReduce HLO, which XLA lowers efficiently on ICI."""
    idx = lax.axis_index(axis)
    contrib = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis)


def ppermute(x: jax.Array, axis: str,
             perm: Sequence[tuple[int, int]]) -> jax.Array:
    """Point-to-point ring/pair exchange (ICI-neighbor transport; the
    primitive under ring attention and Adasum)."""
    return lax.ppermute(x, axis, perm)


def adasum_allreduce(x: jax.Array, axis: str | Sequence[str] = "dp",
                     eps: float = 0.0) -> jax.Array:
    """Adasum over one or more mesh axes via recursive distance-doubling.

    Power-of-2 axis sizes only (the reference's VHDD pairing has the same
    constraint; reference: adasum.h power-of-2 rank pairing). Multiple
    axes are combined sequentially, innermost first (ICI before DCN),
    mirroring the hierarchical AdasumGpuAllreduceOp
    (reference: ops/adasum_gpu_operations.cc).
    """
    axes = _axes(axis)
    for ax in reversed(axes):      # innermost (ICI) leg first
        x = _adasum_one_axis(x, ax, eps)
    return x


def _adasum_one_axis(x: jax.Array, axis: str, eps: float) -> jax.Array:
    # lax.axis_size only exists on newer jax; psum of a literal 1 is the
    # portable static axis size.
    n = lax.psum(1, axis)
    if n == 1:
        return x
    if n & (n - 1):
        raise ValueError(f"Adasum requires power-of-2 axis size, "
                         f"got {axis}={n}")
    idx = lax.axis_index(axis)
    acc_dtype = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) \
        else x.dtype
    v = x.astype(acc_dtype)
    for level in range(int(math.log2(n))):
        distance = 1 << level
        perm = [(i, i ^ distance) for i in range(n)]
        other = lax.ppermute(v, axis, perm)
        # Canonical pair identity: `a` is held by the rank whose `level`
        # bit is clear, so both partners compute identical (a, b) and the
        # combine is symmetric (reference: adasum.h rank pairing).
        bit_clear = (idx & distance) == 0
        a = jnp.where(bit_clear, v, other)
        b = jnp.where(bit_clear, other, v)
        aa = jnp.sum(a * a)
        bb = jnp.sum(b * b)
        ab = jnp.sum(a * b)
        acoef = jnp.where(aa > eps, 1.0 - ab / (2.0 * aa + 1e-30), 1.0)
        bcoef = jnp.where(bb > eps, 1.0 - ab / (2.0 * bb + 1e-30), 1.0)
        zero = (aa == 0.0) & (bb == 0.0)
        acoef = jnp.where(zero, 1.0, acoef)
        bcoef = jnp.where(zero, 1.0, bcoef)
        v = acoef.astype(acc_dtype) * a + bcoef.astype(acc_dtype) * b
    return v.astype(x.dtype)


# ---------------------------------------------------------------------------
# Host-level wrapper
# ---------------------------------------------------------------------------
def device_collective(fn, mesh: Mesh, axis: str | Sequence[str] = "dp",
                      in_spec: Any = None, out_spec: Any = None):
    """jit(shard_map(fn)) over a stacked leading dim: input shape
    (axis_size, ...) — one slice per mesh position on `axis`; all other
    mesh axes see replicated data. Returns the compiled callable.
    """
    ax = _axes(axis)
    in_spec = P(ax) if in_spec is None else in_spec
    out_spec = P(ax) if out_spec is None else out_spec

    def wrapper(*args):
        return fn(*args)

    mapped = shard_map(wrapper, mesh=mesh, in_specs=in_spec,
                       out_specs=out_spec, check_vma=False)
    return jax.jit(mapped)
