"""CLI: run one fleetsim episode from the environment.

``python -m horovod_tpu.fleetsim`` builds a :class:`FleetSim` from the
HOROVOD_FLEETSIM_* knobs (rendezvous endpoints from
HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT, chaos from HOROVOD_CHAOS), runs the
episode, prints one ``FLEETSIM_SUMMARY <json>`` line, and exits 0 when
every step succeeded — the mp_worker batteries and ad-hoc load
generation both ride this entry point.
"""
from __future__ import annotations

import json
import sys

from .harness import FleetConfig, FleetSim


def main(argv=None) -> int:
    cfg = FleetConfig.from_env()
    fleet = FleetSim(cfg)
    report = fleet.run()
    print("FLEETSIM_SUMMARY " + json.dumps(report.to_dict(),
                                           sort_keys=True))
    return 0 if report.failed_steps == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
