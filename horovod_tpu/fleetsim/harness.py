"""The fleet harness: config, shared services, episode lifecycle.

:class:`FleetSim` owns everything the virtual ranks share — the
loopback fabric, one rendezvous client + stamp batcher per simulated
host group, the coordinator-side straggler aggregator, the real
admission controller fed a scripted synthetic load, the real autoscale
policy (up-decisions admit joiner virtual ranks over the live KV join
path, down-decisions drain the highest launch id), and a control-plane
role prober that snapshots every replica's ``/.ctl/role`` through the
episode so the operator console can replay failovers and promotions.

``run()`` drives one episode: start N virtual ranks, let them step to
``HOROVOD_FLEETSIM_STEPS`` boundaries under whatever chaos
``HOROVOD_CHAOS`` specifies, then join everything and (with
``HOROVOD_FLEETSIM_DUMP_DIR`` set) write the rank-stamped evidence the
console renders post-hoc: the flight ring, the metrics snapshot, the
role-probe timeline, and a machine-readable episode summary.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from urllib import request as urlrequest

from ..common import config
from ..common.logging import logger
from ..runner.network import RendezvousClient
from ..serving.admission import AdmissionController
from ..statesync.autoscale import (AutoscaleController, AutoscalePolicy,
                                   registry_source)
from ..telemetry import flight as flight_mod
from ..telemetry import metrics as _tm_metrics
from ..telemetry.exporter import dump_json
from ..telemetry.registry import NULL_REGISTRY
from ..telemetry.straggler import StragglerAggregator
from .kvproxy import HostGroupKV, HostGroupSession
from .loopback import LoopbackFabric
from .vrank import JOIN_SCOPE, VirtualRank

__all__ = ["FleetConfig", "FleetReport", "FleetSim"]


@dataclasses.dataclass
class FleetConfig:
    """One episode's knobs (defaults from the HOROVOD_FLEETSIM_*
    registry — see docs/fleetsim.md for the table)."""

    ranks: int = 32
    steps: int = 12
    step_ms: float = 5.0
    host_group: int = 16
    heartbeat_s: float = 1.0
    fault_timeout_s: float = 20.0
    straggler_vid: int = -1
    straggler_ms: float = 40.0
    step_timeout_s: float = 60.0
    dump_dir: str = ""
    autoscale: bool = False
    epoch: str = "fleet"
    endpoints: str = ""

    @classmethod
    def from_env(cls) -> "FleetConfig":
        addr = config.RENDEZVOUS_ADDR.get()
        port = config.RENDEZVOUS_PORT.get()
        endpoints = ",".join(
            RendezvousClient.parse_endpoints(addr, port)) if addr else ""
        return cls(
            ranks=config.FLEETSIM_RANKS.get(),
            steps=config.FLEETSIM_STEPS.get(),
            step_ms=config.FLEETSIM_STEP_MS.get(),
            host_group=config.FLEETSIM_HOST_GROUP.get(),
            heartbeat_s=config.FLEETSIM_HEARTBEAT_S.get(),
            fault_timeout_s=config.FLEETSIM_FAULT_TIMEOUT_S.get(),
            straggler_vid=config.FLEETSIM_STRAGGLER_RANK.get(),
            straggler_ms=config.FLEETSIM_STRAGGLER_MS.get(),
            step_timeout_s=config.FLEETSIM_STEP_TIMEOUT_S.get(),
            dump_dir=config.FLEETSIM_DUMP_DIR.get(),
            autoscale=config.FLEETSIM_AUTOSCALE.get(),
            epoch=config.RENDEZVOUS_EPOCH.get() or "fleet",
            endpoints=endpoints)


@dataclasses.dataclass
class FleetReport:
    """What one episode did (the battery's assertion surface)."""

    ranks: int = 0
    steps: int = 0
    total_rank_steps: int = 0
    failed_steps: int = 0
    departures: dict = dataclasses.field(default_factory=dict)
    joins: int = 0
    transitions: int = 0
    final_world: list = dataclasses.field(default_factory=list)
    outcomes: dict = dataclasses.field(default_factory=dict)
    straggler_rank: int = -1
    straggler_lag_ms: float = 0.0
    autoscale_decisions: list = dataclasses.field(default_factory=list)
    kv_latency_ms: dict = dataclasses.field(default_factory=dict)
    wal: dict = dataclasses.field(default_factory=dict)
    role_probes: int = 0
    primaries_seen: list = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class _CtlRoleProber:
    """Background sampler of every replica's ``/.ctl/role``: the
    failover/promotion timeline the console renders."""

    def __init__(self, endpoints: list[str],
                 interval_s: float = 0.25) -> None:
        self.endpoints = list(endpoints)
        self.interval_s = interval_s
        self.probes: list[dict] = []
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if not self.endpoints:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="hvd-fleet-ctlwatch")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample()

    def sample(self) -> None:
        if len(self.probes) >= 20000:   # bounded evidence
            return
        t = time.monotonic() - self._t0
        for ep in self.endpoints:
            try:
                with urlrequest.urlopen(
                        f"http://{ep}/.ctl/role", timeout=1.0) as resp:
                    role = resp.read().decode(errors="replace")
            except OSError:
                role = "unreachable"
            self.probes.append({"t": round(t, 3), "endpoint": ep,
                                "role": role})

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            # Reap the sampler (hvdlife HVD701): the stop event is its
            # wakeup; one in-flight probe is bounded by its 1s timeout.
            t.join(timeout=self.interval_s + 5.0)
        self._thread = None

    def primaries(self) -> list[str]:
        """Distinct endpoints observed as primary, in first-seen order."""
        seen: list[str] = []
        for p in self.probes:
            if p["role"].startswith("primary") \
                    and p["endpoint"] not in seen:
                seen.append(p["endpoint"])
        return seen


class _SyntheticRequest:
    __slots__ = ("deadline", "max_new_tokens")

    def __init__(self, deadline: float, max_new_tokens: int = 16) -> None:
        self.deadline = deadline
        self.max_new_tokens = max_new_tokens


class _FleetDriver:
    """The autoscale controller's ``set_target_np`` surface, mapped to
    virtual membership: up admits a joiner, down drains the highest
    launch id."""

    def __init__(self, fleet: "FleetSim") -> None:
        self.fleet = fleet

    def world_size(self) -> int:
        return len(self.fleet.fabric.members())

    def set_target_np(self, target: int) -> None:
        self.fleet.apply_target(target)


class FleetSim:
    """One rank-virtualized fleet episode inside this process."""

    def __init__(self, cfg: FleetConfig, *, server=None) -> None:
        self.cfg = cfg
        # In-proc fallback: a test may hand the RendezvousServer itself
        # (no HTTP hop) — but the default is the REAL client stack.
        self._server = server
        self.aborted = threading.Event()
        self.chaos_spec = config.CHAOS.get().strip()
        self.flight = flight_mod.recorder()
        self.tm = _tm_metrics()
        self.fabric = LoopbackFabric(range(cfg.ranks), cfg.epoch)
        self._epoch_lock = threading.Lock()
        self._epoch_counter = 0
        self._epoch_next: dict[str, str] = {}
        self._sessions: dict[int, HostGroupSession] = {}
        self._next_vid = cfg.ranks
        self._pending_joiners: list[int] = []
        self._granted: set[int] = set()
        self.vranks: dict[int, VirtualRank] = {}
        self._state_lock = threading.Lock()
        self.report = FleetReport(ranks=cfg.ranks, steps=cfg.steps)
        self._prober = _CtlRoleProber(
            cfg.endpoints.split(",") if cfg.endpoints else [])
        # Coordinator-side services (driven by whichever virtual rank
        # currently leads — the leader calls in, the harness owns them).
        self._straggler: StragglerAggregator | None = None
        self._straggler_size = 0
        # Attribution latched by LAUNCH id: the aggregator names a
        # world index, and a membership transition both shifts indices
        # and rebuilds the window — translate and latch at observation
        # time so the finding survives the shrink.
        self._straggler_vid = -1
        self._straggler_lag_ms = 0.0
        self._admission = AdmissionController(
            registry=self.tm if self.tm.enabled else None)
        self._autoscale: AutoscaleController | None = None
        if cfg.autoscale:
            policy = AutoscalePolicy(
                max(2, cfg.ranks // 2), cfg.ranks + 4,
                hysteresis_rounds=2,
                down_lag_ms=max(1.0, cfg.straggler_ms / 2.0))
            self._autoscale = AutoscaleController(
                _FleetDriver(self), registry_source(self.tm),
                policy, interval=3600.0)
        # Fleet-level metrics (shared registry: every virtual rank's
        # steps land here — the load-generator contract).
        tm = self.tm
        self._m_steps = tm.counter(
            "horovod_fleetsim_steps_total",
            "Virtual-rank steps completed across the fleet")
        self._m_failed = tm.counter(
            "horovod_fleetsim_failed_steps_total",
            "Virtual-rank steps that failed (chaos fail verdicts, "
            "boundary desyncs)")
        self._m_world = tm.gauge(
            "horovod_fleetsim_world_size",
            "Live virtual ranks in the fleet")
        self._m_transitions = tm.counter(
            "horovod_fleetsim_transitions_total",
            "Membership epoch transitions folded at fleet boundaries")
        self._m_departures = {
            kind: tm.counter(
                "horovod_fleetsim_departures_total",
                "Virtual ranks that left the fleet, by cause",
                labels={"kind": kind})
            for kind in ("preempt", "kill", "desync", "error")}

    # -- shared-service plumbing (called by virtual ranks) ---------------
    def session_for(self, vid: int) -> HostGroupSession:
        group = vid // max(1, self.cfg.host_group)
        with self._state_lock:
            sess = self._sessions.get(group)
            if sess is None:
                client = self._make_client()
                sess = HostGroupSession(
                    client, self.cfg.host_group,
                    flush_age_s=min(0.25, self.cfg.heartbeat_s / 4.0),
                    snapshot_ttl_s=min(0.5, self.cfg.heartbeat_s / 2.0),
                    registry=self.tm)
                self._sessions[group] = sess
            return sess

    def _make_client(self):
        if self._server is not None:
            return _InProcClient(self._server)
        return RendezvousClient(self.cfg.endpoints, timeout=30.0)

    def kv_for(self, vid: int) -> HostGroupKV:
        return HostGroupKV(self.session_for(vid))

    def monitor_registry(self, vid: int, world: list[int]):
        """Real registry only for the fleet leader's monitor: one full
        per-peer liveness gauge family per process, not 500."""
        return self.tm if world and vid == world[0] else NULL_REGISTRY

    def next_epoch(self, from_epoch: str) -> str:
        """Deterministic epoch tag for the transition folded FROM
        ``from_epoch`` — every survivor of the same boundary computes
        the same fold, so the first caller names it and the rest look
        it up."""
        with self._epoch_lock:
            nxt = self._epoch_next.get(from_epoch)
            if nxt is None:
                self._epoch_counter += 1
                nxt = f"{self.cfg.epoch}~t{self._epoch_counter}"
                self._epoch_next[from_epoch] = nxt
            return nxt

    def scan_joiners(self, world: list[int]) -> tuple:
        """Leader-side: pending ``fleetjoin/join:*`` announcements not
        yet granted (one scope dump per boundary)."""
        try:
            pending = self.kv_for(world[0]).get_scope(JOIN_SCOPE)
        except Exception:  # noqa: BLE001 - failover window: retry next
            return ()
        admits = []
        with self._state_lock:
            for key in pending:
                if not key.startswith("join:"):
                    continue
                vid = int(key.split(":", 1)[1])
                if vid not in world and vid not in self._granted:
                    self._granted.add(vid)
                    admits.append(vid)
        return tuple(sorted(admits))

    # -- counters / notes -------------------------------------------------
    def note_step(self) -> None:
        self._m_steps.inc()

    def note_departure(self, vid: int, kind: str) -> None:
        self._m_departures.get(kind, self._m_departures["error"]).inc()
        with self._state_lock:
            self.report.departures[kind] = \
                self.report.departures.get(kind, 0) + 1

    def note_transition(self, old_epoch: str, new_epoch: str,
                        old_world, new_world, *, departing, vanished,
                        admits, gstep: int) -> None:
        self._m_transitions.inc()
        self._m_world.set(len(new_world))
        with self._state_lock:
            self.report.transitions += 1
            self.report.joins += len(admits)
        if self.flight.enabled:
            kind = "grow" if admits else "shrink"
            self.flight.record(
                kind, new_epoch,
                detail=f"gstep={gstep} {len(old_world)}->"
                       f"{len(new_world)} departing="
                       f"{sorted(departing)} vanished="
                       f"{sorted(vanished)} admits={list(admits)}")
        logger.warning(
            "fleetsim: boundary transition %s -> %s (%d -> %d ranks, "
            "departing=%s vanished=%s admits=%s)", old_epoch, new_epoch,
            len(old_world), len(new_world), sorted(departing),
            sorted(vanished), list(admits))

    # -- leader duties (once per boundary, by the folding leader) --------
    def leader_duties(self, world, views, arrivals, gstep: int) -> None:
        # 1. straggler attribution from REAL boundary arrival skew
        size = len(world)
        if self._straggler is None or self._straggler_size != size:
            self._straggler = StragglerAggregator(
                size, self.tm, window=4)
            self._straggler_size = size
        index = {vid: i for i, vid in enumerate(world)}
        self._straggler.observe_tensor(
            {index[vid]: t for vid, t in arrivals.items()
             if vid in index})
        flagged = self._straggler.last_straggler
        if 0 <= flagged < len(world):
            self._straggler_vid = world[flagged]
            self._straggler_lag_ms = self._straggler.last_skew_ms
        # 2. synthetic serving load through the REAL admission path
        queue_depth, slack_s = self._load_pattern(gstep)
        now = time.monotonic()
        for _ in range(4):
            req = _SyntheticRequest(deadline=now + slack_s)
            ok, _outcome = self._admission.admit(
                req, queue_depth, now=now)
            if ok:
                self._admission.count("served")
                self._admission.observe_step_ms(self.cfg.step_ms)
        # 3. autoscale tick against the live gauges
        if self._autoscale is not None:
            try:
                self._autoscale.tick()
            except Exception:  # noqa: BLE001 - policy must not kill fold
                logger.debug("fleetsim: autoscale tick failed",
                             exc_info=True)

    def _load_pattern(self, gstep: int) -> tuple[float, float]:
        """Scripted offered load: an overloaded first third (deep queue
        → sheds → scale-up pressure), then a calm tail where only the
        straggler signal remains (scale-down pressure) — the
        oscillation shape of ROADMAP item 5."""
        third = max(1, self.cfg.steps // 3)
        if gstep < third:
            return (self._admission.queue_depth_limit * 0.95, 0.001)
        return (0.0, 30.0)

    # -- autoscale application -------------------------------------------
    def apply_target(self, target: int) -> None:
        live = sorted(self.fabric.members())
        if target > len(live):
            for _ in range(target - len(live)):
                self.spawn_joiner()
        elif target < len(live) and len(live) > 1:
            for vid in live[len(live) - target:][::-1]:
                vr = self.vranks.get(vid)
                if vr is not None and not vr.pending_depart:
                    vr.pending_depart = True
                    logger.warning("fleetsim: autoscale draining v%d",
                                   vid)

    def spawn_joiner(self) -> int:
        with self._state_lock:
            vid = self._next_vid
            self._next_vid += 1
        vr = VirtualRank(self, vid, joiner=True)
        self.vranks[vid] = vr
        vr.start()
        return vid

    # -- episode lifecycle ------------------------------------------------
    def run(self, timeout_s: float | None = None) -> FleetReport:
        cfg = self.cfg
        if timeout_s is None:
            timeout_s = cfg.steps * (cfg.step_ms / 1e3 + 0.5) \
                + cfg.step_timeout_s + 30.0
        if self.flight.enabled:
            self.flight.set_metadata(fleetsim_ranks=cfg.ranks,
                                     fleetsim_steps=cfg.steps)
            self.flight.record("fleet-start", cfg.epoch,
                               detail=f"ranks={cfg.ranks} "
                                      f"steps={cfg.steps}")
        self._m_world.set(cfg.ranks)
        self._prober.start()
        for vid in range(cfg.ranks):
            self.vranks[vid] = VirtualRank(self, vid)
        for vr in self.vranks.values():
            vr.start()
        deadline = time.monotonic() + timeout_s
        for vr in list(self.vranks.values()):
            if not vr.join_thread(max(0.1, deadline - time.monotonic())):
                logger.warning("fleetsim: v%d still running at episode "
                               "deadline; aborting fleet", vr.vid)
                self.abort()
                break
        # Joiners spawned mid-run (autoscale) may still be draining.
        for vr in list(self.vranks.values()):
            if not vr.join_thread(max(0.1, deadline - time.monotonic())):
                self.abort()
                vr.join_thread(5.0)
        self.close()
        return self.report

    def abort(self) -> None:
        self.aborted.set()
        self.fabric.abort()

    def close(self) -> None:
        self.aborted.set()
        # Wake any vrank still blocked in the boundary exchange (the
        # abort flag is its only exit) and reap the threads — close()
        # must release every vrank even when run() never joined them
        # (exception paths, driver-initiated teardown).
        self.fabric.abort()
        for vr in list(self.vranks.values()):
            vr.close(5.0)
            if not vr.join_thread(0.0):
                logger.warning("fleetsim: v%d leaked past teardown",
                               vr.vid)
        self._prober.close()
        if self._autoscale is not None:
            self._autoscale.stop()
        for sess in self._sessions.values():
            try:
                sess.flush()
            except Exception:  # noqa: BLE001 - KV gone at teardown
                pass
        self._finalize_report()
        if self.cfg.dump_dir:
            self.dump_evidence(self.cfg.dump_dir)

    def _finalize_report(self) -> None:
        rep = self.report
        rep.total_rank_steps = sum(v.steps_done
                                   for v in self.vranks.values())
        rep.failed_steps = sum(v.failed_steps
                               for v in self.vranks.values())
        if rep.failed_steps:
            self._m_failed.inc(rep.failed_steps)
        rep.final_world = sorted(self.fabric.members())
        outcomes: dict[str, int] = {}
        for v in self.vranks.values():
            outcomes[v.outcome] = outcomes.get(v.outcome, 0) + 1
        rep.outcomes = outcomes
        rep.straggler_rank = self._straggler_vid
        rep.straggler_lag_ms = round(self._straggler_lag_ms, 3)
        if self._autoscale is not None:
            rep.autoscale_decisions = [
                {"direction": d.direction, "target": d.target}
                for d in self._autoscale.decisions]
        if self.tm.enabled:
            for entry in self.tm.snapshot()["metrics"]:
                name = entry.get("name", "")
                if name == "horovod_rendezvous_kv_latency_ms":
                    verb = entry.get("labels", {}).get("verb", "?")
                    rep.kv_latency_ms[verb] = {
                        "count": entry.get("count", 0),
                        "p50": round(entry.get("p50", 0.0), 3),
                        "p99": round(entry.get("p99", 0.0), 3)}
                elif name.startswith("horovod_rendezvous_wal_"):
                    rep.wal[name] = entry.get("value", 0)
        rep.role_probes = len(self._prober.probes)
        rep.primaries_seen = self._prober.primaries()

    def dump_evidence(self, dump_dir: str) -> None:
        """Write the episode's rank-stamped evidence for the console."""
        os.makedirs(dump_dir, exist_ok=True)
        rank = int(os.environ.get("HOROVOD_RANK", "0") or "0")
        if self.flight.enabled:
            self.flight.dump(reason="fleetsim episode end")
        if self.tm.enabled:
            dump_json(self.tm,
                      os.path.join(dump_dir, "metrics.r{rank}.json"),
                      rank)
        if self._prober.probes or self._prober.endpoints:
            path = os.path.join(dump_dir, f"ctl_roles.r{rank}.json")
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"probes": self._prober.probes,
                           "endpoints": self._prober.endpoints}, f,
                          indent=1)
            os.replace(tmp, path)
        path = os.path.join(dump_dir, f"summary.r{rank}.json")
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"fleetsim_summary": self.report.to_dict()}, f,
                      indent=1)
        os.replace(tmp, path)


class _InProcClient:
    """RendezvousClient verb surface over an in-process
    RendezvousServer (unit tests without an HTTP hop)."""

    def __init__(self, server) -> None:
        self._server = server

    def put(self, scope, key, value):
        self._server.put(scope, key, value)

    def put_many(self, records):
        self._server.put_many(records)

    def get(self, scope, key):
        return self._server.get(scope, key)

    def get_scope(self, scope):
        return self._server.get_scope(scope)

    def delete(self, scope, key=""):
        from ..runner.network import _kv_apply
        _kv_apply(self._server._httpd, "delete", scope, key, b"")

    def wait(self, scope, key, timeout=None):
        deadline = time.monotonic() + (timeout or 30.0)
        while True:
            value = self._server.get(scope, key)
            if value is not None:
                return value
            if time.monotonic() > deadline:
                raise TimeoutError(f"{scope}/{key} not available")
            time.sleep(0.02)

    def claim(self, scope, key, task_key=""):
        raise NotImplementedError
