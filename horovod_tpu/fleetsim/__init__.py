"""fleetsim — rank-virtualized O(500) scale harness (ROADMAP item 5).

Runs hundreds of *protocol-only* ranks inside one process: each virtual
rank executes the REAL control-plane client (through a host-group
batching session), the REAL heartbeat monitor, the membership boundary
fold, and deterministic chaos matching — with model compute stubbed to
a configurable delay and the tensor data plane replaced by an
in-process loopback allgather.  Coordinator WAL throughput, failover
storms, liveness fan-out, autoscale oscillation, and straggler
attribution are thereby exercised at fleet scale in CI seconds.

Entry points: ``python -m horovod_tpu.fleetsim`` runs one episode from
the HOROVOD_FLEETSIM_* environment; tests drive :class:`FleetSim`
directly.  The episode's rank-stamped evidence (flight ring, metrics
snapshot, ``/.ctl`` role probes, summary) replays in the operator
console (``python -m horovod_tpu.console``).  See docs/fleetsim.md.
"""
from .harness import FleetConfig, FleetReport, FleetSim
from .kvproxy import HostGroupKV, HostGroupSession
from .loopback import FleetDesyncError, LoopbackFabric
from .vrank import VirtualChaosEngine, VirtualRank

__all__ = ["FleetConfig", "FleetDesyncError", "FleetReport", "FleetSim",
           "HostGroupKV", "HostGroupSession", "LoopbackFabric",
           "VirtualChaosEngine", "VirtualRank"]
