"""Loopback data-plane shim for the fleetsim harness.

The virtual fleet replaces the tensor data plane with ONE in-process
barrier-allgather per step: every live virtual rank deposits its
membership-boundary flags for ``(epoch, seq)`` and blocks until every
other live member has too (the fleet-scale analogue of the statesync
boundary allgather, statesync/service.py).  Per-rank arrival times are
captured on deposit, so the coordinator-side straggler aggregator sees
exactly the skew signal a real negotiation would produce.

Membership is epoch-versioned: a transition (grow/shrink) swaps the
member set and the epoch tag under the same condition variable, and a
virtual rank that died without announcing (chaos ``kill``) is removed
with :meth:`LoopbackFabric.remove` so in-flight exchanges complete
without its slot instead of hanging — the survivors observe the missing
slot and fold it as a hard failure, just as socket death converts to a
structured error on the real transport.
"""
from __future__ import annotations

import threading
import time

__all__ = ["FleetDesyncError", "LoopbackFabric"]

# Completed rounds kept per epoch for late readers; older rounds are
# pruned on entry so a long episode never accumulates per-seq dicts.
_ROUND_KEEP = 8


class FleetDesyncError(RuntimeError):
    """A boundary exchange did not complete inside the step timeout."""


class LoopbackFabric:
    """Epoch-versioned barrier-allgather over one condition variable."""

    def __init__(self, members, epoch: str) -> None:
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._members = set(members)
        self._epoch = epoch
        self._aborted = False
        # (epoch, seq) -> {"slots": {vid: payload}, "arrivals": {vid: t}}
        self._rounds: dict[tuple[str, int], dict] = {}

    def abort(self) -> None:
        """Wake every waiter with a desync error (harness teardown)."""
        with self._lock:
            self._aborted = True
            self._cond.notify_all()

    # -- introspection ---------------------------------------------------
    @property
    def epoch(self) -> str:
        return self._epoch

    def members(self) -> frozenset[int]:
        with self._lock:
            return frozenset(self._members)

    # -- membership ------------------------------------------------------
    def transition(self, new_epoch: str, new_members) -> None:
        """Swap the live member set at a boundary.  Idempotent: every
        survivor folds the same flags and calls this with the same
        arguments; the first caller applies it, the rest verify."""
        with self._lock:
            if self._epoch == new_epoch:
                if set(new_members) != self._members:
                    raise FleetDesyncError(
                        f"divergent transition to {new_epoch!r}: "
                        f"{sorted(new_members)} vs "
                        f"{sorted(self._members)}")
                return
            self._epoch = new_epoch
            self._members = set(new_members)
            self._rounds = {k: v for k, v in self._rounds.items()
                            if k[0] == new_epoch}
            self._cond.notify_all()

    def remove(self, vid: int) -> None:
        """Drop a member that died without a boundary announcement (the
        chaos ``kill`` shape): waiters re-evaluate and complete without
        its slot."""
        with self._lock:
            self._members.discard(vid)
            self._cond.notify_all()

    def await_epoch(self, epoch: str, timeout: float) -> None:
        """Block until the fleet has transitioned to ``epoch`` — the
        joiner's entry gate (incumbents apply the transition at their
        admission boundary)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._epoch != epoch:
                if self._aborted:
                    raise FleetDesyncError("fleet aborted")
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    raise FleetDesyncError(
                        f"fleet never reached epoch {epoch!r} within "
                        f"{timeout:g}s (at {self._epoch!r})")

    # -- the exchange ----------------------------------------------------
    def exchange(self, epoch: str, seq: int, vid: int, payload,
                 timeout: float) -> tuple[dict, dict]:
        """Deposit this rank's boundary flags and block until every
        live member of ``epoch`` has deposited theirs.  Returns
        ``(views, arrivals)``: vid -> payload and vid -> monotonic
        deposit time.  A member that vanished mid-round simply has no
        slot in ``views`` — the callers fold that as a hard failure."""
        deadline = time.monotonic() + timeout
        key = (epoch, seq)
        with self._lock:
            if epoch != self._epoch:
                raise FleetDesyncError(
                    f"v{vid} exchanging on stale epoch {epoch!r} "
                    f"(fleet at {self._epoch!r})")
            for old in [k for k in self._rounds
                        if k[0] == epoch and k[1] < seq - _ROUND_KEEP]:
                del self._rounds[old]
            rd = self._rounds.setdefault(
                key, {"slots": {}, "arrivals": {}})
            rd["slots"][vid] = payload
            rd["arrivals"][vid] = time.monotonic()
            self._cond.notify_all()
            while True:
                if self._aborted:
                    raise FleetDesyncError("fleet aborted")
                if rd.get("done"):
                    # Completed while we slept — possibly already folded
                    # and transitioned by a faster member; the frozen
                    # round is still the right view for this seq.
                    return dict(rd["slots"]), dict(rd["arrivals"])
                if epoch != self._epoch:
                    # The fleet transitioned under us before this round
                    # ever completed (we deposited into a stale seq).
                    raise FleetDesyncError(
                        f"v{vid} overtaken by transition to "
                        f"{self._epoch!r} during seq {seq}")
                waiting_on = self._members - set(rd["slots"])
                if not waiting_on:
                    rd["done"] = True
                    self._cond.notify_all()
                    return dict(rd["slots"]), dict(rd["arrivals"])
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    raise FleetDesyncError(
                        f"v{vid} boundary {epoch!r}/{seq} incomplete "
                        f"after {timeout:g}s: waiting on "
                        f"{sorted(waiting_on)[:8]} "
                        f"({len(waiting_on)} total)")
