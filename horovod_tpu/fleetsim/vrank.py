"""One virtual rank: the real protocol stack over a stubbed step.

A :class:`VirtualRank` is a thread that executes, per step, exactly
what a real worker's control path executes — a live
:class:`~..resilience.heartbeat.HeartbeatMonitor` stamping and polling
the rendezvous liveness table through its host group's shared client,
deterministic chaos matching against the launch id, and the
membership boundary exchange (the statesync flag fold) — with model
compute replaced by ``HOROVOD_FLEETSIM_STEP_MS`` of sleep and the
tensor data plane by the loopback fabric.

Chaos composes unchanged: each virtual rank owns a
:class:`VirtualChaosEngine` whose ``rank`` is the LAUNCH id, so the
existing grammar (``kill:rank=37,op=5``, ``preempt:rank=12,op=9``)
addresses individual virtual ranks.  ``kill``/``preempt`` are
virtualized — they end or drain ONE virtual rank instead of the host
process carrying hundreds — while ``coordkill``/``coordpause`` keep
their real semantics (a signal at the external coordinator process)
and ``freeze``/``fail`` act inline as always.
"""
from __future__ import annotations

import threading
import time

from ..common.logging import logger
from ..resilience.chaos import ChaosAction, ChaosEngine
from ..resilience.heartbeat import HeartbeatMonitor
from .loopback import FleetDesyncError

__all__ = ["VirtualChaosEngine", "VirtualRank"]

# KV scope carrying join announcements and admission grants.
JOIN_SCOPE = "fleetjoin"


class VirtualChaosEngine(ChaosEngine):
    """Chaos engine whose self-directed verdicts are virtual: ``kill``
    and ``preempt`` latch a verdict for the owning virtual rank instead
    of signalling the host process.  Everything else (coord*, freeze,
    fail) inherits the real behavior."""

    def __init__(self, spec: str, rank: int) -> None:
        super().__init__(spec, rank)
        self._pending: str | None = None

    def _fire_kill(self, act: ChaosAction, idx: int) -> None:
        logger.warning("fleetsim: chaos kill of v%d at step %d "
                       "(virtualized)", self.rank, idx)
        self._pending = "kill"

    def _fire_preempt(self, act: ChaosAction, idx: int) -> None:
        logger.warning("fleetsim: chaos preempt of v%d at step %d "
                       "(virtualized SIGTERM)", self.rank, idx)
        if self._pending != "kill":
            self._pending = "preempt"

    def take_pending(self) -> str | None:
        verdict, self._pending = self._pending, None
        return verdict


class VirtualRank:
    """Protocol-only worker: real control plane, stubbed compute."""

    def __init__(self, fleet, vid: int, *, joiner: bool = False) -> None:
        self.fleet = fleet
        self.cfg = fleet.cfg
        self.vid = vid
        self.joiner = joiner
        self.session = fleet.session_for(vid)
        self.kv = fleet.kv_for(vid)
        self.engine: VirtualChaosEngine | None = \
            VirtualChaosEngine(fleet.chaos_spec, vid) \
            if fleet.chaos_spec else None
        # Set by the boundary fold (or by the autoscale driver asking
        # this rank to drain): announce departure at the next boundary.
        self.pending_depart = False
        # Episode state (single-writer: this thread).
        self.epoch = fleet.fabric.epoch
        self.world: list[int] = sorted(fleet.fabric.members())
        self.seq = 0
        self.gstep = 0
        self.steps_done = 0
        self.failed_steps = 0
        self.outcome = "running"
        self.monitor: HeartbeatMonitor | None = None
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"hvd-fleet-vrank-{self.vid}")
        self._thread.start()

    def join_thread(self, timeout: float) -> bool:
        t = self._thread
        if t is not None:
            t.join(timeout)
            return not t.is_alive()
        return True

    def close(self, timeout: float = 5.0) -> None:
        """Teardown: wake the loop (fleet abort is the only exit for a
        rank blocked in the boundary exchange) and reap the thread."""
        self.fleet.aborted.set()
        self.fleet.fabric.abort()
        self.join_thread(timeout)

    # -- helpers ---------------------------------------------------------
    @property
    def world_rank(self) -> int:
        return self.world.index(self.vid)

    def _start_monitor(self) -> None:
        self.monitor = HeartbeatMonitor(
            self.world_rank, len(self.world), self.kv,
            epoch=self.epoch,
            fault_timeout=self.cfg.fault_timeout_s,
            interval=self.cfg.heartbeat_s,
            registry=self.fleet.monitor_registry(self.vid, self.world))
        self.monitor.start()

    def _stop_monitor(self, silent: bool = False) -> None:
        if self.monitor is not None:
            self.monitor.stop(silent=silent)
            self.monitor = None

    def _flight(self, kind: str, detail: str = "") -> None:
        rec = self.fleet.flight
        if rec.enabled:
            rec.record(kind, f"v{self.vid}", detail=detail)

    # -- thread body -----------------------------------------------------
    def _run(self) -> None:
        try:
            if self.joiner:
                if not self._join_fleet():
                    return
            else:
                self._start_monitor()
            self._loop()
        except FleetDesyncError as exc:
            if self.fleet.aborted.is_set():
                self.outcome = "aborted"
                self._stop_monitor()
                return
            self.failed_steps += 1
            self.outcome = "desync"
            self._flight("fleet-desync", detail=str(exc))
            logger.warning("fleetsim: v%d desynced: %s", self.vid, exc)
            self.fleet.fabric.remove(self.vid)
            self._stop_monitor()
            self.fleet.note_departure(self.vid, "desync")
        except Exception:  # noqa: BLE001 - one vrank never kills the host
            self.failed_steps += 1
            self.outcome = "error"
            logger.warning("fleetsim: v%d crashed", self.vid,
                           exc_info=True)
            self.fleet.fabric.remove(self.vid)
            self._stop_monitor()
            self.fleet.note_departure(self.vid, "error")

    def _join_fleet(self) -> bool:
        """Announce over the REAL KV path and wait for the leader's
        admission grant (``fleetjoin/go:<vid>``), then enter the fleet
        at the granted epoch."""
        self.kv.put(JOIN_SCOPE, f"join:{self.vid}", b"waiting")
        self._flight("join-announce")
        deadline = time.monotonic() + self.cfg.step_timeout_s * 2
        grant = None
        while grant is None:
            if self.fleet.aborted.is_set() \
                    or time.monotonic() > deadline:
                self.outcome = "join-abandoned"
                return False
            try:
                grant = self.kv.wait(JOIN_SCOPE, f"go:{self.vid}",
                                     timeout=1.0)
            except TimeoutError:
                continue
        epoch, gstep, world = grant.decode().split("|")
        self.fleet.fabric.await_epoch(epoch, self.cfg.step_timeout_s)
        self.epoch = epoch
        self.gstep = int(gstep)
        self.world = [int(v) for v in world.split(",")]
        self.seq = 0
        self._start_monitor()
        self._flight("join-entered", detail=f"epoch={epoch}")
        return True

    def _loop(self) -> None:
        cfg = self.cfg
        while not self.fleet.aborted.is_set():
            # 1. chaos (the per-step response hook, names carry the
            #    global step so name= matchers compose too)
            if self.engine is not None:
                verdict = self.engine.on_response(
                    (f"fleet.step.{self.gstep}",))
                pending = self.engine.take_pending()
                if pending == "kill":
                    # Silent death: no bye stamp, no boundary flag —
                    # peers see a missing slot now and heartbeat
                    # silence later.
                    self.outcome = "killed"
                    self._flight("fleet-vkill")
                    self.fleet.fabric.remove(self.vid)
                    self._stop_monitor(silent=True)
                    self.fleet.note_departure(self.vid, "kill")
                    return
                if pending == "preempt":
                    self.pending_depart = True
                    self._flight("preempt-notice")
                if verdict == "fail":
                    self.failed_steps += 1
                    self._flight("fleet-step-fail",
                                 detail=f"gstep={self.gstep}")
            # 2. stubbed compute
            delay_ms = cfg.step_ms
            if self.vid == cfg.straggler_vid:
                delay_ms += cfg.straggler_ms
            if delay_ms > 0:
                time.sleep(delay_ms / 1e3)
            # 3. boundary exchange (the loopback data plane)
            leader = self.vid == self.world[0]
            flags = {
                "vid": self.vid,
                "depart": self.vid if self.pending_depart else -1,
                "gstep": self.gstep,
                "admit": self.fleet.scan_joiners(self.world)
                if leader else (),
            }
            views, arrivals = self.fleet.fabric.exchange(
                self.epoch, self.seq, self.vid, flags,
                cfg.step_timeout_s)
            self.steps_done += 1
            self.fleet.note_step()
            if not self._fold(views, arrivals):
                return
        self.outcome = self.outcome if self.outcome != "running" \
            else "aborted"

    def _fold(self, views: dict, arrivals: dict) -> bool:
        """Fold one boundary's flags exactly once per rank; returns
        False when this rank leaves the loop (departure or fleet
        end)."""
        cfg = self.cfg
        present = set(views)
        vanished = set(self.world) - present
        departing = {f["depart"] for f in views.values()
                     if f["depart"] >= 0}
        survivors = [v for v in self.world
                     if v in present and v not in departing]
        gstep = max(f["gstep"] for f in views.values())
        leader_flags = views.get(min(present), {})
        admits = tuple(leader_flags.get("admit", ())) \
            if not (vanished or departing) else ()
        if self.vid == min(survivors or sorted(present)):
            self.fleet.leader_duties(self.world, views, arrivals,
                                     gstep)
        self.gstep = gstep + 1
        # Fleet end: everyone folds the same gstep, everyone leaves.
        if self.gstep >= cfg.steps:
            self.outcome = "finished"
            self._flight("fleet-end", detail=f"gstep={self.gstep}")
            self._stop_monitor()
            return False
        if self.vid in departing:
            self.outcome = "preempted"
            self._flight("departed",
                         detail=f"gstep={self.gstep} orderly")
            self._stop_monitor()
            self.fleet.note_departure(self.vid, "preempt")
            return False
        if vanished or departing or admits:
            new_world = survivors + [v for v in admits
                                     if v not in survivors]
            new_world.sort()
            new_epoch = self.fleet.next_epoch(self.epoch)
            self.fleet.fabric.transition(new_epoch, new_world)
            if self.vid == new_world[0]:
                self.fleet.note_transition(
                    self.epoch, new_epoch, self.world, new_world,
                    departing=departing, vanished=vanished,
                    admits=admits, gstep=self.gstep)
                for a in admits:
                    grant = f"{new_epoch}|{self.gstep}|" \
                            f"{','.join(map(str, new_world))}"
                    self.kv.put(JOIN_SCOPE, f"go:{a}", grant.encode())
                    self.kv.delete(JOIN_SCOPE, f"join:{a}")
            # Epoch rebuild: the old epoch's monitor says goodbye, the
            # new epoch's monitor starts from a clean liveness table.
            self._stop_monitor()
            self.epoch = new_epoch
            self.world = new_world
            self.seq = 0
            self._start_monitor()
            return True
        self.seq += 1
        return True
