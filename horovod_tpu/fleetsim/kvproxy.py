"""Host-group KV adapter: the fleetsim fan-in batching layer.

500 virtual ranks stamping ``hb/<epoch>:<rank>`` individually would
serialize 500 HTTP puts per heartbeat window through one coordinator —
exactly the fan-in a real 500-worker pod amortizes at the HOST level
(one physical host carries N workers and one control-plane session).
:class:`HostGroupKV` reproduces that topology: every simulated host
group shares one :class:`~..runner.network.RendezvousClient`, and

- **writes**: periodic heartbeat stamps are buffered per group and
  flushed as ONE ``PUT /.batch/`` (``RendezvousClient.put_many``) when
  the group's live members have all stamped or the oldest buffered
  stamp exceeds the flush age — the server applies the batch under a
  single lock hold, so the WAL group-commits it in one fsync lane pass
  (asserted by the coalesce counters in
  ``horovod_rendezvous_wal_*_total``).  Urgent liveness signals —
  ``bye|`` departure stamps and ``dead/`` marks — bypass the buffer:
  coalescing must never delay failure evidence.
- **reads**: the ``hb``/``dead`` liveness tables are served from one
  TTL-cached scope dump per group (``RendezvousClient.get_scope``)
  instead of ``size``-many gets per monitor poll.  A failed refresh
  poisons the snapshot so every reader in the group observes the KV
  outage (heartbeat monitors pause their staleness clocks), matching
  what per-rank clients would all see.

Everything else (membership scopes, waits, deletes) passes straight
through to the shared client.
"""
from __future__ import annotations

import threading
import time

__all__ = ["HostGroupKV", "HostGroupSession"]

# Liveness scopes served from the cached snapshot / batched on write.
_SNAPSHOT_SCOPES = ("hb", "dead")


class HostGroupSession:
    """Shared per-host-group state: one rendezvous client, one stamp
    buffer, one snapshot cache."""

    def __init__(self, client, group_size: int,
                 flush_age_s: float = 0.25,
                 snapshot_ttl_s: float = 0.5,
                 registry=None) -> None:
        self.client = client
        self.group_size = max(1, int(group_size))
        self.flush_age_s = float(flush_age_s)
        self.snapshot_ttl_s = float(snapshot_ttl_s)
        self._lock = threading.Lock()
        # (scope, key) -> value: a later stamp overwrites the buffered
        # one, so the buffer is bounded by the group's key universe.
        self._buffer: dict[tuple[str, str], bytes] = {}
        self._buffer_since: float | None = None
        # scope -> (fetched_monotonic, dict | None, error | None)
        self._snap: dict[str, tuple[float, dict | None, Exception | None]] \
            = {}
        self._refreshing: set[str] = set()
        if registry is None:
            from ..telemetry import metrics
            registry = metrics()
        self._m_stamps = registry.counter(
            "horovod_fleetsim_hb_stamps_total",
            "Heartbeat stamps produced by this process's virtual ranks")
        self._m_flushes = registry.counter(
            "horovod_fleetsim_hb_flushes_total",
            "Batched put_many flushes carrying those stamps (the "
            "host-group fan-in coalescing ratio)")

    # -- write path ------------------------------------------------------
    def put(self, scope: str, key: str, value: bytes) -> None:
        # Only periodic hb stamps coalesce.  Urgent liveness signals —
        # bye| departure stamps, dead/ marks — and every membership
        # record go straight through.
        if scope == "hb" and not bytes(value).startswith(b"bye|"):
            self._buffer_put(scope, key, value)
            return
        self.client.put(scope, key, value)

    def _buffer_put(self, scope: str, key: str, value: bytes) -> None:
        now = time.monotonic()
        flush: list | None = None
        with self._lock:
            self._buffer[(scope, key)] = bytes(value)
            self._m_stamps.inc()
            if self._buffer_since is None:
                self._buffer_since = now
            full = len(self._buffer) >= self.group_size
            aged = now - self._buffer_since >= self.flush_age_s
            if full or aged:
                flush = [(s, k, v)
                         for (s, k), v in self._buffer.items()]
                self._buffer.clear()
                self._buffer_since = None
        if flush:
            # HTTP outside the lock: a slow coordinator must not stall
            # the other monitors' stamping.
            self.client.put_many(flush)
            self._m_flushes.inc()

    def flush(self) -> None:
        """Drain whatever is buffered now (teardown, tests)."""
        with self._lock:
            flush = [(s, k, v) for (s, k), v in self._buffer.items()]
            self._buffer.clear()
            self._buffer_since = None
        if flush:
            self.client.put_many(flush)
            self._m_flushes.inc()

    # -- read path -------------------------------------------------------
    def snapshot_get(self, scope: str, key: str) -> bytes | None:
        now = time.monotonic()
        refresh = False
        with self._lock:
            entry = self._snap.get(scope)
            stale = entry is None \
                or now - entry[0] >= self.snapshot_ttl_s
            if stale and scope not in self._refreshing:
                self._refreshing.add(scope)
                refresh = True
        if refresh:
            # One refresher per scope; HTTP outside the lock.  A failed
            # refresh poisons the snapshot so EVERY reader in the group
            # observes the outage (monitors pause staleness clocks).
            try:
                snap, snap_err = self.client.get_scope(scope), None
            except Exception as exc:  # noqa: BLE001 - poisoned below
                snap, snap_err = None, exc
            with self._lock:
                self._snap[scope] = (time.monotonic(), snap, snap_err)
                self._refreshing.discard(scope)
        with self._lock:
            entry = self._snap.get(scope)
        if entry is None:
            # Another thread's FIRST refresh is still in flight: a
            # direct get beats fabricating an empty liveness view.
            return self.client.get(scope, key)
        _fetched, data, err = entry
        if err is not None:
            raise ConnectionError(
                f"host-group snapshot of {scope!r} failed") from err
        return (data or {}).get(key)


class HostGroupKV:
    """The per-virtual-rank KV facade handed to the real
    :class:`~..resilience.heartbeat.HeartbeatMonitor` (duck-typed to
    RendezvousClient's verb surface)."""

    def __init__(self, session: HostGroupSession) -> None:
        self._s = session

    def put(self, scope: str, key: str, value: bytes) -> None:
        self._s.put(scope, key, value)

    def get(self, scope: str, key: str) -> bytes | None:
        if scope in _SNAPSHOT_SCOPES:
            return self._s.snapshot_get(scope, key)
        return self._s.client.get(scope, key)

    def get_scope(self, scope: str) -> dict[str, bytes]:
        return self._s.client.get_scope(scope)

    def wait(self, scope: str, key: str, timeout: float | None = None):
        return self._s.client.wait(scope, key, timeout)

    def delete(self, scope: str, key: str = "") -> None:
        self._s.client.delete(scope, key)

    def claim(self, scope: str, key: str, task_key: str = "") -> int:
        return self._s.client.claim(scope, key, task_key)
