"""Cross-rank synchronized batch normalization for torch.

Reference: horovod/torch/sync_batch_norm.py:40-218 — batch statistics are
combined across all ranks in forward (allgather of per-rank mean/var/count)
and the reduction terms of the gradient are allreduced in backward, so the
layer behaves as if the global batch lived on one device. Weight/bias
gradients are left local: the DistributedOptimizer allreduces them like
every other parameter gradient.
"""
from __future__ import annotations

import numpy as np
import torch
import torch.nn.functional as F
from torch.nn.modules.batchnorm import _BatchNorm

from .mpi_ops import allgather, allreduce, size, Sum


class SyncBatchNorm(_BatchNorm):
    """Drop-in `nn.BatchNorm*d` replacement with cross-rank statistics."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True):
        super().__init__(num_features, eps, momentum, affine,
                         track_running_stats)

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D)")

    def forward(self, input):
        self._check_input_dim(input)
        if not self.training and self.track_running_stats:
            return F.batch_norm(input, self.running_mean, self.running_var,
                                self.weight, self.bias, False, 0.0,
                                self.eps)
        if size() <= 1:
            return F.batch_norm(input, self.running_mean, self.running_var,
                                self.weight, self.bias, True,
                                self.momentum, self.eps)
        return _SyncBatchNormFn.apply(
            input, self.weight, self.bias, self.running_mean,
            self.running_var, self.eps, self.momentum)


class _SyncBatchNormFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, input, weight, bias, running_mean, running_var, eps,
                momentum):
        c = input.shape[1]
        reduce_dims = [0] + list(range(2, input.dim()))
        count = input.numel() // c

        local_mean = input.mean(dim=reduce_dims)
        local_sqmean = (input * input).mean(dim=reduce_dims)

        # Combine stats across ranks, weighting by per-rank element count
        # (supports uneven local batches, reference: sync_batch_norm.py
        # allgathers count tensors).
        packed = torch.cat([local_mean.float() * count,
                            local_sqmean.float() * count,
                            torch.tensor([float(count)])])
        gathered = allgather(packed.unsqueeze(0), name=f"syncbn.{c}")
        totals = gathered.sum(dim=0)
        total_count = totals[-1]
        mean = totals[:c] / total_count
        sqmean = totals[c:2 * c] / total_count
        var = sqmean - mean * mean
        invstd = torch.rsqrt(var + eps)

        if running_mean is not None:
            with torch.no_grad():
                unbiased = var * (total_count / (total_count - 1))
                running_mean.mul_(1 - momentum).add_(
                    mean.to(running_mean.dtype), alpha=momentum)
                running_var.mul_(1 - momentum).add_(
                    unbiased.to(running_var.dtype), alpha=momentum)

        shape = [1, c] + [1] * (input.dim() - 2)
        xhat = (input - mean.view(shape).to(input.dtype)) \
            * invstd.view(shape).to(input.dtype)
        out = xhat
        if weight is not None:
            out = out * weight.view(shape) + bias.view(shape)
        ctx.save_for_backward(xhat, weight, invstd, total_count)
        return out

    @staticmethod
    def backward(ctx, grad_output):
        xhat, weight, invstd, total_count = ctx.saved_tensors
        c = grad_output.shape[1]
        reduce_dims = [0] + list(range(2, grad_output.dim()))
        shape = [1, c] + [1] * (grad_output.dim() - 2)

        dxhat = grad_output
        if weight is not None:
            dxhat = grad_output * weight.view(shape)

        # Global reduction terms (reference allreduces sum_dy /
        # sum_dy_xmu, sync_batch_norm.py backward).
        sum_dxhat = dxhat.sum(dim=reduce_dims)
        sum_dxhat_xhat = (dxhat * xhat).sum(dim=reduce_dims)
        packed = torch.stack([sum_dxhat.float(), sum_dxhat_xhat.float()])
        packed = allreduce(packed, op=Sum, name=f"syncbn.bwd.{c}")
        sum_dxhat, sum_dxhat_xhat = packed[0], packed[1]

        n = total_count
        grad_input = (dxhat
                      - (sum_dxhat / n).view(shape).to(dxhat.dtype)
                      - xhat * (sum_dxhat_xhat / n).view(shape).to(
                          dxhat.dtype)) \
            * invstd.view(shape).to(dxhat.dtype)

        grad_weight = grad_bias = None
        if weight is not None:
            grad_weight = (grad_output * xhat).sum(dim=reduce_dims) \
                .to(weight.dtype)
            grad_bias = grad_output.sum(dim=reduce_dims).to(weight.dtype)
        return grad_input, grad_weight, grad_bias, None, None, None, None
