"""Parameter/state broadcast helpers (reference: horovod/torch/functions.py).

`broadcast_parameters` pushes rank 0's model weights to every rank before
training; `broadcast_optimizer_state` does the same for optimizer state
(tensors broadcast element-wise, non-tensor hyperparameters via pickled
object broadcast); `broadcast_object` ships any picklable object.
"""
from __future__ import annotations

import collections

import torch

from .. import broadcast_object  # core object bcast (pickle over wire)
from .mpi_ops import broadcast_, rank, synchronize, broadcast_async_


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast model parameters from root to all ranks. Accepts
    `model.state_dict()`, `model.named_parameters()`, or a list of
    (name, tensor) (reference: functions.py broadcast_parameters)."""
    if isinstance(params, dict):
        params = sorted(params.items())
    elif isinstance(params, collections.abc.Iterable):
        params = list(params)
        if params and not isinstance(params[0], tuple):
            raise ValueError("invalid params: expected (name, tensor) pairs")
    handles = []
    for name, p in params:
        if p is None or not isinstance(p, torch.Tensor):
            continue
        handles.append(broadcast_async_(p.data, root_rank,
                                        name=f"bcast_param.{name}"))
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """Broadcast rank 0's optimizer state
    (reference: functions.py broadcast_optimizer_state: scalars are
    wrapped as tensors; non-numeric state travels as pickled objects)."""
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")
    state_dict = optimizer.state_dict()

    # Non-tensor payload (param_groups + scalar state) and the root's
    # tensor-entry key list travel as one pickled object; tensor entries
    # then broadcast in the root's key order so every rank enqueues the
    # identical op sequence.
    meta = {
        "param_groups": state_dict["param_groups"],
        "scalars": {
            (sid, k): v
            for sid, s in state_dict["state"].items()
            for k, v in s.items() if not isinstance(v, torch.Tensor)},
        "tensor_keys": [
            (sid, k)
            for sid, s in sorted(state_dict["state"].items())
            for k, v in sorted(s.items()) if isinstance(v, torch.Tensor)],
    }
    meta = broadcast_object(meta, root_rank, name="opt_state.meta")

    if rank() != root_rank:
        # Materialize state on ranks whose optimizers are still empty by
        # stepping with zero gradients (same trick as the reference,
        # functions.py:120-150) — but only when the root has state.
        if meta["tensor_keys"] and not state_dict["state"]:
            for group in optimizer.param_groups:
                for p in group["params"]:
                    if p.requires_grad and p.grad is None:
                        p.grad = torch.zeros_like(p)
            optimizer.step()
            state_dict = optimizer.state_dict()
        state_dict["param_groups"] = meta["param_groups"]
        for (sid, k), v in meta["scalars"].items():
            state_dict["state"].setdefault(sid, {})[k] = v

    handles = []
    for sid, k in meta["tensor_keys"]:
        v = state_dict["state"].get(sid, {}).get(k)
        if not isinstance(v, torch.Tensor):
            raise ValueError(
                f"optimizer state [{sid}][{k}] is a tensor on the root "
                f"but {type(v).__name__} on rank {rank()}")
        handles.append(broadcast_async_(v, root_rank,
                                        name=f"opt_state.{sid}.{k}"))
    for h in handles:
        synchronize(h)
    optimizer.load_state_dict(state_dict)
