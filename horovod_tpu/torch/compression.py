"""Gradient compression algorithms for the torch binding.

Same contract as the reference (reference: horovod/torch/compression.py):
`Compression.fp16.compress(tensor) -> (compressed, ctx)` casts floating
tensors to fp16 before the wire, `decompress` casts back. The reduction
itself then runs in the wire dtype, halving allreduce bytes.
"""
from __future__ import annotations

import torch


class Compressor:
    @staticmethod
    def compress(tensor: torch.Tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Pass-through (reference: compression.py NoneCompressor)."""

    @staticmethod
    def compress(tensor: torch.Tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast floating tensors to fp16 for the wire
    (reference: compression.py:46-63)."""

    @staticmethod
    def compress(tensor: torch.Tensor):
        if tensor.dtype.is_floating_point:
            return tensor.type(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        if ctx is not None:
            return tensor.type(ctx)
        return tensor


class BF16Compressor(Compressor):
    """TPU-native wire dtype: bfloat16 keeps fp32's exponent range, so no
    loss-scale plumbing is needed (no reference analogue — the reference
    only ships fp16)."""

    @staticmethod
    def compress(tensor: torch.Tensor):
        if tensor.dtype.is_floating_point:
            return tensor.type(torch.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        if ctx is not None:
            return tensor.type(ctx)
        return tensor


class Compression:
    """Optional gradient compression algorithm used during allreduce."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
