"""Gradient compression algorithms for the torch binding.

Same contract as the reference (reference: horovod/torch/compression.py):
`Compression.fp16.compress(tensor) -> (compressed, ctx)` casts floating
tensors to fp16 before the wire, `decompress` casts back. The reduction
itself then runs in the wire dtype, halving allreduce bytes.
"""
from __future__ import annotations

import torch


class Compressor:
    @staticmethod
    def compress(tensor: torch.Tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Pass-through (reference: compression.py NoneCompressor)."""

    @staticmethod
    def compress(tensor: torch.Tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast floating tensors to fp16 for the wire
    (reference: compression.py:46-63)."""

    @staticmethod
    def compress(tensor: torch.Tensor):
        if tensor.dtype.is_floating_point:
            return tensor.type(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        if ctx is not None:
            return tensor.type(ctx)
        return tensor


class BF16Compressor(Compressor):
    """TPU-native wire dtype: bfloat16 keeps fp32's exponent range, so no
    loss-scale plumbing is needed (no reference analogue — the reference
    only ships fp16)."""

    @staticmethod
    def compress(tensor: torch.Tensor):
        if tensor.dtype.is_floating_point:
            return tensor.type(torch.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        if ctx is not None:
            return tensor.type(ctx)
        return tensor


class Int8Compressor(Compressor):
    """Block-wise int8 wire quantization (compress/ subsystem, EQuARX
    shape).  The tensor passes through UNCHANGED here — the runtime's
    data planes quantize per fusion bucket (per-block scale+zero-point,
    fp32 accumulation at the reduce) so the quantized payload is what
    actually crosses the network/shm, ~4x fewer wire bytes than fp32.
    Not composable with op=Adasum (the controller rejects it with a
    structured error).  Block size: HOROVOD_COMPRESSION_BLOCK_SIZE."""

    wire_codec = "int8"

    @staticmethod
    def compress(tensor: torch.Tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        return tensor


class Uint4Compressor(Int8Compressor):
    """4-bit variant: ~8x fewer wire bytes, wider error bound."""

    wire_codec = "uint4"


class Compression:
    """Optional gradient compression algorithm used during allreduce."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    uint4 = Uint4Compressor

    @staticmethod
    def resolve(spec):
        """Accept a Compressor class or a codec name string
        ("none"/"fp16"/"bf16"/"int8"/"uint4")."""
        if spec is None:
            return Compression.none
        if isinstance(spec, str):
            try:
                return getattr(Compression, spec.strip().lower())
            except AttributeError:
                raise ValueError(
                    f"Unknown compression {spec!r}; expected one of "
                    "none/fp16/bf16/int8/uint4") from None
        return spec
