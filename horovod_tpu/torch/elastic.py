"""Elastic state for PyTorch models/optimizers.

Reference: horovod/torch/elastic/state.py — ``TorchState`` composes
per-object handlers (module state_dict, optimizer state_dict, plain values)
over the generic commit/restore/sync machinery; sync broadcasts the
committed state from rank 0 using ``broadcast_object``.
"""
from __future__ import annotations

import copy
import io
from typing import Any

from ..elastic.sampler import ElasticSampler  # noqa: F401 (re-export)
from ..elastic.state import State


class StateHandler:
    """Save/restore/sync one value of a known type."""

    def __init__(self, value: Any) -> None:
        self.value = value

    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def set_value(self, value: Any) -> None:
        self.value = value
        self.save()


class ModelStateHandler(StateHandler):
    def __init__(self, model) -> None:
        super().__init__(model)
        self._saved_state = copy.deepcopy(self.value.state_dict())

    def save(self) -> None:
        self._saved_state = copy.deepcopy(self.value.state_dict())

    def restore(self) -> None:
        self.value.load_state_dict(self._saved_state)

    def sync(self) -> None:
        from .functions import broadcast_parameters
        broadcast_parameters(self.value.state_dict(), root_rank=0)
        self.save()


class OptimizerStateHandler(StateHandler):
    def __init__(self, optimizer) -> None:
        super().__init__(optimizer)
        self._saved_state = copy.deepcopy(self.value.state_dict())

    def save(self) -> None:
        self._saved_state = copy.deepcopy(self.value.state_dict())

    def restore(self) -> None:
        self.value.load_state_dict(self._saved_state)

    def sync(self) -> None:
        from .functions import broadcast_optimizer_state
        broadcast_optimizer_state(self.value, root_rank=0)
        self.save()


class SamplerStateHandler(StateHandler):
    def __init__(self, sampler: ElasticSampler) -> None:
        super().__init__(sampler)
        self._saved_state = self.value.state_dict()

    def save(self) -> None:
        self._saved_state = self.value.state_dict()

    def restore(self) -> None:
        self.value.load_state_dict(self._saved_state)

    def sync(self) -> None:
        from .. import broadcast_object
        # Merge processed indices across the old world so the re-shard skips
        # everything anyone already consumed, then share from rank 0.
        from .. import allgather_object
        all_states = allgather_object(self.value.state_dict(),
                                      name="__elastic_sampler_state__")
        merged: set[int] = set()
        for st in all_states:
            merged.update(st["processed_indices"])
        synced = broadcast_object(
            {"epoch": max(st["epoch"] for st in all_states),
             "processed_indices": sorted(merged)},
            root_rank=0, name="__elastic_sampler_sync__")
        self.value.load_state_dict(synced)
        self.save()


def _get_handler(value: Any) -> StateHandler | None:
    try:
        import torch
        if isinstance(value, torch.nn.Module):
            return ModelStateHandler(value)
        if isinstance(value, torch.optim.Optimizer):
            return OptimizerStateHandler(value)
    except ImportError:
        pass
    if isinstance(value, ElasticSampler):
        return SamplerStateHandler(value)
    return None


class TorchState(State):
    """Elastic state wrapping torch modules, optimizers, samplers, and
    plain picklable attributes (reference: torch/elastic/state.py)."""

    def __init__(self, model=None, optimizer=None, **kwargs: Any) -> None:
        kwargs = dict(kwargs)
        if model is not None:
            kwargs["model"] = model
        if optimizer is not None:
            kwargs["optimizer"] = optimizer

        self._handlers: dict[str, StateHandler] = {}
        self._plain: dict[str, Any] = {}
        for name, value in kwargs.items():
            handler = _get_handler(value)
            if handler is not None:
                self._handlers[name] = handler
            else:
                self._plain[name] = copy.deepcopy(value)
            object.__setattr__(self, name, value)
        super().__init__()

    def __setattr__(self, name: str, value: Any) -> None:
        handler = getattr(self, "_handlers", {}).get(name)
        if handler is not None:
            handler.set_value(value)
        elif name in getattr(self, "_plain", {}):
            self._plain[name] = copy.deepcopy(value)
        object.__setattr__(self, name, value)

    def save(self) -> None:
        for handler in self._handlers.values():
            handler.save()
        for name in self._plain:
            self._plain[name] = copy.deepcopy(getattr(self, name))

    def restore(self) -> None:
        for handler in self._handlers.values():
            handler.restore()
        for name, value in self._plain.items():
            object.__setattr__(self, name, copy.deepcopy(value))

    def sync(self) -> None:
        for handler in self._handlers.values():
            handler.sync()
        if self._plain:
            from .. import broadcast_object
            synced = broadcast_object(self._plain, root_rank=0,
                                      name="__elastic_torch_plain__")
            self._plain = synced
            for name, value in synced.items():
                object.__setattr__(self, name, copy.deepcopy(value))


def save_to_bytes(obj) -> bytes:
    """Serialize a torch object to bytes (checkpoint transport helper)."""
    import torch
    buf = io.BytesIO()
    torch.save(obj, buf)
    return buf.getvalue()
