"""Torch collective ops: the reference torch/mpi_ops.py surface over the
horovod_tpu core.

The reference binds these through pybind11 into the C++ enqueue API
(reference: horovod/torch/mpi_ops_v2.cc:64-686, torch/mpi_ops.py:95-900);
here CPU torch tensors stage zero-copy into the core via the buffer
protocol, and completion flows back through Handle futures. In-place
variants copy the reduced result back into the caller's tensor at
synchronize time (the reference's callback does the same divide+copy,
mpi_ops_v2.cc:81-87).
"""
from __future__ import annotations

from typing import Sequence

import numpy as np
import torch

from .. import (Adasum, Average, Sum, barrier, join)  # noqa: F401
from .. import (allgather_async as _allgather_async,
                allreduce_async as _allreduce_async,
                alltoall_async as _alltoall_async,
                broadcast_async as _broadcast_async,
                grouped_allreduce_async as _grouped_allreduce_async,
                reducescatter_async as _reducescatter_async)
from ..core import (Handle, init, is_initialized, shutdown, rank, size,
                    local_rank, local_size, cross_rank, cross_size)


def _check_cpu(tensor: torch.Tensor):
    if tensor.device.type != "cpu":
        raise ValueError(
            "horovod_tpu.torch stages through host memory; move the "
            "tensor to CPU (TPU-resident training should use the JAX "
            "path, horovod_tpu.training.Trainer).")
    tensor = tensor.detach().contiguous()
    if tensor.dtype == torch.bfloat16:
        # torch cannot export bf16 through the buffer protocol; the
        # int16 view shares memory, and the ml_dtypes view re-types it
        # for the core (which already treats bf16 wires fp32-accumulated)
        # — still zero-copy.
        import ml_dtypes
        return tensor.view(torch.int16).numpy().view(ml_dtypes.bfloat16)
    return tensor


def _from_np(out: np.ndarray) -> torch.Tensor:
    """np array (possibly ml_dtypes.bfloat16) -> torch tensor."""
    out = np.ascontiguousarray(out)
    if out.dtype.name == "bfloat16":
        return torch.from_numpy(out.view(np.int16)).view(torch.bfloat16)
    return torch.from_numpy(out)


def _copy_out(target: torch.Tensor, out: np.ndarray) -> torch.Tensor:
    src = _from_np(out)
    with torch.no_grad():
        if target.shape != src.shape:
            target.resize_(src.shape)
        target.copy_(src.to(target.dtype))
    return target


# -- allreduce ---------------------------------------------------------------
def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0,
                    compression=None) -> Handle:
    return _allreduce_async(_check_cpu(tensor), average, name, op,
                            prescale_factor, postscale_factor,
                            compression)


def allreduce(tensor, average=None, name=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0,
              compression=None) -> torch.Tensor:
    handle = allreduce_async(tensor, average, name, op, prescale_factor,
                             postscale_factor, compression)
    return synchronize(handle)


def allreduce_async_(tensor, average=None, name=None, op=None,
                     prescale_factor=1.0, postscale_factor=1.0) -> Handle:
    handle = _allreduce_async(_check_cpu(tensor), average, name, op,
                              prescale_factor, postscale_factor)
    handle.inplace_targets = [tensor]
    return handle


def allreduce_(tensor, average=None, name=None, op=None,
               prescale_factor=1.0, postscale_factor=1.0) -> torch.Tensor:
    return synchronize(allreduce_async_(tensor, average, name, op,
                                        prescale_factor, postscale_factor))


def grouped_allreduce_async(tensors: Sequence[torch.Tensor], average=None,
                            name=None, op=None, prescale_factor=1.0,
                            postscale_factor=1.0,
                            compression=None) -> Handle:
    return _grouped_allreduce_async([_check_cpu(t) for t in tensors],
                                    average, name, op, prescale_factor,
                                    postscale_factor, compression)


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      prescale_factor=1.0, postscale_factor=1.0):
    return synchronize(grouped_allreduce_async(
        tensors, average, name, op, prescale_factor, postscale_factor))


def grouped_allreduce_async_(tensors, average=None, name=None, op=None,
                             prescale_factor=1.0,
                             postscale_factor=1.0) -> Handle:
    handle = _grouped_allreduce_async([_check_cpu(t) for t in tensors],
                                      average, name, op, prescale_factor,
                                      postscale_factor)
    handle.inplace_targets = list(tensors)
    return handle


def grouped_allreduce_(tensors, average=None, name=None, op=None,
                       prescale_factor=1.0, postscale_factor=1.0):
    return synchronize(grouped_allreduce_async_(
        tensors, average, name, op, prescale_factor, postscale_factor))


# -- allgather / broadcast / alltoall ---------------------------------------
def allgather_async(tensor, name=None) -> Handle:
    return _allgather_async(_check_cpu(tensor), name)


def allgather(tensor, name=None) -> torch.Tensor:
    return synchronize(allgather_async(tensor, name))


def reducescatter_async(tensor, name=None, op=None,
                        prescale_factor=1.0, postscale_factor=1.0) -> Handle:
    """Reduce across ranks, return this rank's dim-0 slice (op=None
    averages, upstream reducescatter semantics)."""
    return _reducescatter_async(_check_cpu(tensor), name, op,
                                prescale_factor, postscale_factor)


def reducescatter(tensor, name=None, op=None, prescale_factor=1.0,
                  postscale_factor=1.0) -> torch.Tensor:
    return synchronize(reducescatter_async(tensor, name, op,
                                           prescale_factor,
                                           postscale_factor))


def broadcast_async(tensor, root_rank, name=None) -> Handle:
    return _broadcast_async(_check_cpu(tensor), root_rank, name)


def broadcast(tensor, root_rank, name=None) -> torch.Tensor:
    return synchronize(broadcast_async(tensor, root_rank, name))


def broadcast_async_(tensor, root_rank, name=None) -> Handle:
    handle = _broadcast_async(_check_cpu(tensor), root_rank, name)
    handle.inplace_targets = [tensor]
    return handle


def broadcast_(tensor, root_rank, name=None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name))


def alltoall_async(tensor, splits=None, name=None) -> Handle:
    if splits is not None and isinstance(splits, torch.Tensor):
        splits = splits.numpy()
    handle = _alltoall_async(_check_cpu(tensor), splits, name)
    handle.wants_recv_splits = splits is not None
    return handle


def alltoall(tensor, splits=None, name=None):
    return synchronize(alltoall_async(tensor, splits, name))


# -- completion --------------------------------------------------------------
def synchronize(handle: Handle):
    """Wait for an async op; in-place variants copy back into the original
    tensors (reference: torch/mpi_ops.py:862-884 synchronize)."""
    status = handle.wait()
    status.raise_if_error()
    targets = getattr(handle, "inplace_targets", None)
    if targets:
        outs = [_copy_out(t, e.output)
                for t, e in zip(targets, handle.entries)]
        return outs[0] if len(outs) == 1 else outs
    outs = [_from_np(e.output) for e in handle.entries]
    if getattr(handle, "wants_recv_splits", False):
        recv = torch.from_numpy(np.asarray(handle.entries[0].received_splits,
                                           dtype=np.int32))
        return outs[0], recv
    return outs[0] if len(outs) == 1 else outs


def poll(handle: Handle) -> bool:
    return handle.done()


# -- sparse gradients --------------------------------------------------------
def sparse_allreduce_async(tensor, name=None, op=None):
    """Gather-based sparse reduction (reference: torch/mpi_ops.py:512
    sparse_allreduce_async): allgather every rank's (indices, values), sum
    duplicates via sparse coalescing.  Returns a callable handle; resolve
    with `synchronize`-style `handle()`."""
    from .. import allgather as _allgather_np, size as _size

    t = tensor.coalesce() if tensor.is_sparse else tensor.to_sparse()
    t = t.coalesce()
    indices = t.indices().numpy()
    values = t.values().numpy()
    base = name or f"sparse.{id(tensor)}"

    # Variable-first-dim allgather: transpose indices to [nnz, ndim].
    all_idx = _allgather_np(np.ascontiguousarray(indices.T),
                            name=f"{base}.idx")
    all_val = _allgather_np(np.ascontiguousarray(values),
                            name=f"{base}.val")

    def _resolve():
        idx = torch.from_numpy(np.ascontiguousarray(np.asarray(all_idx).T))
        val = torch.from_numpy(np.ascontiguousarray(np.asarray(all_val)))
        out = torch.sparse_coo_tensor(idx, val, size=t.shape).coalesce()
        from .. import Average
        if op is None or op is Average:
            out = out / _size()
        return out

    return _resolve


def sparse_allreduce(tensor, name=None, op=None):
    return sparse_allreduce_async(tensor, name=name, op=op)()
