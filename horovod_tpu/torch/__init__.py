"""horovod_tpu.torch — the PyTorch binding.

Drop-in surface of the reference's horovod.torch module
(reference: horovod/torch/__init__.py): `hvd.init()`, collectives with
sync/async/in-place variants, `DistributedOptimizer`, `Compression`,
parameter/optimizer-state broadcast. Torch tensors stage through host
memory into the TPU-native core.
"""
from .. import (Adasum, Average, Sum, barrier, broadcast_object, join,
                HorovodInternalError, HostsUpdatedInterrupt)
from ..core import (init, is_initialized, shutdown, rank, size, local_rank,
                    local_size, cross_rank, cross_size, is_homogeneous,
                    start_timeline, stop_timeline)
from .compression import Compression
from .functions import broadcast_optimizer_state, broadcast_parameters
from .mpi_ops import (allgather, allgather_async, allreduce, allreduce_,
                      allreduce_async, allreduce_async_, alltoall,
                      alltoall_async, broadcast, broadcast_,
                      broadcast_async, broadcast_async_, grouped_allreduce,
                      grouped_allreduce_, grouped_allreduce_async,
                      grouped_allreduce_async_, poll, reducescatter,
                      reducescatter_async, sparse_allreduce,
                      sparse_allreduce_async, synchronize)
from .optimizer import DistributedOptimizer
from .sync_batch_norm import SyncBatchNorm

__all__ = [
    "Adasum", "Average", "Sum", "Compression", "DistributedOptimizer",
    "SyncBatchNorm", "allgather", "allgather_async", "allreduce",
    "allreduce_", "allreduce_async", "allreduce_async_", "alltoall",
    "alltoall_async", "barrier", "broadcast", "broadcast_",
    "broadcast_async", "broadcast_async_", "broadcast_object",
    "broadcast_optimizer_state", "broadcast_parameters", "cross_rank",
    "cross_size", "grouped_allreduce", "grouped_allreduce_",
    "grouped_allreduce_async", "grouped_allreduce_async_", "init",
    "is_homogeneous", "is_initialized", "join", "local_rank", "local_size",
    "poll", "rank", "reducescatter", "reducescatter_async", "shutdown",
    "size", "start_timeline", "stop_timeline",
    "synchronize", "HorovodInternalError", "HostsUpdatedInterrupt",
]
